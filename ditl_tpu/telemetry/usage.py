"""Per-tenant usage metering & cost attribution (ISSUE 15 tentpole).

The gateway has known WHO a request belongs to since ISSUE 4
(``gateway/admission.tenant_label`` — a stable sha digest, never the raw
bearer), and the engine has computed per-request cost since ISSUES 6-13
(prompt vs generated tokens, reused-vs-prefilled splits per cache tier,
queue wait, interference absorbed, preemptions) — but the two never met:
"millions of users" meant millions of indistinguishable requests. This
module is the meeting point, three pieces:

- :class:`UsageLedger` — the crash-consistent on-disk artifact: ONE JSONL
  row per terminal request (outcome 200/429/504/cancel), written once at
  end exactly like spans, riding ``telemetry/journal.py``'s line-buffered
  append + segment rotation. A SIGKILL loses at most the row mid-write;
  the aggregator skips the torn tail (the ``load_trace`` rule).
- :class:`UsageMeter` — the in-memory half: bounded per-tenant rollups
  (the ``/usage`` endpoints' payload), bounded per-tenant metric families
  (``ditl_usage_tenant_<t>_*`` — tokens in/out, cached-tokens-saved,
  device-seconds; tenants beyond ``max_tenant_families`` fold into
  ``other``, the GatewayMetrics rule), and the WINDOWED per-tenant
  prefill-token / device-time accounting the noisy-neighbor conviction
  reads (telemetry/anomaly.py) — fed live from the scheduler (a mid-storm
  batch job must be convictable before it terminates).
- the aggregator — ``load_usage``/``rollup`` + the CLI
  (``python -m ditl_tpu.telemetry.usage --dir D``): ledger files -> one
  deterministic per-tenant rollup (byte-identical across runs over the
  same directory, pinned by test).

Tenant identity discipline: every identifier entering this module is
expected to ALREADY be a credential-safe label (the admission digest or a
configured public name); :func:`sanitize_label` is applied again on every
path anyway — defense in depth, and the static half lives in the
``tenant-label-discipline`` analysis rule (ISSUE 15 satellite). jax-free
and zero-device-sync like everything in telemetry/: every number is a host
float the scheduler already held.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import threading

from ditl_tpu.telemetry.journal import EventJournal, read_journal

__all__ = [
    "LEDGER_EVENT",
    "OUTCOMES",
    "USAGE_SCHEMA",
    "UsageLedger",
    "UsageMeter",
    "convict_noisy_neighbor",
    "load_usage",
    "main",
    "merge_rollups",
    "read_ledger",
    "rollup",
    "sanitize_label",
    "tenant_label",
    "usage_ledger_path",
]

PREFIX = "ditl_usage"
USAGE_SCHEMA = 1
# The journal event name every ledger row carries; readers filter on it so
# a usage file that shares a directory with span journals stays parseable.
LEDGER_EVENT = "usage.request"
# Terminal outcomes a row may carry. Fixed vocabulary on purpose: outcome
# counters become metric families, and families must be bounded. Engine
# rows use 200/429/504/cancel; gateway-edge rows additionally use 503
# (no live replica); "adapter" rows are the adapter plane's owner-billing
# flushes (infer/adapters.py — HBM residency + gather attribution, no
# request behind them) — anything else folds into "other".
OUTCOMES = ("200", "429", "503", "504", "cancel", "adapter")

# Numeric row fields the rollup sums per tenant (absent fields count 0, so
# gateway-side rows — which carry only estimates — aggregate next to
# engine rows without special casing).
_SUM_FIELDS = (
    "prompt_tokens",
    "generated_tokens",
    "cache_hit_tokens",
    "cache_hit_host_tokens",
    "cache_hit_handoff_tokens",
    "prefilled_tokens",
    "queue_wait_s",
    "device_time_est_s",
    "interference_absorbed_s",
    "preemptions",
    "resume_prefill_tokens",
    # Adapter-plane owner billing (outcome="adapter" flush rows,
    # infer/adapters.py): estimated gather device-seconds + HBM pool-row
    # residency-seconds + the request count behind the gather estimate.
    "adapter_gather_est_s",
    "adapter_residency_s",
    "adapter_requests",
)


def sanitize_label(s: str) -> str:
    """Metric-name-safe tenant label — a deliberate copy of
    ``gateway/admission.sanitize_label`` (telemetry/ must not import the
    gateway package: its ``__init__`` pulls the whole gateway in, and the
    dependency already points the other way). Pinned equal by test, the
    SLO_CLASS_NAMES mirror rule."""
    out = re.sub(r"[^A-Za-z0-9_]", "_", s or "")[:48]
    return out or "anonymous"


def tenant_label(tenant: str, known=()) -> str:
    """Credential-safe tenant identifier — the same deliberate mirror of
    ``gateway/admission.tenant_label`` as :func:`sanitize_label` above
    (pinned equal by test): configured public names in ``known`` and the
    ``anonymous`` tenant stay readable, every other value (usually a raw
    bearer) reduces to the stable ``t_<sha256[:12]>`` digest. Lets
    infer/server.py digest a direct client's bearer without growing an
    infer -> gateway import edge."""
    if tenant == "anonymous" or tenant in known:
        return sanitize_label(tenant)
    digest = hashlib.sha256(
        tenant.encode("utf-8", "surrogatepass")
    ).hexdigest()[:12]
    return f"t_{digest}"


def usage_ledger_path(directory: str, source: str) -> str:
    """``usage-<source>.jsonl`` — deliberately OUTSIDE the ``events-*``
    glob ``merge_journals`` consumes, so billing rows never interleave
    into pod timelines or incident journal tails; rotation segments
    (``usage-x.rNNNN.jsonl``) still match :func:`load_usage`'s glob."""
    return os.path.join(directory, f"usage-{sanitize_label(source)}.jsonl")


class UsageLedger:
    """Crash-consistent per-request usage ledger for ONE process: an
    :class:`EventJournal` under the hood (lock-serialized line-buffered
    appends, max-bytes segment rotation), one :data:`LEDGER_EVENT` row per
    terminal request."""

    def __init__(self, path: str, source: str = "",
                 max_bytes: int | None = None):
        self.journal = EventJournal(path, source=source or "usage",
                                    max_bytes=max_bytes)
        self.rows = 0

    @property
    def path(self) -> str:
        return self.journal.path

    def record(self, **row) -> None:
        row.setdefault("schema", USAGE_SCHEMA)
        self.journal.event(LEDGER_EVENT, **row)
        self.rows += 1

    def close(self) -> None:
        self.journal.close()


class UsageMeter:
    """In-memory per-tenant accounting for one engine (or gateway).

    Three consumers, one object:

    - ``snapshot()`` — the ``/usage`` endpoint's per-tenant rollups.
    - the registry families — ``ditl_usage_tenant_<t>_{prompt_tokens,
      generated_tokens,cached_tokens_saved,device_seconds}`` plus the
      aggregate ``ditl_usage_requests[_<outcome>]`` counters, created
      lazily against the registry :meth:`bind` attached (the engine binds
      its own ServingMetrics registry so /metrics renders them).
    - ``advance_window()`` — per-tenant prefill-token / device-second
      DELTAS since the last call, the detector-cadence input
      :func:`convict_noisy_neighbor` judges.

    Bounded by construction: tenants beyond ``max_tenant_families`` fold
    into the ``other`` label everywhere (families, rollups, windows) — a
    client cycling random bearer tokens grows nothing without bound.
    Thread-safe: terminal notes arrive from the engine driver AND from
    HTTP handler threads (submit-time 429s)."""

    def __init__(self, registry=None, max_tenant_families: int = 32):
        self.registry = registry
        self.max_tenant_families = max(1, int(max_tenant_families))
        self._lock = threading.Lock()
        self._labels: set[str] = set()  # guarded-by: _lock
        self._rollups: dict[str, dict] = {}  # guarded-by: _lock
        self._window: dict[str, list] = {}  # guarded-by: _lock
        # Lifetime live accounting [prefill_tokens, device_s] fed at
        # DISPATCH time (not terminal) — a tenant whose storm is still in
        # flight must already have a snapshot entry when a conviction
        # needs its bill (meter-only: offline ledger rollups carry the
        # terminal fields instead).
        self._live: dict[str, list] = {}  # guarded-by: _lock
        self.total_requests = 0

    def bind(self, registry) -> None:
        """Attach the registry the per-tenant families render into
        (idempotent; the engine calls this at construction so the meter
        shares the bundle /metrics already renders)."""
        if self.registry is None:
            self.registry = registry

    # -- label bounding ----------------------------------------------------

    def _label_locked(self, tenant) -> str:
        """Sanitized-and-bounded label (caller holds ``_lock``)."""
        label = sanitize_label(str(tenant or "anonymous"))
        if label in self._labels:
            return label
        if len(self._labels) >= self.max_tenant_families:
            return "other"
        self._labels.add(label)
        return label

    def _tenant_counter(self, label: str, kind: str, help_: str):
        return self.registry.counter(
            f"{PREFIX}_tenant_{label}_{kind}",
            f"{help_} attributed to tenant {label}")

    # -- live feeds (engine driver thread) ---------------------------------

    def note_prefill(self, tenant, tokens: int) -> None:
        """One prefill dispatch's token count — fed from the scheduler at
        dispatch time (NOT at terminal) so a mid-flight batch storm is
        visible in the conviction window while it is happening."""
        if tokens <= 0:
            return
        with self._lock:
            label = self._label_locked(tenant)
            w = self._window.setdefault(label, [0, 0.0])
            w[0] += int(tokens)
            self._live.setdefault(label, [0, 0.0])[0] += int(tokens)

    def note_device(self, tenant, seconds: float) -> None:
        """One request's share of a tick's device-time estimate (host wall
        attribution, infer/continuous.py) — same live-window rationale as
        :meth:`note_prefill`."""
        if seconds <= 0:
            return
        with self._lock:
            label = self._label_locked(tenant)
            w = self._window.setdefault(label, [0, 0.0])
            w[1] += float(seconds)
            self._live.setdefault(label, [0, 0.0])[1] += float(seconds)

    # -- terminal rows -----------------------------------------------------

    def note_terminal(self, row: dict) -> None:
        """Fold one terminal ledger row into the rollups + families. The
        row is the same dict the ledger records — one spelling of the
        accounting, two sinks."""
        outcome = str(row.get("outcome", "200"))
        if outcome not in OUTCOMES:
            outcome = "other"
        with self._lock:
            label = self._label_locked(row.get("tenant"))
            r = self._rollups.setdefault(label, {
                "requests": 0,
                "by_outcome": {},
                **{k: 0 for k in _SUM_FIELDS},
            })
            r["requests"] += 1
            r["by_outcome"][outcome] = r["by_outcome"].get(outcome, 0) + 1
            for k in _SUM_FIELDS:
                v = row.get(k)
                if isinstance(v, (int, float)) and v == v:
                    r[k] = round(r[k] + v, 6) if isinstance(v, float) \
                        else r[k] + v
            self.total_requests += 1
        if self.registry is not None:
            self.registry.counter(
                f"{PREFIX}_requests", "terminal requests metered").inc()
            self.registry.counter(
                f"{PREFIX}_requests_{sanitize_label(outcome)}",
                f"terminal requests metered with outcome {outcome}").inc()
            self._tenant_counter(
                label, "prompt_tokens", "prompt tokens").inc(
                float(row.get("prompt_tokens") or 0))
            self._tenant_counter(
                label, "generated_tokens", "generated tokens").inc(
                float(row.get("generated_tokens") or 0))
            self._tenant_counter(
                label, "cached_tokens_saved",
                "prompt tokens served from cached KV (all tiers)").inc(
                float(row.get("cache_hit_tokens") or 0))
            self._tenant_counter(
                label, "device_seconds",
                "estimated device-seconds (prefill wall + decode-tick "
                "share)").inc(
                max(0.0, float(row.get("device_time_est_s") or 0.0)))

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Deterministic per-tenant rollups (sorted keys, rounded
        floats) — the ``/usage`` endpoint body. Tenants with ONLY
        in-flight work so far still appear (terminal fields zero), with
        ``live_prefill_tokens``/``live_device_s`` carrying the
        dispatch-time accounting — the convictable-before-terminal
        contract."""
        with self._lock:
            out: dict[str, dict] = {}
            for label in sorted(set(self._rollups) | set(self._live)):
                r = self._rollups.get(label) or {
                    "requests": 0, "by_outcome": {},
                    **{k: 0 for k in _SUM_FIELDS},
                }
                entry = {
                    **{k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in r.items() if k != "by_outcome"},
                    "by_outcome": dict(sorted(r["by_outcome"].items())),
                }
                live = self._live.get(label)
                if live is not None:
                    entry["live_prefill_tokens"] = int(live[0])
                    entry["live_device_s"] = round(live[1], 6)
                out[label] = entry
            return out

    def advance_window(self) -> dict:
        """Per-tenant prefill-token / device-second deltas since the last
        call, then reset — the detector-cadence counterpart of
        ``ServingDetector``'s counter-delta windows."""
        with self._lock:
            window, self._window = self._window, {}
        tenants = {
            label: {"prefill_tokens": int(p), "device_s": round(d, 6)}
            for label, (p, d) in sorted(window.items())
        }
        return {
            "tenants": tenants,
            "prefill_tokens_total": sum(
                t["prefill_tokens"] for t in tenants.values()),
            "device_s_total": round(sum(
                t["device_s"] for t in tenants.values()), 6),
        }


def convict_noisy_neighbor(window: dict, share_threshold: float,
                           min_tokens: int,
                           snapshot: dict | None = None) -> dict | None:
    """Judge one :meth:`UsageMeter.advance_window` result: the tenant with
    the dominant prefill-token share is convicted when its share clears
    ``share_threshold`` AND the window moved at least ``min_tokens``
    prompt tokens (thin windows convict nobody — a single small prefill is
    not a storm). The verdict carries both the prefill-token and the
    device-time share plus the tenant's lifetime usage ``snapshot`` row, so
    the incident manifest names the culprit WITH its bill attached."""
    tenants = window.get("tenants") or {}
    total_p = window.get("prefill_tokens_total") or 0
    if not tenants or total_p < max(1, min_tokens):
        return None
    label, top = max(tenants.items(),
                     key=lambda kv: kv[1]["prefill_tokens"])
    share = top["prefill_tokens"] / total_p
    if share < share_threshold:
        return None
    total_d = window.get("device_s_total") or 0.0
    verdict = {
        "tenant": label,
        "window_prefill_tokens": top["prefill_tokens"],
        "window_prefill_share": round(share, 4),
        "window_device_s": top["device_s"],
        "window_device_share": (
            round(top["device_s"] / total_d, 4) if total_d > 0 else None
        ),
        "window_total_prefill_tokens": total_p,
    }
    if snapshot is not None:
        verdict["usage"] = snapshot.get(label, {})
    return verdict


# ---------------------------------------------------------------------------
# Aggregator (ledger files -> rollups) + CLI
# ---------------------------------------------------------------------------


def read_ledger(path: str) -> list[dict]:
    """One ledger file's usage rows. Torn/corrupt lines — the tail a
    SIGKILL mid-write leaves — are skipped, never fatal (the journal
    reader's rule); non-usage events in a shared file are filtered out."""
    return [rec for rec in read_journal(path)
            if rec.get("event") == LEDGER_EVENT]


def load_usage(directory: str) -> list[dict]:
    """Every ``usage-*.jsonl`` row under ``directory``, RECURSIVELY
    (rotated segments match the same glob): the gateway launcher writes
    its edge ledger at the top of ``usage.ledger_dir`` and gives each
    replica its own subdirectory, and one ``--dir`` over the root must
    see the whole fleet. Deterministic order (path, then file order) so
    two aggregator runs over the same directory produce byte-identical
    rollups. Note rows keep their journal ``source`` — a request served
    through the gateway appears TWICE (one engine row with the real
    token/device accounting, one gateway edge row with estimates); see
    the CLI's ``--source`` filter and docs/troubleshooting.md §33."""
    rows: list[dict] = []
    pattern = os.path.join(directory, "**", "usage-*.jsonl")
    for path in sorted(glob.glob(pattern, recursive=True)):
        rows.extend(read_ledger(path))
    return rows


def rollup(rows: list[dict]) -> dict:
    """Per-tenant aggregation of ledger rows — the same shape
    :meth:`UsageMeter.snapshot` serves live, rebuilt from disk. Purely a
    fold over the input order-insensitively (sums and counts), so the
    result depends only on the row SET: byte-identical across runs."""
    meter = UsageMeter(registry=None, max_tenant_families=2 ** 30)
    for row in rows:
        meter.note_terminal(row)
    return meter.snapshot()


def merge_rollups(parts: list[dict]) -> dict:
    """Sum a list of per-tenant rollups (the gateway's /usage fan-out:
    one part per replica) into one fleet rollup. Numeric leaves add;
    ``by_outcome`` maps add key-wise."""
    out: dict[str, dict] = {}
    for part in parts:
        if not isinstance(part, dict):
            continue
        for tenant, r in part.items():
            if not isinstance(r, dict):
                continue
            dst = out.setdefault(tenant, {"requests": 0, "by_outcome": {},
                                          **{k: 0 for k in _SUM_FIELDS}})
            for k, v in r.items():
                if k == "by_outcome" and isinstance(v, dict):
                    for o, n in v.items():
                        if isinstance(n, (int, float)):
                            dst["by_outcome"][o] = (
                                dst["by_outcome"].get(o, 0) + n
                            )
                elif isinstance(v, (int, float)) and not isinstance(v, bool):
                    dst[k] = round(dst.get(k, 0) + v, 6) \
                        if isinstance(v, float) or isinstance(
                            dst.get(k, 0), float) else dst.get(k, 0) + v
    return {
        t: {**{k: v for k, v in sorted(r.items()) if k != "by_outcome"},
            "by_outcome": dict(sorted(r["by_outcome"].items()))}
        for t, r in sorted(out.items())
    }


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ditl_tpu.telemetry.usage",
        description="aggregate per-tenant usage ledgers (ISSUE 15)",
    )
    parser.add_argument("--dir", required=True,
                        help="directory holding usage-*.jsonl ledger files")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable rollup (sorted keys — "
                        "byte-identical across runs over the same ledger)")
    parser.add_argument("--tenant", default="",
                        help="restrict the output to one tenant label")
    parser.add_argument("--source", default="",
                        help="restrict to ledger rows whose journal "
                        "source contains this substring (e.g. 'server' "
                        "for engine rows, 'gateway' for edge rows) — a "
                        "gateway-relayed request appears in BOTH, so the "
                        "unfiltered union double-counts its prompt "
                        "tokens (troubleshooting §33)")
    args = parser.parse_args(argv)

    rows = load_usage(args.dir)
    if args.source:
        rows = [r for r in rows
                if args.source in str(r.get("source", ""))]
    sources = sorted({str(r.get("source", "")) for r in rows})
    agg = rollup(rows)
    if args.tenant:
        label = sanitize_label(args.tenant)
        agg = {label: agg[label]} if label in agg else {}
    mixed = (not args.source and any("gateway" in s_ for s_ in sources)
             and any("gateway" not in s_ for s_ in sources))
    if args.json:
        print(json.dumps({"schema": USAGE_SCHEMA, "rows": len(rows),
                          "sources": sources, "tenants": agg},
                         sort_keys=True))
        return 0
    if not agg:
        print(f"no usage rows in {args.dir}"
              + (f" for tenant {args.tenant!r}" if args.tenant else ""))
        return 0
    print(f"{len(rows)} usage row(s), {len(agg)} tenant(s)"
          + (f" from {len(sources)} source(s)" if len(sources) > 1 else ""))
    if mixed:
        print("  note: gateway edge rows AND engine rows present — a "
              "relayed request is counted in both; filter with "
              "--source server / --source gateway for an unduplicated "
              "view")
    for tenant, r in agg.items():
        outcomes = " ".join(
            f"{k}={v}" for k, v in r["by_outcome"].items())
        print(f"  {tenant}: requests={r['requests']} ({outcomes}) "
              f"tokens_in={r['prompt_tokens']} "
              f"tokens_out={r['generated_tokens']} "
              f"cached={r['cache_hit_tokens']} "
              f"device_s={r['device_time_est_s']} "
              f"queue_wait_s={r['queue_wait_s']} "
              f"preemptions={r['preemptions']}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
