"""Bench/sweep regression gate (ISSUE 7 tentpole leg 3).

    python -m ditl_tpu.telemetry.perf_compare old.json new.json \
        [--threshold 0.05]

Diffs two performance records — either two single bench rows (``bench.py``'s
one-JSON-line output, saved to a file) or two versioned sweep records
(``bench.py --sweep`` / ``experiments/bwd_kernels.py``) — metric by metric
against a relative threshold, and **exits nonzero on regression**. This is
the gate every MFU-push PR runs against the previous round's record: a
lever that silently lost throughput fails CI instead of shipping.

Comparison rules:

- Each known metric has a direction: throughput/MFU regress when they FALL,
  step time regresses when it RISES. Unknown keys are ignored (records may
  grow fields without breaking old gates).
- Sweep records compare cell-by-cell on the cell key (the dotted-override
  spec), so only identical configurations are ever diffed; cells present
  only on one side are reported but do not gate (a grown grid is not a
  regression).
- Mismatched schema versions or record shapes are a usage error (exit 2),
  never a silent pass.

Exit codes: 0 = within thresholds, 1 = regression, 2 = usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys

from ditl_tpu.telemetry.perf import SWEEP_SCHEMA

__all__ = ["compare_metrics", "compare_records", "main"]

# Metric -> direction: +1 = higher is better (regression when it falls),
# -1 = lower is better (regression when it rises).
COMPARE_KEYS = {
    "value": +1,  # bench headline (tokens/sec[/chip])
    "tokens_per_sec_per_chip": +1,
    "mfu": +1,
    "mfu_cost": +1,
    "roofline_mfu_cap": 0,  # informational: config property, never gates
    "step_time_p50_ms": -1,
    "step_ms": -1,
    # Serving-row keys (ISSUE 8, bench --serve-* rows' hoisted `serving`
    # block): scheduler-interference p95 regresses when it RISES (a stall
    # crept back into the budgeted tick composition); the measured
    # prefix-cache hit ratio regresses when it FALLS (routing or paging
    # stopped reusing KV). p50 and totals are reported-not-gated noise.
    "interference_p95_s": -1,
    "prefix_cache_hit_ratio": +1,
    "ttft_p95_s": -1,
    # Disaggregated-fleet A/B keys (ISSUE 9): the heterogeneous-fleet rows
    # are graded on INTERACTIVE latency specifically — batch work is
    # supposed to absorb the prefill burden, so only the interactive split
    # gates (batch p95s are reported context, not regressions).
    "interactive_interference_p95_s": -1,
    "interactive_ttft_p95_s": -1,
    # Autoscaler A/B keys (ISSUE 12, bench --serve-trace-replay rows'
    # hoisted `autoscale` block): replica_seconds is the resource cost the
    # autoscaler exists to cut (regresses when it RISES — the on-vs-off
    # A/B gates it next to the ttft_p95_s already above); the interactive
    # TTFT-SLO violation rate regresses when it rises (scaling must not
    # buy replica-seconds with burned SLO budget).
    "replica_seconds": -1,
    "ttft_slo_violation_rate": -1,
    # KV movement plane keys (ISSUE 13, the tier/handoff bench blocks —
    # `schema`-stamped like the PR 8 serving block): the host-tier hit
    # ratio regresses when it FALLS (the tier stopped absorbing eviction
    # churn — 0.0 on tier-off rows never gates, the a == 0 rule); swap-in
    # p95 regresses when it RISES (host hits are only wins while the
    # device_put stays cheap); the handoff fallback ratio regresses when
    # it rises (shipped prefills failing back to re-prefill means the
    # handoff plane is burning work, not saving it).
    "host_tier_hit_ratio": +1,
    "swap_in_p95_s": -1,
    "handoff_fallback_ratio": -1,
    # Gateway data-plane overhead keys (ISSUE 14, bench
    # --serve-gateway-overhead rows' hoisted `gateway_overhead` block):
    # the stub-replica closed loop isolates the gateway's OWN per-request
    # tax from any device work, so these gate host-side regressions the
    # device benches can't see. Requests/sec through the gateway regresses
    # when it falls; the added latency vs hitting a replica directly
    # regresses when it rises (p50 = the steady tax, p95 = the tail the
    # connect-per-request churn used to own). The pool hit ratio is
    # reported context, not gated — it is 0.0 by construction on the
    # fresh-connect A/B leg.
    "gateway_rps": +1,
    "gateway_added_p50_s": -1,
    "gateway_added_p95_s": -1,
    # Event-loop data plane keys (ISSUE 17, same hoisted block): the
    # evloop-vs-threaded throughput ratio at the legacy concurrency
    # point regresses when it falls below parity — the new plane may
    # never hide a per-request slowdown behind its concurrency win; the
    # max resident gateway thread count during the --serve-concurrency
    # stream hold regresses when it RISES — the whole point of the
    # selector loop is that N open streams cost ~13 threads, not ~N.
    "evloop_vs_threaded_rps_ratio": +1,
    "gateway_max_resident_threads": -1,
    # Usage-metering keys (ISSUE 15, bench --serve-gateway-overhead
    # --serve-usage-metering rows' hoisted `usage_metering` block): the
    # metered leg's requests/sec regresses when it falls, and the
    # fractional rps cost of arming the ledger regresses when it rises —
    # per-tenant accounting must stay cheap enough that nobody is
    # tempted to turn billing off under load.
    "gateway_rps_metered": +1,
    "metering_overhead_ratio": -1,
    # Adapter plane keys (ISSUE 16, bench --serve-multi-lora rows' hoisted
    # `adapters` block): the fractional throughput cost of serving through
    # the stacked adapter gather (vs the base-only A/B leg) regresses when
    # it rises — multi-tenant LoRA is only viable while the per-request
    # gather tax stays a few percent; and the p95 hot-swap wall (verify ->
    # install -> flip) regresses when it rises — a slow swap stretches the
    # window where a publication holds a spare row.
    "adapter_gather_overhead_ratio": -1,
    "adapter_swap_p95_s": -1,
    # Continuous-profiling keys (ISSUE 18, bench --serve-gateway-overhead
    # rows' hoisted `profiler_overhead` block): the profiler-on vs
    # profiler-off req/s ratio regresses when it falls — the always-on
    # sampler + loop-lag watchdog are only "always-on" while they cost
    # within the same-box noise floor of running dark.
    "prof_vs_off_rps_ratio": +1,
    # Bulk-lane goodput keys (ISSUE 19, bench --serve-bulk-backlog rows'
    # hoisted `bulk` block): the lane's tokens/sec regresses when it
    # falls — spare decode capacity the offline backlog stopped soaking
    # is throughput thrown away; and the interactive TTFT p95 measured
    # WITH the backlog running regresses when it rises — the lane's
    # whole contract is zero interactive SLO burn, so bulk-induced
    # interference is a regression of the lane, not of the fleet.
    "bulk_tokens_per_s": +1,
    "bulk_interactive_ttft_p95_s": -1,
}

# Per-key noise floors: gated keys whose honest run-to-run spread on a
# shared box exceeds the default threshold. The evloop-vs-threaded
# ratio is a quotient of two same-box closed loops — the paired-median
# estimator in bench.py cancels drift, but ~±10% spread at parity
# survives it, so gating the ratio at the generic 5% flags the box's
# mood as a data-plane regression. 15% still catches any real
# per-request slowdown while two honest parity rows compare clean.
# The effective threshold is max(--threshold, floor): a caller asking
# for a LOOSER gate than the floor gets what they asked for.
KEY_THRESHOLDS = {
    "evloop_vs_threaded_rps_ratio": 0.15,
    # Same estimator shape, same box: a quotient of two closed loops.
    "prof_vs_off_rps_ratio": 0.15,
}


def _flat(rec: dict) -> dict:
    """The comparable view of one record/cell: top-level keys plus the
    nested ``roofline`` (train rows), ``serving`` (serve rows),
    ``autoscale`` (trace-replay rows), ``kv_handoff`` (handoff-armed
    gateway rows, ISSUE 13), and ``gateway_overhead`` (stub-fleet
    overhead rows, ISSUE 14), ``usage_metering`` (metering-armed
    overhead rows, ISSUE 15), and ``adapters`` (multi-LoRA serving rows,
    ISSUE 16) blocks hoisted — without the hoist the gate
    would silently never compare cost-counted MFU, the serving scheduler
    metrics, the replica-seconds the autoscaler A/B is graded on, the
    handoff fallback ratio, or the gateway's own per-request tax."""
    out = rec
    for block in ("roofline", "serving", "autoscale", "kv_handoff",
                  "gateway_overhead", "usage_metering", "adapters",
                  "profiler_overhead", "bulk"):
        nested = rec.get(block)
        if isinstance(nested, dict):
            out = {**nested, **out}
    return out


def compare_metrics(
    old: dict, new: dict, threshold: float, label: str
) -> tuple[list[str], list[str]]:
    """(report lines, regression lines) for one old/new metric-dict pair.
    A record that went from measured to errored is itself a regression —
    a config that now crashes must not pass the gate because it has no
    numbers to compare."""
    lines: list[str] = []
    regressions: list[str] = []
    old, new = _flat(old), _flat(new)
    # Incident gating (ISSUE 10 satellite): bench rows embed the run's
    # assembled-incident count. NEW incidents on the new side are a
    # "now fails"-class regression — a perf lever that wins throughput by
    # provoking anomaly storms (deadline expiries, preemption thrash) must
    # not pass the gate on its throughput numbers. When BOTH sides had
    # incidents the comparison is reported, not gated (a known-noisy
    # config's storms are context, not a new regression).
    old_inc, new_inc = old.get("incidents"), new.get("incidents")
    if isinstance(new_inc, (int, float)) and new_inc > 0:
        if not old_inc:
            msg = (f"{label}incidents: 0 -> {int(new_inc)} (anomaly "
                   "bundles on the new side; previously clean)")
            lines.append(f"  {msg} REGRESSION")
            regressions.append(msg)
        else:
            lines.append(
                f"  {label}incidents: {int(old_inc)} -> {int(new_inc)} "
                "(both sides had incidents; reported, not gated)"
            )
    # Handoff-fallback gating (ISSUE 13): the generic direction loop
    # below skips keys whose old value is 0 (no relative delta exists),
    # which would make the fallback-ratio gate vacuous in exactly the
    # normal case — a previously CLEAN handoff plane (ratio 0.0). Treat
    # 0 -> >0 like incidents: fallbacks appearing is a regression class
    # of its own, not a percentage move.
    old_fb = old.get("handoff_fallback_ratio")
    new_fb = new.get("handoff_fallback_ratio")
    if (isinstance(new_fb, (int, float)) and new_fb > 0
            and isinstance(old_fb, (int, float)) and old_fb == 0):
        msg = (f"{label}handoff_fallback_ratio: 0 -> {new_fb:g} (shipped "
               "prefills now failing back to re-prefill; previously clean)")
        lines.append(f"  {msg} REGRESSION")
        regressions.append(msg)
    # Invariant-lint gating (ISSUE 11 satellite): rows stamp
    # `analysis_clean` (bench runs `ditl_tpu.analysis` once per process).
    # clean -> dirty is a "now fails"-class regression — a perf win that
    # ships an invariant violation (a stray sync, an unguarded attribute)
    # must not pass on its numbers. Both-sides-dirty is reported, not
    # gated; rows predating the stamp (absent) are skipped.
    old_an, new_an = old.get("analysis_clean"), new.get("analysis_clean")
    if new_an is False:
        if old_an is True:
            msg = (f"{label}analysis_clean: true -> false (invariant "
                   "lint now fails; run python -m ditl_tpu.analysis)")
            lines.append(f"  {msg} REGRESSION")
            regressions.append(msg)
        else:
            lines.append(
                f"  {label}analysis_clean: false on "
                f"{'both sides' if old_an is False else 'new side only'} "
                "(reported, not gated)"
            )
    if new.get("error") and not old.get("error"):
        msg = (f"{label}previously measured, now fails: "
               f"{str(new['error'])[:200]}")
        lines.append(f"  {msg} REGRESSION")
        regressions.append(msg)
        return lines, regressions
    if old.get("error"):
        state = "still failing" if new.get("error") else "now measured"
        lines.append(f"  {label}old record errored ({state}; not gated)")
        return lines, regressions
    for key, direction in COMPARE_KEYS.items():
        a, b = old.get(key), new.get(key)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            continue
        if a == 0:
            continue
        rel = (b - a) / abs(a)
        # Signed "improvement" in the metric's own direction.
        gain = rel * direction
        key_threshold = max(threshold, KEY_THRESHOLDS.get(key, 0.0))
        verdict = "ok"
        if direction != 0 and gain < -key_threshold:
            verdict = "REGRESSION"
            regressions.append(
                f"{label}{key}: {a:g} -> {b:g} ({rel:+.1%}, threshold "
                f"{key_threshold:.0%})"
            )
        lines.append(f"  {label}{key}: {a:g} -> {b:g} ({rel:+.1%}) {verdict}")
    return lines, regressions


def _is_sweep(rec: dict) -> bool:
    return isinstance(rec.get("cells"), dict)


def compare_records(old: dict, new: dict, threshold: float) -> tuple[int, str]:
    """(exit code, human report). Accepts two bench rows or two sweep
    records; mixing shapes is a usage error."""
    out: list[str] = []
    regressions: list[str] = []
    if _is_sweep(old) != _is_sweep(new):
        return 2, "error: cannot compare a sweep record with a bench row"
    for side, rec in (("old", old), ("new", new)):
        schema = rec.get("schema")
        if schema is not None and schema != SWEEP_SCHEMA:
            return 2, (
                f"error: {side} record has schema {schema!r}; this tool "
                f"understands schema {SWEEP_SCHEMA}"
            )
    if _is_sweep(old):
        old_cells, new_cells = old["cells"], new["cells"]
        common = [k for k in old_cells if k in new_cells]
        if not common:
            return 2, "error: the two sweep records share no cells"
        for side, only in (
            ("old", sorted(set(old_cells) - set(new_cells))),
            ("new", sorted(set(new_cells) - set(old_cells))),
        ):
            for k in only:
                out.append(f"  [{k}] only in {side} record (not gated)")
        for k in sorted(common):
            lines, regs = compare_metrics(
                old_cells[k], new_cells[k], threshold, f"[{k}] "
            )
            out.extend(lines)
            regressions.extend(regs)
    else:
        m_old, m_new = old.get("metric"), new.get("metric")
        if m_old != m_new:
            out.append(
                f"  warning: metric labels differ ({m_old!r} vs {m_new!r}) "
                "— comparing anyway; make sure the configs match"
            )
        lines, regs = compare_metrics(old, new, threshold, "")
        out.extend(lines)
        regressions.extend(regs)
        if not lines:
            return 2, "error: no comparable numeric metrics in the records"
    if regressions:
        out.append("")
        out.append(f"FAIL: {len(regressions)} regression(s)")
        out.extend(f"  {r}" for r in regressions)
        return 1, "\n".join(out)
    out.append("")
    out.append(f"PASS: no metric regressed past {threshold:.0%}")
    return 0, "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ditl_tpu.telemetry.perf_compare",
        description="diff two bench/sweep JSON records; exit 1 on regression",
    )
    parser.add_argument("old", help="baseline record (bench row or sweep JSON)")
    parser.add_argument("new", help="candidate record to gate")
    parser.add_argument(
        "--threshold", type=float, default=0.05,
        help="relative regression threshold (default 0.05 = 5%%)",
    )
    args = parser.parse_args(argv)
    if not 0 < args.threshold < 1:
        print(f"error: --threshold must be in (0, 1), got {args.threshold}",
              file=sys.stderr)
        return 2
    records = []
    for path in (args.old, args.new):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 2
        if not isinstance(rec, dict):
            print(f"error: {path} is not a JSON object", file=sys.stderr)
            return 2
        records.append(rec)
    code, report = compare_records(records[0], records[1], args.threshold)
    print(f"perf_compare: {args.old} -> {args.new}")
    print(report)
    return code


if __name__ == "__main__":
    sys.exit(main())
