"""Unified telemetry subsystem (ISSUE 3): process-local metrics registry
(registry.py), serving instrument bundle (serving.py), goodput/badput
accounting (goodput.py), and the cross-process JSONL event journal
(journal.py). Host-only by design — importing this package never touches
jax, and no instrument accepts a device value."""

from ditl_tpu.telemetry.goodput import (
    BADPUT_BUCKETS,
    GoodputTracker,
    lost_work_from_journal,
)
from ditl_tpu.telemetry.journal import (
    EventJournal,
    controller_journal_path,
    merge_journals,
    read_journal,
    worker_journal_path,
    write_pod_timeline,
)
from ditl_tpu.telemetry.registry import (
    LATENCY_BUCKETS_S,
    TOKEN_LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from ditl_tpu.telemetry.serving import ServingMetrics

__all__ = [
    "BADPUT_BUCKETS",
    "Counter",
    "EventJournal",
    "Gauge",
    "GoodputTracker",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricsRegistry",
    "ServingMetrics",
    "TOKEN_LATENCY_BUCKETS_S",
    "controller_journal_path",
    "lost_work_from_journal",
    "merge_journals",
    "read_journal",
    "worker_journal_path",
    "write_pod_timeline",
]
