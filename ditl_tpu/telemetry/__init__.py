"""Unified telemetry subsystem (ISSUE 3 + 6 + 7 + 10): process-local
metrics registry (registry.py), serving instrument bundle (serving.py),
goodput/badput accounting (goodput.py), the cross-process JSONL event
journal (journal.py), end-to-end request tracing (tracing.py), Chrome-trace
export (trace_export.py), SLO burn-rate monitoring (slo.py), the training
performance observatory (perf.py: step-time anatomy, roofline cost
analysis, versioned sweep records; perf_compare.py: the regression gate),
HBM accounting (memwatch.py), and the flight-recorder/anomaly/incident
plane (flight.py: always-on black-box rings; anomaly.py: signal-driven
detectors; incident.py: fingerprint-deduped self-contained bundles;
catalog.py: the generated metrics catalog), and per-tenant usage
metering/cost attribution (usage.py: the crash-consistent usage ledger,
bounded per-tenant meters, and the noisy-neighbor conviction the
serving anomaly monitor applies — ISSUE 15). Host-only by design —
importing this package never touches jax (memwatch imports it lazily
inside functions), and no instrument accepts a device value."""

from ditl_tpu.telemetry.anomaly import (
    Anomaly,
    AnomalyPlane,
    GatewayDetector,
    ServingAnomalyMonitor,
    ServingDetector,
    TrainingDetector,
)
from ditl_tpu.telemetry.flight import (
    LIVENESS_RING,
    ROUTING_RING,
    STEP_RING,
    TICK_RING,
    FlightRecorder,
    FlightRing,
)
from ditl_tpu.telemetry.goodput import (
    BADPUT_BUCKETS,
    GoodputTracker,
    lost_work_from_journal,
)
from ditl_tpu.telemetry.incident import (
    IncidentManager,
    incidents_total,
    list_bundles,
    read_bundle,
)
from ditl_tpu.telemetry.memwatch import MemoryWatcher, live_buffer_topk
from ditl_tpu.telemetry.perf import (
    ANATOMY_BUCKETS,
    SWEEP_SCHEMA,
    StepAnatomy,
    compiled_cost,
    load_sweep_record,
    new_sweep_record,
    record_sweep_cell,
    roofline,
)
from ditl_tpu.telemetry.journal import (
    EventJournal,
    controller_journal_path,
    merge_journals,
    read_journal,
    worker_journal_path,
    write_pod_timeline,
)
from ditl_tpu.telemetry.registry import (
    LATENCY_BUCKETS_S,
    TOKEN_LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from ditl_tpu.telemetry.serving import ServingMetrics
from ditl_tpu.telemetry.usage import (
    UsageLedger,
    UsageMeter,
    convict_noisy_neighbor,
    load_usage,
    rollup,
    usage_ledger_path,
)
from ditl_tpu.telemetry.slo import (
    BurnRateMonitor,
    Objective,
    gateway_slo,
    serving_slo,
)
from ditl_tpu.telemetry.tracing import (
    NULL_TRACER,
    Span,
    SpanContext,
    Tracer,
    format_traceparent,
    new_request_id,
    parse_traceparent,
)

__all__ = [
    "ANATOMY_BUCKETS",
    "Anomaly",
    "AnomalyPlane",
    "BADPUT_BUCKETS",
    "BurnRateMonitor",
    "Counter",
    "EventJournal",
    "FlightRecorder",
    "FlightRing",
    "Gauge",
    "GatewayDetector",
    "GoodputTracker",
    "Histogram",
    "IncidentManager",
    "LATENCY_BUCKETS_S",
    "LIVENESS_RING",
    "MemoryWatcher",
    "MetricsRegistry",
    "NULL_TRACER",
    "Objective",
    "ROUTING_RING",
    "STEP_RING",
    "SWEEP_SCHEMA",
    "ServingAnomalyMonitor",
    "ServingDetector",
    "ServingMetrics",
    "Span",
    "SpanContext",
    "StepAnatomy",
    "TICK_RING",
    "TOKEN_LATENCY_BUCKETS_S",
    "Tracer",
    "TrainingDetector",
    "UsageLedger",
    "UsageMeter",
    "compiled_cost",
    "controller_journal_path",
    "convict_noisy_neighbor",
    "format_traceparent",
    "gateway_slo",
    "incidents_total",
    "list_bundles",
    "live_buffer_topk",
    "load_sweep_record",
    "load_usage",
    "lost_work_from_journal",
    "merge_journals",
    "new_request_id",
    "new_sweep_record",
    "parse_traceparent",
    "read_bundle",
    "read_journal",
    "record_sweep_cell",
    "rollup",
    "roofline",
    "serving_slo",
    "usage_ledger_path",
    "worker_journal_path",
    "write_pod_timeline",
]
