"""Serving-side telemetry bundle (ISSUE 3 tentpole leg 1).

One object carrying every per-request instrument the serving stack records —
latency histograms (queue-wait, TTFT, per-token decode, end-to-end) and the
operational counters (admissions, 429s, preemptions, degrade windows,
grammar-masked tokens, speculative accept/reject) — shared between
``infer/continuous.ContinuousEngine`` (which records on its scheduler ticks)
and ``infer/server.py`` (which records the lock-step path and renders
``/metrics``).

Semantics worth pinning (the vLLM-style contract, adapted to chunked ticks):

- **queue wait**: submit -> the admission that moved the request into a slot.
  A preemption-resume is NOT a second admission (the request never left the
  user's perspective of "running").
- **TTFT**: submit -> the harvest that delivered the first generated token to
  the host. Harvests happen once per decode tick, so TTFT is quantized by the
  tick (decode_chunk steps) — that IS when a streaming client can first see
  the token, so the quantization is honest, not an artifact.
- **per-token decode latency**: harvest-interval / tokens-in-chunk, observed
  once per token of the chunk. The histogram's shape answers "TPOT p50/p99".
- **grammar-masked tokens**: generated tokens whose request carried an FSM
  constraint — every one of those decode steps paid the mask gather.
- **speculative accepted/rejected**: accepted = drafted tokens the verify
  forward kept; rejected = drafted tokens it threw away. The per-round bonus
  token (emitted even at zero acceptance) is neither — it is ordinary decode
  output, counted by ``tokens_generated``.
- **prefix-cache hit/miss tokens** (ISSUE 8): at slot admission, prompt
  tokens whose KV came from the prefix cache (paged content-hash match or a
  registered contiguous prefix) count as hits; tokens the engine actually
  prefilled count as misses. Resume re-prefills after a preemption are
  NEITHER — the request already paid (and was credited) for its prompt at
  first admission; resume cost is thrash, tracked separately. The ratio
  gauge is recomputed from the counters at render time, and TTFT is
  additionally observed into a hit/miss split pair so "does a routed cache
  hit actually buy latency" is answerable from /metrics alone.

All increments are host-side floats/ints the scheduler already holds — zero
device syncs (registry.py's rule).
"""

from __future__ import annotations

import math
import time
from typing import Iterable, Sequence

from ditl_tpu.telemetry.registry import (
    LATENCY_BUCKETS_S,
    Histogram,
    MetricsRegistry,
    TOKEN_LATENCY_BUCKETS_S,
)

__all__ = ["SLO_CLASS_NAMES", "ServingMetrics", "backlog_retry_after",
           "flattened_stats_lines", "merged_histogram",
           "serving_bench_summary", "snapshot_serving",
           "ttft_slo_violation_rate"]


def flattened_stats_lines(stats: dict, reserved: frozenset | set = frozenset(),
                          prefix: str = "ditl_serving") -> list[str]:
    """The /v1/stats snapshot flattened to ``<prefix>_<path>`` gauge lines
    (slot occupancy, queue depth, page pool, acceptance EMA) — point-in-
    time state, kept as gauges on purpose. ``reserved`` names registry
    metrics a flattened gauge must not shadow (e.g. the lifetime
    "preemptions" count, a real ``_total`` counter — exposing both a ``x``
    gauge and an ``x_total`` counter for the same fact invites dashboards
    built on the wrong one). Shared by ``infer/server.py``'s /metrics and
    the metrics-catalog drift guard (telemetry/catalog.py), so the
    exposition and the catalog cannot diverge silently."""
    lines: list[str] = []

    def emit(path: str, obj) -> None:
        if isinstance(obj, dict):
            for k, v in obj.items():
                emit(f"{path}_{k}" if path else str(k), v)
        elif f"{prefix}_{path}" in reserved:
            return
        elif isinstance(obj, bool):
            lines.append(f"# TYPE {prefix}_{path} gauge")
            lines.append(f"{prefix}_{path} {int(obj)}")
        elif isinstance(obj, (int, float)) and obj == obj:  # drop NaN
            lines.append(f"# TYPE {prefix}_{path} gauge")
            lines.append(f"{prefix}_{path} {obj}")
        # strings (engine/cache_mode names) have no gauge form; skip

    emit("", stats)
    return lines


def backlog_retry_after(
    samples: Iterable[tuple[float, float]],
    backlog: int,
    *,
    floor: int = 1,
    now: float | None = None,
    max_age_s: float = 60.0,
    clamp_s: int = 30,
    slo_class: str = "",
) -> int:
    """Backlog-aware ``Retry-After``: seconds until ``backlog`` requests
    clear at the recently measured service rate, clamped to
    ``[max(1, floor), clamp_s]``. ``samples`` are ``(wall_time,
    cumulative_completed)`` pairs; only the last ``max_age_s`` worth count —
    an hour-old sample would otherwise collapse the measured rate to ~zero
    and send a trivial backlog straight to the clamp. With no measurable
    rate (cold start, burst before the first completion) the estimate
    degrades to one second per backlogged request — still
    backlog-proportional, so client herds honoring Retry-After
    (client/llm.py) space out instead of synchronizing. Shared by
    ``infer/server.py`` (per-replica 429s) and ``gateway/gateway.py``
    (fleet-level 429s); jax-free like everything in telemetry/.

    ``slo_class`` is the ISSUE 19 class hint: for ``best_effort`` the
    clamp relaxes 4x and the floor's urgency is dropped. The interactive
    clamp exists so a latency-sensitive client retries soon after a
    transient spike — but a bulk submitter bounced off a deep offline
    backlog should come back when the backlog has actually moved, not
    hammer the fleet every ``clamp_s`` seconds. The estimate itself is
    unchanged: callers pass bulk-lane samples/backlog for bulk 429s."""
    now = time.time() if now is None else now
    if slo_class == "best_effort":
        clamp_s = clamp_s * 4
        floor = 1
    # Callers pass a LIVE deque that other handler threads append to
    # mid-overload (exactly when 429s fire); tuple() snapshots it in one
    # C-level pass, where iterating directly would raise "deque mutated
    # during iteration".
    recent = [(t, c) for t, c in tuple(samples) if now - t <= max_age_s]
    rate = 0.0
    if len(recent) >= 2:
        (t0, c0), (t1, c1) = recent[0], recent[-1]
        if t1 - t0 >= 0.5 and c1 > c0:
            rate = (c1 - c0) / (t1 - t0)
    estimate = backlog / rate if rate > 0 else float(1 + backlog)
    return max(1, floor, min(clamp_s, math.ceil(estimate)))

PREFIX = "ditl_serving"

# Mirror of infer/continuous.SLO_CLASSES' names — duplicated (not imported)
# so telemetry/ stays jax-free on import, like gateway/admission.py's copy;
# all three surfaces are pinned equal by test.
SLO_CLASS_NAMES = ("interactive", "batch", "best_effort")


class ServingMetrics:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self.queue_wait = r.histogram(
            f"{PREFIX}_request_queue_wait_seconds",
            "submit -> slot admission", LATENCY_BUCKETS_S,
        )
        self.ttft = r.histogram(
            f"{PREFIX}_request_ttft_seconds",
            "submit -> first generated token harvested", LATENCY_BUCKETS_S,
        )
        self.decode_token = r.histogram(
            f"{PREFIX}_decode_token_seconds",
            "per-token decode latency (harvest interval / chunk tokens)",
            TOKEN_LATENCY_BUCKETS_S,
        )
        self.e2e = r.histogram(
            f"{PREFIX}_request_e2e_seconds",
            "submit -> request finished", LATENCY_BUCKETS_S,
        )
        self.requests = r.counter(
            f"{PREFIX}_requests", "requests accepted by submit")
        self.admitted = r.counter(
            f"{PREFIX}_requests_admitted", "requests admitted into a slot")
        self.completed = r.counter(
            f"{PREFIX}_requests_completed", "requests finished")
        self.queue_full = r.counter(
            f"{PREFIX}_queue_full", "submissions rejected QueueFull (HTTP 429)")
        self.preemptions = r.counter(
            f"{PREFIX}_preemptions",
            "optimistic-admission preemptions (pages reclaimed mid-flight)")
        self.admission_degrades = r.counter(
            f"{PREFIX}_admission_degrade_windows",
            "tick windows that engaged the anti-thrash admission degrade")
        self.grammar_masked = r.counter(
            f"{PREFIX}_grammar_masked_tokens",
            "generated tokens decoded under an FSM grammar mask")
        self.spec_accepted = r.counter(
            f"{PREFIX}_spec_accepted_tokens",
            "speculative drafted tokens accepted by verify forwards")
        self.spec_rejected = r.counter(
            f"{PREFIX}_spec_rejected_tokens",
            "speculative drafted tokens rejected by verify forwards")
        self.tokens_generated = r.counter(
            f"{PREFIX}_tokens_generated", "tokens generated (all requests)")
        self.tpot_interference = r.histogram(
            f"{PREFIX}_tpot_interference_seconds",
            "per-tick decode delay a victim request absorbed because the "
            "tick also ran another request's prefill chunk(s) — the "
            "scheduler-interference signal behind chunked-prefill tuning "
            "(ISSUE 6)", TOKEN_LATENCY_BUCKETS_S,
        )
        self.deadline_expired = r.counter(
            f"{PREFIX}_deadline_expired",
            "requests evicted from the queue/slots at their deadline "
            "(expired work stops consuming engine ticks)")
        self.client_disconnects = r.counter(
            f"{PREFIX}_client_disconnects",
            "in-flight generations cancelled because the client vanished "
            "mid-stream")
        # -- prefix-cache accounting (ISSUE 8) ---------------------------
        self.prefix_cache_hit_tokens = r.counter(
            f"{PREFIX}_prefix_cache_hit_tokens",
            "prompt tokens whose KV was reused from the prefix cache at "
            "slot admission (paged content-hash match or registered prefix)")
        self.prefix_cache_miss_tokens = r.counter(
            f"{PREFIX}_prefix_cache_miss_tokens",
            "prompt tokens the engine prefilled because no cached KV "
            "covered them")
        self.prefix_cache_evictions = r.counter(
            f"{PREFIX}_prefix_cache_evictions",
            "published prefix pages reclaimed by LRU eviction under pool "
            "pressure")
        self.prefix_cache_hit_ratio = r.gauge(
            f"{PREFIX}_prefix_cache_hit_ratio",
            "measured hit tokens / (hit + miss) tokens — the number the "
            "gateway affinity router's score is validated against")
        self.ttft_cache_hit = r.histogram(
            f"{PREFIX}_request_ttft_cache_hit_seconds",
            "TTFT of requests whose prompt hit the prefix cache (>= 1 "
            "reused token)", LATENCY_BUCKETS_S,
        )
        self.ttft_cache_miss = r.histogram(
            f"{PREFIX}_request_ttft_cache_miss_seconds",
            "TTFT of requests whose prompt missed the prefix cache "
            "entirely", LATENCY_BUCKETS_S,
        )
        # -- tiered hits + host tier + KV handoff (ISSUE 13) -------------
        # The total hit counters above stay the PR 8 aggregate; the tier
        # split says WHERE the reuse came from — an HBM match, a host-RAM
        # swap-in, or a shipped prefill-handoff page. Host hits cost a
        # device_put (the swap-in histogram), so conflating them with HBM
        # hits would hide exactly the churn the tier absorbs.
        self.prefix_cache_hit_tokens_by_tier = {
            tier: r.counter(
                f"{PREFIX}_prefix_cache_hit_tokens_{tier}",
                f"prompt tokens reused via the {tier} tier "
                f"({desc})",
            )
            for tier, desc in (
                ("hbm", "published pages resident in the device pool"),
                ("host", "pages swapped back in from the host-RAM tier"),
                ("handoff", "pages shipped by a prefill->decode handoff"),
            )
        }
        self.host_tier_swap_in = r.histogram(
            f"{PREFIX}_host_tier_swap_in_seconds",
            "host-tier swap-in latency per admission (crc verify + "
            "device_put + republish of the matched run)",
            LATENCY_BUCKETS_S,
        )
        self.host_tier_spilled_pages = r.counter(
            f"{PREFIX}_host_tier_spilled_pages",
            "LRU-evicted published pages spilled into the host-RAM tier")
        self.host_tier_swapped_pages = r.counter(
            f"{PREFIX}_host_tier_swapped_pages",
            "host-tier pages swapped back into the device pool on an "
            "admission miss")
        self.host_tier_dropped_pages = r.counter(
            f"{PREFIX}_host_tier_dropped_pages",
            "spill pages dropped (tier cap, oversized entry, or an "
            "injected kvtier.spill fault)")
        self.host_tier_corrupt_entries = r.counter(
            f"{PREFIX}_host_tier_corrupt_entries",
            "host-tier entries whose crc32 failed at swap-in — detected, "
            "dropped, and re-prefilled; never served")
        self.host_tier_evictions = r.counter(
            f"{PREFIX}_host_tier_evictions",
            "host-tier entries LRU-evicted under the size cap")
        self.kv_handoff_imports = r.counter(
            f"{PREFIX}_kv_handoff_imports",
            "prefill->decode KV blobs imported by this replica")
        self.kv_handoff_tokens = r.counter(
            f"{PREFIX}_kv_handoff_tokens",
            "prompt tokens installed from shipped prefill-handoff pages")
        self.kv_handoff_rejected = r.counter(
            f"{PREFIX}_kv_handoff_rejected",
            "KV handoff blobs rejected (torn/short read, crc mismatch, or "
            "geometry mismatch) — reject-don't-install")
        # -- per-SLO-class splits (ISSUE 9) ------------------------------
        # The disaggregated-serving A/B is graded on INTERACTIVE latency
        # specifically (batch work is supposed to absorb the prefill
        # burden), so TTFT and scheduler interference split by the
        # request's class. The unsplit histograms above remain the
        # all-traffic aggregate.
        self.ttft_by_class = {
            cls: r.histogram(
                f"{PREFIX}_request_ttft_{cls}_seconds",
                f"TTFT of {cls}-class requests", LATENCY_BUCKETS_S,
            )
            for cls in SLO_CLASS_NAMES
        }
        self.interference_by_class = {
            cls: r.histogram(
                f"{PREFIX}_tpot_interference_{cls}_seconds",
                f"per-tick decode delay absorbed by {cls}-class victims "
                "because the tick also ran another request's prefill",
                TOKEN_LATENCY_BUCKETS_S,
            )
            for cls in SLO_CLASS_NAMES
        }

    def note_prefix_cache(self, hit_tokens: int, miss_tokens: int,
                          host_tokens: int = 0,
                          handoff_tokens: int = 0) -> None:
        """Record one admission's reused-vs-prefilled prompt token split.
        ``host_tokens`` / ``handoff_tokens`` attribute part of the hit to
        the host-RAM tier / a shipped handoff (ISSUE 13); the remainder is
        an HBM hit. The total counters keep the PR 8 semantics exactly."""
        if hit_tokens > 0:
            self.prefix_cache_hit_tokens.inc(hit_tokens)
            tiers = self.prefix_cache_hit_tokens_by_tier
            hbm = hit_tokens - host_tokens - handoff_tokens
            if hbm > 0:
                tiers["hbm"].inc(hbm)
            if host_tokens > 0:
                tiers["host"].inc(host_tokens)
            if handoff_tokens > 0:
                tiers["handoff"].inc(handoff_tokens)
        if miss_tokens > 0:
            self.prefix_cache_miss_tokens.inc(miss_tokens)

    def cache_hit_ratio(self) -> float | None:
        """hit / (hit + miss) tokens; None before any admission."""
        hit = self.prefix_cache_hit_tokens.value
        total = hit + self.prefix_cache_miss_tokens.value
        if total == 0:
            return None
        return hit / total

    def _refresh_derived(self) -> None:
        ratio = self.cache_hit_ratio()
        if ratio is not None:
            self.prefix_cache_hit_ratio.set(round(ratio, 6))

    def render(self) -> str:
        self._refresh_derived()
        return self.registry.render()

    def summary(self) -> dict:
        self._refresh_derived()
        return self.registry.summary()


def merged_histogram(hists: Sequence[Histogram]) -> Histogram:
    """One histogram holding every input's observations (identical bucket
    ladders required) — how fleet-level quantiles are computed from
    per-replica instruments without a shared registry (bench.py embeds
    the p50/p95 of the merged interference histogram, not a quantile of
    per-replica quantiles, which would not be a quantile of anything)."""
    if not hists:
        raise ValueError("need at least one histogram to merge")
    buckets = hists[0].buckets
    out = Histogram("_merged", buckets=buckets)
    for h in hists:
        if h.buckets != buckets:
            raise ValueError(
                f"bucket ladders differ: {h.buckets} vs {buckets}"
            )
        for i, c in enumerate(h._counts):
            out._counts[i] += c
        out._sum += h._sum
        out._count += h._count
    return out


def _hist_snap(hists: Sequence[Histogram]) -> list:
    return [(list(h._counts), h.sum, h.count) for h in hists]


def snapshot_serving(bundles: Sequence["ServingMetrics"]) -> dict:
    """Cumulative snapshot of the instruments ``serving_bench_summary``
    consumes — taken AFTER warm-up so the gated summary covers only the
    timed region (warm-up TTFTs are compile seconds, and their prompt
    misses deflate the hit ratio; both would corrupt the perf_compare
    gate)."""
    return {
        "interference": _hist_snap([b.tpot_interference for b in bundles]),
        "ttft": _hist_snap([b.ttft for b in bundles]),
        "ttft_by_class": {
            cls: _hist_snap([b.ttft_by_class[cls] for b in bundles])
            for cls in SLO_CLASS_NAMES
        },
        "interference_by_class": {
            cls: _hist_snap([b.interference_by_class[cls] for b in bundles])
            for cls in SLO_CLASS_NAMES
        },
        "hit": sum(b.prefix_cache_hit_tokens.value for b in bundles),
        "miss": sum(b.prefix_cache_miss_tokens.value for b in bundles),
        "evictions": sum(
            b.prefix_cache_evictions.value for b in bundles
        ),
        # Tiered-hit + swap-in accounting (ISSUE 13): timed-region scoping
        # for the host-tier block the bench rows embed.
        "tier_hit": {
            tier: sum(
                b.prefix_cache_hit_tokens_by_tier[tier].value
                for b in bundles
            )
            for tier in ("hbm", "host", "handoff")
        },
        "swap_in": _hist_snap([b.host_tier_swap_in for b in bundles]),
    }


def _subtract(hist: Histogram, snaps) -> None:
    for counts, s, c in snaps:
        for i, v in enumerate(counts):
            hist._counts[i] -= v
        hist._sum -= s
        hist._count -= c


def serving_bench_summary(bundles: Sequence["ServingMetrics"],
                          since: dict | None = None) -> dict:
    """The serving block a ``bench.py --serve-*`` row embeds (ISSUE 8
    satellite): fleet-merged interference quantiles plus the measured
    prefix-cache hit ratio, flat numeric keys so
    ``telemetry/perf_compare.py`` can gate them like train metrics.
    ``since`` (a :func:`snapshot_serving` taken after warm-up) restricts
    every number to the timed region. Per-SLO-class TTFT/interference p95s
    (ISSUE 9) ride along as ``<class>_ttft_p95_s`` /
    ``<class>_interference_p95_s`` — the interactive pair is what the
    disaggregated-fleet A/B is perf_compare-gated on."""
    interference = merged_histogram([b.tpot_interference for b in bundles])
    ttft = merged_histogram([b.ttft for b in bundles])
    by_class = {
        cls: (merged_histogram([b.ttft_by_class[cls] for b in bundles]),
              merged_histogram(
                  [b.interference_by_class[cls] for b in bundles]))
        for cls in SLO_CLASS_NAMES
    }
    hit = sum(b.prefix_cache_hit_tokens.value for b in bundles)
    miss = sum(b.prefix_cache_miss_tokens.value for b in bundles)
    evictions = sum(b.prefix_cache_evictions.value for b in bundles)
    tier_hit = {
        tier: sum(
            b.prefix_cache_hit_tokens_by_tier[tier].value for b in bundles
        )
        for tier in ("hbm", "host", "handoff")
    }
    swap_in = merged_histogram([b.host_tier_swap_in for b in bundles])
    if since is not None:
        _subtract(interference, since["interference"])
        _subtract(ttft, since["ttft"])
        for cls, (t_h, i_h) in by_class.items():
            _subtract(t_h, since["ttft_by_class"][cls])
            _subtract(i_h, since["interference_by_class"][cls])
        hit -= since["hit"]
        miss -= since["miss"]
        evictions -= since["evictions"]
        # Older snapshots (pre-ISSUE-13 sweep records) carry no tier keys.
        for tier, v in since.get("tier_hit", {}).items():
            tier_hit[tier] -= v
        if "swap_in" in since:
            _subtract(swap_in, since["swap_in"])
    out = {
        "interference_count": interference.count,
        "interference_total_s": round(interference.sum, 6),
        "prefix_cache_hit_tokens": int(hit),
        "prefix_cache_miss_tokens": int(miss),
        "prefix_cache_evictions": int(evictions),
    }
    tq = ttft.quantile(0.95)
    out["ttft_p95_s"] = round(tq, 6) if tq is not None else None
    for q, key in ((0.5, "interference_p50_s"), (0.95, "interference_p95_s")):
        v = interference.quantile(q)
        out[key] = round(v, 6) if v is not None else None
    for cls, (t_h, i_h) in by_class.items():
        tv, iv = t_h.quantile(0.95), i_h.quantile(0.95)
        out[f"{cls}_ttft_p95_s"] = round(tv, 6) if tv is not None else None
        out[f"{cls}_interference_p95_s"] = (
            round(iv, 6) if iv is not None else None
        )
        out[f"{cls}_interference_count"] = i_h.count
    if hit + miss > 0:
        out["prefix_cache_hit_ratio"] = round(hit / (hit + miss), 4)
        # Host-tier hit ratio (ISSUE 13): host-attributed reuse over ALL
        # prompt tokens — the fraction of the working set the tier (not
        # HBM) carried. 0.0 with the tier off, so perf_compare skips it
        # on an off-leg (a == 0 never gates) and gates it round-over-round
        # on tier-armed rows.
        out["host_tier_hit_ratio"] = round(
            tier_hit["host"] / (hit + miss), 4
        )
        out["tier_hit_tokens"] = dict(tier_hit)
    sq = swap_in.quantile(0.95)
    out["swap_in_count"] = swap_in.count
    out["swap_in_p95_s"] = round(sq, 6) if sq is not None else None
    return out


def ttft_slo_violation_rate(bundles: Sequence["ServingMetrics"],
                            threshold_s: float,
                            since: dict | None = None,
                            slo_class: str = "interactive") -> float | None:
    """Fraction of timed-region TTFT observations ABOVE ``threshold_s``
    (the threshold snaps DOWN to the histogram ladder, the /slo
    convention) — the "interactive SLO burn" number the autoscaler A/B
    row embeds and perf_compare gates (ISSUE 12): scaling down must not
    buy replica-seconds with burned TTFT budget. Computed over the
    ``slo_class`` split by default (unclassed requests schedule — and
    record — as interactive, so they are covered; batch work has no TTFT
    SLO and must not mask or trip the gate); pass ``slo_class=None`` for
    the all-class rate. ``since`` is a :func:`snapshot_serving`
    restricting to the timed region; None when nothing was observed
    (absent != 0)."""
    if slo_class is None:
        ttft = merged_histogram([b.ttft for b in bundles])
        if since is not None:
            _subtract(ttft, since["ttft"])
    else:
        ttft = merged_histogram([b.ttft_by_class[slo_class]
                                 for b in bundles])
        if since is not None:
            _subtract(ttft, since["ttft_by_class"][slo_class])
    if ttft.count <= 0:
        return None
    good, effective = ttft.count_le(threshold_s)
    if effective is None:
        return None
    return round(1.0 - good / ttft.count, 4)
