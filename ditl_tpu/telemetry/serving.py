"""Serving-side telemetry bundle (ISSUE 3 tentpole leg 1).

One object carrying every per-request instrument the serving stack records —
latency histograms (queue-wait, TTFT, per-token decode, end-to-end) and the
operational counters (admissions, 429s, preemptions, degrade windows,
grammar-masked tokens, speculative accept/reject) — shared between
``infer/continuous.ContinuousEngine`` (which records on its scheduler ticks)
and ``infer/server.py`` (which records the lock-step path and renders
``/metrics``).

Semantics worth pinning (the vLLM-style contract, adapted to chunked ticks):

- **queue wait**: submit -> the admission that moved the request into a slot.
  A preemption-resume is NOT a second admission (the request never left the
  user's perspective of "running").
- **TTFT**: submit -> the harvest that delivered the first generated token to
  the host. Harvests happen once per decode tick, so TTFT is quantized by the
  tick (decode_chunk steps) — that IS when a streaming client can first see
  the token, so the quantization is honest, not an artifact.
- **per-token decode latency**: harvest-interval / tokens-in-chunk, observed
  once per token of the chunk. The histogram's shape answers "TPOT p50/p99".
- **grammar-masked tokens**: generated tokens whose request carried an FSM
  constraint — every one of those decode steps paid the mask gather.
- **speculative accepted/rejected**: accepted = drafted tokens the verify
  forward kept; rejected = drafted tokens it threw away. The per-round bonus
  token (emitted even at zero acceptance) is neither — it is ordinary decode
  output, counted by ``tokens_generated``.

All increments are host-side floats/ints the scheduler already holds — zero
device syncs (registry.py's rule).
"""

from __future__ import annotations

import math
import time
from typing import Iterable

from ditl_tpu.telemetry.registry import (
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    TOKEN_LATENCY_BUCKETS_S,
)

__all__ = ["ServingMetrics", "backlog_retry_after"]


def backlog_retry_after(
    samples: Iterable[tuple[float, float]],
    backlog: int,
    *,
    floor: int = 1,
    now: float | None = None,
    max_age_s: float = 60.0,
    clamp_s: int = 30,
) -> int:
    """Backlog-aware ``Retry-After``: seconds until ``backlog`` requests
    clear at the recently measured service rate, clamped to
    ``[max(1, floor), clamp_s]``. ``samples`` are ``(wall_time,
    cumulative_completed)`` pairs; only the last ``max_age_s`` worth count —
    an hour-old sample would otherwise collapse the measured rate to ~zero
    and send a trivial backlog straight to the clamp. With no measurable
    rate (cold start, burst before the first completion) the estimate
    degrades to one second per backlogged request — still
    backlog-proportional, so client herds honoring Retry-After
    (client/llm.py) space out instead of synchronizing. Shared by
    ``infer/server.py`` (per-replica 429s) and ``gateway/gateway.py``
    (fleet-level 429s); jax-free like everything in telemetry/."""
    now = time.time() if now is None else now
    # Callers pass a LIVE deque that other handler threads append to
    # mid-overload (exactly when 429s fire); tuple() snapshots it in one
    # C-level pass, where iterating directly would raise "deque mutated
    # during iteration".
    recent = [(t, c) for t, c in tuple(samples) if now - t <= max_age_s]
    rate = 0.0
    if len(recent) >= 2:
        (t0, c0), (t1, c1) = recent[0], recent[-1]
        if t1 - t0 >= 0.5 and c1 > c0:
            rate = (c1 - c0) / (t1 - t0)
    estimate = backlog / rate if rate > 0 else float(1 + backlog)
    return max(1, floor, min(clamp_s, math.ceil(estimate)))

PREFIX = "ditl_serving"


class ServingMetrics:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self.queue_wait = r.histogram(
            f"{PREFIX}_request_queue_wait_seconds",
            "submit -> slot admission", LATENCY_BUCKETS_S,
        )
        self.ttft = r.histogram(
            f"{PREFIX}_request_ttft_seconds",
            "submit -> first generated token harvested", LATENCY_BUCKETS_S,
        )
        self.decode_token = r.histogram(
            f"{PREFIX}_decode_token_seconds",
            "per-token decode latency (harvest interval / chunk tokens)",
            TOKEN_LATENCY_BUCKETS_S,
        )
        self.e2e = r.histogram(
            f"{PREFIX}_request_e2e_seconds",
            "submit -> request finished", LATENCY_BUCKETS_S,
        )
        self.requests = r.counter(
            f"{PREFIX}_requests", "requests accepted by submit")
        self.admitted = r.counter(
            f"{PREFIX}_requests_admitted", "requests admitted into a slot")
        self.completed = r.counter(
            f"{PREFIX}_requests_completed", "requests finished")
        self.queue_full = r.counter(
            f"{PREFIX}_queue_full", "submissions rejected QueueFull (HTTP 429)")
        self.preemptions = r.counter(
            f"{PREFIX}_preemptions",
            "optimistic-admission preemptions (pages reclaimed mid-flight)")
        self.admission_degrades = r.counter(
            f"{PREFIX}_admission_degrade_windows",
            "tick windows that engaged the anti-thrash admission degrade")
        self.grammar_masked = r.counter(
            f"{PREFIX}_grammar_masked_tokens",
            "generated tokens decoded under an FSM grammar mask")
        self.spec_accepted = r.counter(
            f"{PREFIX}_spec_accepted_tokens",
            "speculative drafted tokens accepted by verify forwards")
        self.spec_rejected = r.counter(
            f"{PREFIX}_spec_rejected_tokens",
            "speculative drafted tokens rejected by verify forwards")
        self.tokens_generated = r.counter(
            f"{PREFIX}_tokens_generated", "tokens generated (all requests)")
        self.tpot_interference = r.histogram(
            f"{PREFIX}_tpot_interference_seconds",
            "per-tick decode delay a victim request absorbed because the "
            "tick also ran another request's prefill chunk(s) — the "
            "scheduler-interference signal behind chunked-prefill tuning "
            "(ISSUE 6)", TOKEN_LATENCY_BUCKETS_S,
        )
        self.deadline_expired = r.counter(
            f"{PREFIX}_deadline_expired",
            "requests evicted from the queue/slots at their deadline "
            "(expired work stops consuming engine ticks)")
        self.client_disconnects = r.counter(
            f"{PREFIX}_client_disconnects",
            "in-flight generations cancelled because the client vanished "
            "mid-stream")

    def render(self) -> str:
        return self.registry.render()

    def summary(self) -> dict:
        return self.registry.summary()
