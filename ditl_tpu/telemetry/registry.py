"""Process-local metrics registry (L0): counters, gauges, fixed-bucket
histograms, rendered in real Prometheus text exposition.

Design rules (ISSUE 3 tentpole):

- **Zero device syncs on the hot path.** Every instrument takes plain Python
  floats the caller already holds (wall-clock deltas, host counters). Nothing
  in this module imports jax; handing it a device array is a caller bug.
- **Lock-cheap increments.** Increments are plain int/float adds under the
  GIL — no lock on the hot path. A racing pair of increments can lose one
  update (telemetry-tolerable); values never go backwards, so the Prometheus
  monotonicity contract for counters and histogram buckets holds. ``render``
  reads a snapshot of the same fields; a scrape concurrent with an increment
  sees either the old or the new value, never a torn one (ints/floats are
  whole objects).
- **Fixed buckets.** Histograms bucket at observe time into a fixed upper-
  bound ladder (no per-sample storage), so memory is O(buckets) no matter
  the request rate, and the exposition is the cumulative ``_bucket``/
  ``_sum``/``_count`` triple Prometheus expects — not a flattened gauge.
"""

from __future__ import annotations

import bisect
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_BUCKETS_S",
    "TOKEN_LATENCY_BUCKETS_S",
]

# Request-scale latency ladder (seconds): sub-ms to the 60 s an overloaded
# queue can reach. Used for queue-wait / TTFT / end-to-end.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Per-token decode ladder (seconds/token): TPU decode steps live in the
# 100 us – 100 ms band; the tail covers CPU-simulation and pathology.
TOKEN_LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0,
)


def _fmt(v: float) -> str:
    """Prometheus number formatting: integral floats print bare."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class Counter:
    """Monotonic counter. ``name`` is the logical name WITHOUT the
    ``_total`` suffix; render appends it to BOTH the sample and the
    ``# TYPE`` line — in the classic text format (``text/plain;
    version=0.0.4``, what /metrics serves) type metadata attaches to the
    exposed sample name, so ``# TYPE x counter`` + ``x_total`` would leave
    the series untyped (the OpenMetrics spelling, a different format)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self._value += n

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name}_total {self.help}")
        lines.append(f"# TYPE {self.name}_total counter")
        lines.append(f"{self.name}_total {_fmt(self._value)}")
        return lines


class Gauge:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    def dec(self, n: float = 1.0) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} gauge")
        lines.append(f"{self.name} {_fmt(self._value)}")
        return lines


class Histogram:
    """Fixed-bucket histogram. ``buckets`` are finite upper bounds in
    increasing order; the implicit +Inf bucket is always present. Bucket
    counts are stored NON-cumulative (one int add per observe) and summed
    cumulatively only at render/quantile time — the exposition-side cost,
    not the hot path's."""

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = LATENCY_BUCKETS_S):
        if list(buckets) != sorted(buckets) or len(set(buckets)) != len(buckets):
            raise ValueError(f"buckets must be strictly increasing: {buckets}")
        self.name = name
        self.help = help
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``n`` observations of ``value`` (one bucket add — used by
        chunked harvests attributing a shared per-token latency to every
        token in the chunk)."""
        i = bisect.bisect_left(self.buckets, value)
        self._counts[i] += n
        self._sum += value * n
        self._count += n

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def count_le(self, bound: float) -> tuple[int, float | None]:
        """Cumulative observations in buckets whose upper bound is <=
        ``bound``, with the effective (snapped-down) bound — the exact
        question a fixed-bucket histogram can answer, used by the SLO
        burn-rate monitor (telemetry/slo.py) to count "requests under the
        latency threshold". ``(0, None)`` when ``bound`` sits below the
        first bucket (no bucket can answer it)."""
        i = bisect.bisect_right(self.buckets, bound)
        if i == 0:
            return 0, None
        return sum(self._counts[:i]), self.buckets[i - 1]

    def quantile(self, q: float) -> float | None:
        """Approximate quantile from the bucket ladder (linear interpolation
        within the bucket, Prometheus ``histogram_quantile`` style). None
        when empty; the top bucket's lower bound when the quantile lands in
        +Inf."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if i == len(self.buckets):  # +Inf bucket: no upper bound
                    return self.buckets[-1] if self.buckets else 0.0
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.buckets[-1] if self.buckets else 0.0

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        cum = 0
        for bound, c in zip(self.buckets, self._counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        cum += self._counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {_fmt(self._sum)}")
        lines.append(f"{self.name}_count {cum}")
        return lines


class MetricsRegistry:
    """Name -> instrument registry. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent per name, type-checked), so independent call
    sites can share an instrument without plumbing references."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()  # registration only, never increments

    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        buckets: tuple[float, ...] = LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def render(self) -> str:
        """Full Prometheus text exposition (no trailing newline; callers
        join sections and append one)."""
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines)

    def summary(self) -> dict:
        """Host-JSON snapshot for bench/stats embedding: counters/gauges as
        scalars; histograms as count/sum/p50/p99."""
        out: dict = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Histogram):
                if m.count:
                    out[name] = {
                        "count": m.count,
                        "sum_s": round(m.sum, 6),
                        "p50_s": round(m.quantile(0.5), 6),
                        "p99_s": round(m.quantile(0.99), 6),
                    }
            else:
                out[name] = m.value
        return out
