"""Structured JSONL event journal (ISSUE 3 tentpole leg 3).

Typed events and wall-clock spans, one JSON object per line, append-only and
line-buffered so a SIGKILLed process loses at most the line it never wrote.
Each elastic-pod participant writes its OWN file (``events-controller.jsonl``,
``events-worker-N.jsonl``) — no cross-process locking, no torn lines — and
the pod controller merges them into one time-ordered pod timeline at the end
of a run, which is how "what happened, in order, when a worker died" becomes
a readable artifact instead of interleaved stderr archaeology.

Ordering: events are sorted by wall-clock ``ts`` with a per-file monotonic
``seq`` tiebreak. Wall clocks are shared here (one host per pod in this
repo's drills); cross-host skew would reorder only events closer together
than the skew, and the per-source ``seq`` keeps each process's own story
internally ordered regardless.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import time

__all__ = [
    "EventJournal",
    "controller_journal_path",
    "worker_journal_path",
    "read_journal",
    "merge_journals",
    "write_pod_timeline",
]

TIMELINE_FILENAME = "pod_timeline.jsonl"


def controller_journal_path(directory: str) -> str:
    return os.path.join(directory, "events-controller.jsonl")


def worker_journal_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"events-worker-{process_index}.jsonl")


class EventJournal:
    """Append-only JSONL event writer for ONE process."""

    def __init__(self, path: str, source: str = ""):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.source = source or os.path.basename(path).rsplit(".", 1)[0]
        self._seq = 0
        # Line-buffered append: one write per event, durable up to the last
        # whole line even through SIGKILL.
        self._fh = open(path, "a", buffering=1)

    def event(self, event: str, **attrs) -> dict:
        """Record one instantaneous event; returns the record written."""
        rec = {
            "ts": time.time(),
            "seq": self._seq,
            "source": self.source,
            "pid": os.getpid(),
            "event": event,
            **attrs,
        }
        self._seq += 1
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec

    @contextlib.contextmanager
    def span(self, event: str, **attrs):
        """Wall-clock span: writes ONE line at exit with start ``ts`` and
        measured ``dur_s`` (start-stamped so the merged timeline orders the
        span where it began)."""
        t0 = time.time()
        try:
            yield
        finally:
            rec = {
                "ts": t0,
                "seq": self._seq,
                "source": self.source,
                "pid": os.getpid(),
                "event": event,
                "dur_s": round(time.time() - t0, 6),
                **attrs,
            }
            self._seq += 1
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_journal(path: str) -> list[dict]:
    """Parse one journal file; corrupt/truncated lines (a process died
    mid-write on a non-line boundary) are skipped, never fatal."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "ts" in rec and "event" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def merge_journals(directory: str) -> list[dict]:
    """All ``events-*.jsonl`` files in ``directory`` merged into one list
    ordered by (ts, source, seq)."""
    records: list[dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "events-*.jsonl"))):
        records.extend(read_journal(path))
    records.sort(key=lambda r: (r["ts"], str(r.get("source", "")),
                                r.get("seq", 0)))
    return records


def write_pod_timeline(directory: str) -> str:
    """Merge every per-process journal in ``directory`` into
    ``pod_timeline.jsonl`` (overwritten whole each call — the merge is
    idempotent, and a partial previous merge must not prefix the new one).
    Returns the timeline path."""
    path = os.path.join(directory, TIMELINE_FILENAME)
    records = merge_journals(directory)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return path
