"""Structured JSONL event journal (ISSUE 3 tentpole leg 3).

Typed events and wall-clock spans, one JSON object per line, append-only and
line-buffered so a SIGKILLed process loses at most the line it never wrote.
Each elastic-pod participant writes its OWN file (``events-controller.jsonl``,
``events-worker-N.jsonl``) — no cross-process locking, no torn lines — and
the pod controller merges them into one time-ordered pod timeline at the end
of a run, which is how "what happened, in order, when a worker died" becomes
a readable artifact instead of interleaved stderr archaeology.

Ordering: events are sorted by wall-clock ``ts`` with a per-file monotonic
``seq`` tiebreak. Wall clocks are shared here (one host per pod in this
repo's drills); cross-host skew would reorder only events closer together
than the skew, and the per-source ``seq`` keeps each process's own story
internally ordered regardless.

Size control (ISSUE 6 satellite): ``max_bytes`` arms rotation so a
long-lived serving process (span records arrive per request, tick instants
per scheduler tick) cannot grow its journal unboundedly. The journal
rotates into sibling segments named ``<stem>.rNNNN.jsonl`` — still matching
the ``events-*.jsonl`` merge glob, and carrying the SAME ``source`` and a
``seq`` that keeps counting, so ``merge_journals`` orders rotated segments
correctly with no special casing. Total footprint is bounded: each segment
caps at ``max_bytes // KEEP_SEGMENTS`` and only the newest
``KEEP_SEGMENTS - 1`` rotated segments are kept (the oldest is deleted),
so disk usage stays ~``max_bytes`` while the newest events always survive.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import threading
import time

__all__ = [
    "EventJournal",
    "controller_journal_path",
    "worker_journal_path",
    "read_journal",
    "merge_journals",
    "write_pod_timeline",
]

TIMELINE_FILENAME = "pod_timeline.jsonl"

# Rotation keeps this many segments (the live file + KEEP_SEGMENTS - 1
# rotated ones), each capped at max_bytes / KEEP_SEGMENTS.
KEEP_SEGMENTS = 4


def controller_journal_path(directory: str) -> str:
    return os.path.join(directory, "events-controller.jsonl")


def worker_journal_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"events-worker-{process_index}.jsonl")


class EventJournal:
    """Append-only JSONL event writer for ONE process. Writes are
    lock-serialized: serving hands one journal to many HTTP handler threads
    (span records), and interleaved partial writes would tear lines."""

    def __init__(self, path: str, source: str = "",
                 max_bytes: int | None = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.source = source or os.path.basename(path).rsplit(".", 1)[0]
        if max_bytes is not None and max_bytes <= 0:
            max_bytes = None
        self.max_bytes = max_bytes
        self._segment_bytes = (
            max(4096, max_bytes // KEEP_SEGMENTS) if max_bytes else None
        )
        # Resume the segment counter from what is already on disk: a
        # relaunched process (elastic worker, restarted replica) reuses the
        # same journal path, and restarting at 0 would os.replace() onto —
        # and silently destroy — the previous incarnation's rotated
        # segments while they are still inside the keep budget.
        self._rotated = 0
        if self._segment_bytes is not None:
            stem, ext = os.path.splitext(self.path)
            for p in glob.glob(f"{stem}.r[0-9][0-9][0-9][0-9]{ext}"):
                try:
                    n = int(p[len(stem) + 2: len(p) - len(ext)])
                except ValueError:
                    continue
                self._rotated = max(self._rotated, n)
        self._seq = 0
        self._lock = threading.Lock()
        # Line-buffered append: one write per event, durable up to the last
        # whole line even through SIGKILL.
        self._fh = open(path, "a", buffering=1)
        self._bytes = self._fh.tell()

    def _rotated_path(self, n: int) -> str:
        stem, ext = os.path.splitext(self.path)
        return f"{stem}.r{n:04d}{ext}"

    def _maybe_rotate(self, incoming: int) -> None:
        """Called under the lock, before a write: when the live segment
        would exceed its cap, rename it to the next rotated-segment name and
        start fresh, deleting segments that age out of the keep budget."""
        if self._segment_bytes is None or self._bytes == 0:
            return
        if self._bytes + incoming <= self._segment_bytes:
            return
        self._fh.close()
        self._rotated += 1
        os.replace(self.path, self._rotated_path(self._rotated))
        expired = self._rotated - (KEEP_SEGMENTS - 1)
        if expired >= 1:
            with contextlib.suppress(OSError):
                os.remove(self._rotated_path(expired))
        self._fh = open(self.path, "a", buffering=1)
        self._bytes = 0

    def event(self, event: str, _ts: float | None = None, **attrs) -> dict:
        """Record one instantaneous event; returns the record written.
        ``_ts`` overrides the stamped wall clock — span records
        (telemetry/tracing.py) are written at END but stamped with their
        START so the merged timeline orders them where they began."""
        base = {
            "ts": time.time() if _ts is None else _ts,
            "source": self.source,
            "pid": os.getpid(),
            "event": event,
            **attrs,
        }
        with self._lock:
            rec = {**base, "seq": self._seq}
            self._seq += 1
            if self._fh is not None:
                line = json.dumps(rec, sort_keys=True) + "\n"
                self._maybe_rotate(len(line))
                self._fh.write(line)
                self._bytes += len(line)
        return rec

    @contextlib.contextmanager
    def span(self, event: str, **attrs):
        """Wall-clock span: writes ONE line at exit with start ``ts`` and
        measured ``dur_s`` (start-stamped so the merged timeline orders the
        span where it began)."""
        t0 = time.time()
        try:
            yield
        finally:
            self.event(event, _ts=t0,
                       dur_s=round(time.time() - t0, 6), **attrs)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_journal(path: str) -> list[dict]:
    """Parse one journal file; corrupt/truncated lines (a process died
    mid-write on a non-line boundary) are skipped, never fatal."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "ts" in rec and "event" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def merge_journals(directory: str) -> list[dict]:
    """All ``events-*.jsonl`` files in ``directory`` merged into one list
    ordered by (ts, source, seq). Rotated segments (``events-x.rNNNN.jsonl``)
    match the same glob and carry the same source + monotonic seq, so they
    interleave back into order with no special casing."""
    records: list[dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "events-*.jsonl"))):
        records.extend(read_journal(path))
    records.sort(key=lambda r: (r["ts"], str(r.get("source", "")),
                                r.get("seq", 0)))
    return records


def write_pod_timeline(directory: str) -> str:
    """Merge every per-process journal in ``directory`` into
    ``pod_timeline.jsonl`` (overwritten whole each call — the merge is
    idempotent, and a partial previous merge must not prefix the new one).
    Returns the timeline path."""
    path = os.path.join(directory, TIMELINE_FILENAME)
    records = merge_journals(directory)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return path
