"""Always-on flight recorder (ISSUE 10 tentpole leg a): bounded, lock-cheap,
jax-free ring buffers holding the most recent high-resolution host state —
the black box the incident plane dumps the moment a detector fires.

Every observability layer before this one is either *aggregated* (metrics:
you know the deadline-expiry COUNT, not which ticks expired whom) or
*unbounded* (journals rotate, but span records are per-request and the
interesting 30 seconds may already be three segments gone). The flight
recorder is the third shape: per-tick / per-request / per-step rows kept in
fixed-size rings, recorded unconditionally, read only when something goes
wrong. Cost discipline:

- **Zero device syncs.** Rows are plain host dicts of values the caller
  already holds (scheduler counters, wall clocks, host floats fetched by an
  existing flush). Handing a ring a device array is a caller bug, same rule
  as the metrics registry.
- **Lock-cheap recording.** A record is one dict build plus one
  ``deque.append`` — appends on a bounded deque are atomic under the GIL,
  so the hot path takes no lock. The registry lock covers ring
  *creation* only (get-or-create, like MetricsRegistry).
- **Bounded by construction.** Each ring holds at most ``capacity`` rows
  (``deque(maxlen=...)``); a month-long serving run holds the same memory
  as a minute-long one.
- **Dumped only on trigger.** Nothing iterates a ring on the metrics
  scrape path or the scheduler path; ``dump()`` runs when an incident
  bundle is assembled (telemetry/incident.py) — the tier-1 drill pins that
  ``/metrics`` never touches a ring.

Standard ring names (shared between recorders and bundle readers so a
bundle's ``flight/engine_tick.jsonl`` means the same thing everywhere):
``TICK_RING`` (continuous-engine per-tick snapshots), ``ROUTING_RING``
(gateway per-request routing decisions), ``STEP_RING`` (trainer per-step
rows), ``LIVENESS_RING`` (elastic-controller liveness events).
"""

from __future__ import annotations

import collections
import threading
import time

from ditl_tpu.annotations import hot_path

__all__ = [
    "ACTION_RING",
    "BULK_RING",
    "FLIGHT_SCHEMA",
    "LIVENESS_RING",
    "ROUTING_RING",
    "STEP_RING",
    "TICK_RING",
    "FlightRecorder",
    "FlightRing",
]

# Stamped into every incident bundle so a reader of an old artifact knows
# which row vocabulary produced it.
FLIGHT_SCHEMA = 1

TICK_RING = "engine_tick"
ROUTING_RING = "gateway_routing"
STEP_RING = "train_step"
LIVENESS_RING = "pod_liveness"
# Autoscale/remediation actions (ISSUE 12): one row when an action is
# planned and one per terminal outcome (executed/refused/failed/dry_run),
# each carrying the triggering signal snapshot — the black-box record that
# makes a bad remediation as diagnosable as the failure it chased.
ACTION_RING = "supervisor_action"
# Bulk-lane dispatch decisions (ISSUE 19): one row per work-item dispatch
# attempt (job, idx, attempt, outcome, tenant) — the ROUTING-ring
# discipline applied to the offline lane, so an incident bundle shows
# exactly which items the lane pushed and what the fleet answered.
BULK_RING = "bulk_dispatch"

DEFAULT_CAPACITY = 512


class FlightRing:
    """One bounded ring of recent rows. ``record`` is the hot path: a dict
    build plus an atomic bounded-deque append — no lock, no allocation
    growth. ``recorded`` counts lifetime rows so a dump can say how many
    rows the ring's horizon dropped."""

    __slots__ = ("name", "capacity", "recorded", "_ring")

    def __init__(self, name: str, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.name = name
        self.capacity = capacity
        self.recorded = 0
        self._ring: collections.deque = collections.deque(maxlen=capacity)

    @hot_path
    def record(self, _ts: float | None = None, **row) -> None:
        """Append one row (stamped with the wall clock unless ``_ts``
        overrides it — callers batching rows from an existing host flush
        backdate them to when the work happened)."""
        row["ts"] = time.time() if _ts is None else _ts
        self._ring.append(row)
        self.recorded += 1

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self) -> list[dict]:
        """Snapshot the ring oldest-first. ``list(deque)`` is one C-level
        pass, safe against concurrent appends (the same snapshot rule
        backlog_retry_after uses on its live deque)."""
        return list(self._ring)


class FlightRecorder:
    """Name -> ring registry for one process. ``ring()`` is get-or-create
    (idempotent per name) so independent call sites — engine tick loop,
    HTTP handlers, the pod controller — share a ring without plumbing
    references, exactly like MetricsRegistry instruments."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rings: dict[str, FlightRing] = {}  # guarded-by: _lock
        self._lock = threading.Lock()  # ring creation only, never records

    def ring(self, name: str, capacity: int | None = None) -> FlightRing:
        # ditl: allow(lock-discipline) -- double-checked fast path: a racy dict read returns either the ring or None (GIL-whole), and None falls through to the locked create
        ring = self._rings.get(name)
        if ring is not None:
            return ring
        with self._lock:
            ring = self._rings.get(name)
            if ring is None:
                ring = FlightRing(name, capacity or self.capacity)
                self._rings[name] = ring
            return ring

    def rings(self) -> dict[str, FlightRing]:
        with self._lock:
            return dict(self._rings)

    def dump_all(self) -> dict[str, list[dict]]:
        """{ring name: rows oldest-first} for every ring that recorded
        anything — the incident bundle's ``flight/`` payload."""
        return {
            name: ring.dump()
            for name, ring in sorted(self.rings().items())
            if len(ring)
        }
