"""Self-contained incident bundles (ISSUE 10 tentpole leg c).

When a detector (telemetry/anomaly.py) fires, the evidence an operator
needs is scattered across process state that is about to be lost: the
flight rings' last few hundred rows, the current /metrics exposition, the
journal tail, the trace records of the affected window. The
:class:`IncidentManager` freezes all of it into ONE directory — the
"diagnosable from one artifact" contract — with the production hygiene a
black box needs:

- **fingerprint dedupe + cooldown**: the same anomaly kind maps to the
  same fingerprint; within ``cooldown_s`` of a bundle for that
  fingerprint, further triggers only bump the suppressed counter. A
  sustained deadline storm produces exactly one bundle, not one per
  detector window (tier-1-pinned).
- **atomic assembly**: every bundle is written into a hidden
  ``.tmp-*`` directory and ``os.rename``d into place whole, so a SIGKILL
  mid-dump never leaves a torn bundle ``--list`` chokes on; stale tmp
  dirs from a killed dump are swept on the next manager construction
  (drilled with a chaos kill at the ``incident.dump`` seam).
- **bounded on disk**: bundles are count-capped and size-capped with
  oldest-first GC, the journal-rotation spirit applied to incident dirs.
- **attributable**: the manifest stamps schema versions, git revision,
  the config snapshot, and — when the chaos plane is armed and has fired —
  the ``injected_fault`` summary, closing the loop between the fault
  plane and the diagnosis plane (a chaos-injected storm reads as such,
  not as an organic mystery).

Bundle layout::

    incident-<utc>-<seq>-<kind>-<fingerprint>/
      incident.json        manifest (trigger, evidence, stamps, file list)
      flight/<ring>.jsonl  flight-ring dumps (telemetry/flight.py)
      metrics.prom         /metrics snapshot at trigger time
      journal_tail.jsonl   last-N merged journal events
      trace_slice.json     Chrome-trace JSON of the affected window
      memwatch.json        HBM top-k (only when a watcher is armed)

Inspect from the CLI (stdlib-only, jax-free like everything here)::

    python -m ditl_tpu.telemetry.incident --dir DIR [--list | --show NAME]

Counters (``ditl_incidents_total``, ``ditl_incidents_suppressed_total``,
``ditl_incidents_trigger_<kind>_total``) land in the caller's registry so
/metrics answers "did anything fire" without listing directories.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import shutil
import threading
import time

from ditl_tpu.telemetry.anomaly import Anomaly
from ditl_tpu.telemetry.flight import FLIGHT_SCHEMA, FlightRecorder
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "INCIDENT_SCHEMA",
    "MANIFEST_NAME",
    "IncidentManager",
    "incidents_total",
    "list_bundles",
    "main",
    "read_bundle",
]

INCIDENT_SCHEMA = 1
MANIFEST_NAME = "incident.json"
_BUNDLE_PREFIX = "incident-"
_TMP_PREFIX = ".tmp-"

_SLUG_RE = re.compile(r"[^a-z0-9_]+")


def _slug(s: str) -> str:
    return _SLUG_RE.sub("_", s.lower()).strip("_") or "unknown"


def _git_rev() -> str:
    """Best-effort HEAD revision (cached): bundles from a fleet must say
    what code produced them; absence (no git, no binary) is recorded as
    "unknown", never an error."""
    global _GIT_REV
    if _GIT_REV is None:
        import subprocess

        rev = "unknown"
        try:
            out = subprocess.run(
                ["git", "rev-parse", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=5,
            )
            if out.returncode == 0:
                rev = out.stdout.strip()
        except (OSError, subprocess.SubprocessError):
            pass
        _GIT_REV = rev
    return _GIT_REV


_GIT_REV: str | None = None

# Process-lifetime bundle count (a plain int, NOT a list of manager
# references — pinning every per-run manager would leak their config
# snapshots and rings for process lifetime), so bench.py can embed ONE
# "incidents this run" count without plumbing managers through fleet
# factories (chaos/plane.py's injected_summary pattern). Bench captures
# the value at run start and embeds the delta, so in-process sweep cells
# never inherit earlier cells' incidents.
_CREATED_TOTAL = 0


def incidents_total() -> int:
    """Bundles assembled by every manager in this process — the number a
    bench row embeds as a run-start delta (0 when no manager was armed,
    so healthy baselines still carry the key for the perf_compare
    gate)."""
    return _CREATED_TOTAL


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            with contextlib.suppress(OSError):
                total += os.path.getsize(os.path.join(root, f))
    return total


class IncidentManager:
    """Assembles fingerprint-deduped, cooldown-rate-limited, size/count-
    capped incident bundles for ONE process. Thread-safe: detectors fire
    from the engine driver, HTTP handlers, and supervisor threads."""

    def __init__(
        self,
        directory: str,
        *,
        flight: FlightRecorder | None = None,
        metrics_render=None,
        journal_dir: str = "",
        registry=None,
        config_snapshot: dict | None = None,
        memwatch_dump=None,
        source: str = "",
        cooldown_s: float = 300.0,
        max_bundles: int = 16,
        max_total_mb: float = 64.0,
        journal_tail: int = 200,
        trace_window_s: float = 30.0,
    ):
        if not directory:
            raise ValueError("IncidentManager needs a directory")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.flight = flight
        self.metrics_render = metrics_render
        self.journal_dir = journal_dir
        self.registry = registry
        self.config_snapshot = config_snapshot
        self.memwatch_dump = memwatch_dump
        self.source = source or f"pid-{os.getpid()}"
        self.cooldown_s = cooldown_s
        self.max_bundles = max(1, max_bundles)
        self.max_total_bytes = int(max_total_mb * 1048576)
        self.journal_tail = max(0, journal_tail)
        self.trace_window_s = trace_window_s
        self.created = 0
        self.suppressed_total = 0  # lifetime, never reset (endpoint-read)
        self.paths: list[str] = []
        self._lock = threading.Lock()
        self._last_fire: dict[str, float] = {}  # guarded-by: _lock
        self._suppressed: dict[str, int] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        if registry is not None:
            self._total = registry.counter(
                "ditl_incidents", "incident bundles assembled")
            self._suppressed_c = registry.counter(
                "ditl_incidents_suppressed",
                "anomaly triggers deduped/cooled down without a bundle")
        else:
            self._total = self._suppressed_c = None
        # Sweep torn tmp dirs a killed dump left behind (the atomic-rename
        # contract's other half): they are invisible to --list already
        # (hidden names), and deleting them keeps the size cap honest.
        # Tmp names carry the writer's pid: a dir whose owner is STILL
        # ALIVE is a peer's in-progress dump (pod workers may share a
        # directory), never swept.
        for name in os.listdir(directory):
            if not name.startswith(_TMP_PREFIX):
                continue
            try:
                owner = int(name[len(_TMP_PREFIX):].split("-", 1)[0])
            except ValueError:
                owner = 0
            if owner and owner != os.getpid():
                try:
                    os.kill(owner, 0)  # signal 0: existence check only
                    continue  # owner alive: an in-progress dump
                except OSError:
                    pass
            with contextlib.suppress(OSError):
                shutil.rmtree(os.path.join(directory, name))

    # -- trigger -----------------------------------------------------------

    def trigger(self, anomaly: Anomaly) -> str | None:
        """Assemble a bundle for ``anomaly`` unless its fingerprint is in
        cooldown. Returns the bundle path, or None when suppressed. Never
        raises — a failed dump is logged and counted, not propagated into
        the loop that detected the anomaly."""
        fp = anomaly.fingerprint()
        with self._lock:
            last = self._last_fire.get(fp)
            if last is not None and anomaly.ts - last < self.cooldown_s:
                self._suppressed[fp] = self._suppressed.get(fp, 0) + 1
                self.suppressed_total += 1
                if self._suppressed_c is not None:
                    self._suppressed_c.inc()
                return None
            self._last_fire[fp] = anomaly.ts
            suppressed_prior = self._suppressed.pop(fp, 0)
            self._seq += 1
            seq = self._seq
        try:
            path = self._assemble(anomaly, fp, seq, suppressed_prior)
        except Exception:  # noqa: BLE001 - diagnosis must not crash work
            logger.exception("incident: bundle assembly failed for %s",
                             anomaly.kind)
            # Roll the cooldown stamp back: a FAILED dump must not burn
            # the window — the next trigger for this fingerprint retries
            # instead of being suppressed against a bundle that does not
            # exist.
            with self._lock:
                if last is None:
                    self._last_fire.pop(fp, None)
                else:
                    self._last_fire[fp] = last
                if suppressed_prior:
                    self._suppressed[fp] = (
                        self._suppressed.get(fp, 0) + suppressed_prior
                    )
            return None
        global _CREATED_TOTAL
        with self._lock:
            self.created += 1
            _CREATED_TOTAL += 1
            self.paths.append(path)
        if self._total is not None:
            self._total.inc()
            if self.registry is not None:
                self.registry.counter(
                    f"ditl_incidents_trigger_{_slug(anomaly.kind)}",
                    f"incident bundles triggered by {anomaly.kind}",
                ).inc()
        logger.warning("incident: %s -> %s", anomaly.kind, path)
        self._gc()
        return path

    # -- assembly ----------------------------------------------------------

    def _assemble(self, anomaly: Anomaly, fp: str, seq: int,
                  suppressed_prior: int) -> str:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(anomaly.ts))
        # The pid keeps names unique when several processes share one
        # directory (pod workers firing the same replicated anomaly in the
        # same second would otherwise collide on the publishing rename);
        # the timestamp prefix keeps the oldest-first GC sort chronological.
        name = (f"{_BUNDLE_PREFIX}{stamp}-{os.getpid()}-{seq:03d}-"
                f"{_slug(anomaly.kind)}-{fp}")
        tmp = os.path.join(self.directory, f"{_TMP_PREFIX}{os.getpid()}-{seq}")
        os.makedirs(tmp, exist_ok=True)
        files: list[str] = []

        def write_json(rel: str, obj) -> None:
            p = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(p) or tmp, exist_ok=True)
            with open(p, "w") as f:
                json.dump(obj, f, indent=2, sort_keys=True, default=str)
            files.append(rel)

        # Flight rings: one JSONL per ring, rows oldest-first.
        if self.flight is not None:
            for ring_name, rows in self.flight.dump_all().items():
                rel = os.path.join("flight", f"{ring_name}.jsonl")
                p = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(p), exist_ok=True)
                with open(p, "w") as f:
                    for row in rows:
                        f.write(json.dumps(row, sort_keys=True,
                                           default=str) + "\n")
                files.append(rel)
        # /metrics snapshot at trigger time.
        if self.metrics_render is not None:
            with contextlib.suppress(Exception):
                body = self.metrics_render()
                with open(os.path.join(tmp, "metrics.prom"), "w") as f:
                    f.write(body if body.endswith("\n") else body + "\n")
                files.append("metrics.prom")
        # Journal tail + trace slice of the affected window.
        if self.journal_dir:
            from ditl_tpu.telemetry.journal import merge_journals
            from ditl_tpu.telemetry.trace_export import to_chrome_trace

            records = merge_journals(self.journal_dir)
            if self.journal_tail:
                tail = records[-self.journal_tail:]
                with open(os.path.join(tmp, "journal_tail.jsonl"), "w") as f:
                    for rec in tail:
                        f.write(json.dumps(rec, sort_keys=True) + "\n")
                files.append("journal_tail.jsonl")
            lo = anomaly.ts - self.trace_window_s
            hi = anomaly.ts + 1.0
            window = [r for r in records if lo <= r.get("ts", 0.0) <= hi]
            write_json("trace_slice.json", to_chrome_trace(window))
        # HBM top-k, when a watcher is armed (training leg).
        if self.memwatch_dump is not None:
            with contextlib.suppress(Exception):
                dump = self.memwatch_dump()
                if dump:
                    write_json("memwatch.json", dump)
        # Collapsed-stack profile (ISSUE 18): when a sampling profiler is
        # armed in this process, the bundle carries what every thread was
        # running around the trigger — the "what code was it" evidence
        # next to the "what happened" rings.
        with contextlib.suppress(Exception):
            from ditl_tpu.telemetry.prof import active_profiler

            prof = active_profiler()
            if prof is not None:
                text = prof.collapsed()
                if text:
                    with open(os.path.join(tmp, "profile.txt"), "w") as f:
                        f.write(text if text.endswith("\n") else text + "\n")
                    files.append("profile.txt")
        # Chaos attribution: when the fault plane is armed AND has fired,
        # the injected-fault summary rides the manifest — a chaos-forced
        # storm must read as injected, not organic.
        injected = None
        with contextlib.suppress(Exception):
            from ditl_tpu.chaos import injected_summary

            summary = injected_summary()
            if summary is not None and summary.get("injected"):
                injected = summary
        manifest = {
            "schema": INCIDENT_SCHEMA,
            "flight_schema": FLIGHT_SCHEMA,
            "name": name,
            "trigger": anomaly.kind,
            "severity": anomaly.severity,
            "fingerprint": fp,
            "ts": anomaly.ts,
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 time.gmtime(anomaly.ts)),
            "detail": anomaly.detail,
            "source": self.source,
            "pid": os.getpid(),
            "suppressed_prior": suppressed_prior,
            "git_rev": _git_rev(),
            "files": None,  # filled below, after every file is written
        }
        if self.config_snapshot is not None:
            manifest["config"] = self.config_snapshot
        if injected is not None:
            manifest["injected_fault"] = injected
        manifest["files"] = sorted(files)
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True, default=str)
        # Chaos seam: a `kill` here dies BETWEEN writing the tmp dir and
        # the publishing rename — the torn-bundle drill (the tmp dir must
        # be invisible to --list and swept by the next manager).
        with contextlib.suppress(Exception):
            from ditl_tpu.chaos import maybe_inject

            maybe_inject("incident.dump")
        final = os.path.join(self.directory, name)
        os.rename(tmp, final)
        return final

    # -- retention ---------------------------------------------------------

    def _gc(self) -> None:
        """Oldest-first GC to the count and size caps (bundle names sort
        chronologically by construction). Never deletes the newest bundle
        — a single over-cap bundle is better evidence than none."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(_BUNDLE_PREFIX)
            )
            while len(names) > self.max_bundles:
                shutil.rmtree(os.path.join(self.directory, names.pop(0)),
                              ignore_errors=True)
            if self.max_total_bytes > 0:
                sizes = [(n, _dir_bytes(os.path.join(self.directory, n)))
                         for n in names]
                total = sum(s for _, s in sizes)
                while total > self.max_total_bytes and len(sizes) > 1:
                    name, size = sizes.pop(0)
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
                    total -= size
        except OSError:
            logger.exception("incident: GC failed (bundles may exceed caps)")


# ---------------------------------------------------------------------------
# Reading side (CLI + /incidents endpoints)
# ---------------------------------------------------------------------------


def read_bundle(path: str) -> dict | None:
    """One bundle's manifest; None when torn/unreadable (a reader must
    skip, never crash — the journal's corrupt-tail rule)."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict) or "trigger" not in manifest:
        return None
    manifest["path"] = path
    return manifest


def list_bundles(directory: str) -> list[dict]:
    """Every readable bundle manifest in ``directory``, oldest first.
    Hidden tmp dirs (mid-assembly or torn by a kill) and unreadable
    bundles are skipped silently."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.startswith(_BUNDLE_PREFIX):
            continue
        manifest = read_bundle(os.path.join(directory, name))
        if manifest is not None:
            out.append(manifest)
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ditl_tpu.telemetry.incident",
        description="list / inspect incident bundles (ISSUE 10)",
    )
    parser.add_argument("--dir", required=True,
                        help="incident directory (bundle dirs inside)")
    parser.add_argument("--list", action="store_true",
                        help="one line per bundle (the default)")
    parser.add_argument("--show", default="",
                        help="print one bundle's manifest JSON by name")
    args = parser.parse_args(argv)

    if args.show:
        manifest = read_bundle(os.path.join(args.dir, args.show))
        if manifest is None:
            print(f"no readable bundle {args.show!r} in {args.dir}")
            return 1
        print(json.dumps(manifest, indent=2, sort_keys=True))
        return 0
    bundles = list_bundles(args.dir)
    if not bundles:
        print(f"no incident bundles in {args.dir}")
        return 0
    for m in bundles:
        injected = " [injected_fault]" if m.get("injected_fault") else ""
        print(f"{m['name']}  {m['iso']}  {m['trigger']} "
              f"({m.get('severity', '?')}){injected}  "
              f"{len(m.get('files') or [])} file(s)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
