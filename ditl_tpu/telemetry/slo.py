"""SLO objectives + multi-window burn-rate evaluation (ISSUE 6 tentpole
leg c). jax-free, zero hot-path cost by construction: instead of
instrumenting request paths, the monitor SAMPLES the cumulative
good/total counts the registry already holds (histogram bucket prefixes,
counters) whenever ``/slo`` or ``/metrics`` is scraped, and computes
windowed error rates from sample deltas — the scrape cadence IS the
sampling cadence, exactly how a Prometheus ``increase()`` would see it.

Definitions (the SRE-workbook shapes):

- An **objective** says "fraction ``target`` of requests must be good",
  where good is e.g. "TTFT <= threshold" or "request completed".
- **Error budget** = ``1 - target``.
- **Burn rate** over a window = (bad fraction in window) / budget. Burn 1.0
  consumes exactly the budget at steady state; 14.4 eats a 30-day budget in
  2 days.
- **Multi-window alerting**: the alert fires only when BOTH the fast and
  the slow window burn above ``burn_alert`` — the fast window gives
  responsiveness, the slow window de-flaps it.

Latency thresholds snap DOWN to the histogram's bucket ladder (the
cumulative bucket prefix is the only count the fixed-bucket histogram can
answer exactly); the effective threshold is reported so a dashboard never
silently grades against a different number than configured.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass
from typing import Callable

__all__ = [
    "BurnRateMonitor",
    "Objective",
    "gateway_slo",
    "serving_slo",
]


@dataclass(frozen=True)
class Objective:
    """One SLO: ``good_total()`` returns the CUMULATIVE (good, total)
    counts; ``threshold_s`` is the effective latency bound (None for
    availability-shaped objectives)."""

    name: str
    target: float
    good_total: Callable[[], tuple[float, float]]
    threshold_s: float | None = None
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r} target must be in (0, 1), got "
                f"{self.target} (a target of 1.0 has zero error budget — "
                "burn rate would be undefined)"
            )


class BurnRateMonitor:
    """Windowed burn-rate evaluation over cumulative counts.

    ``sample()`` appends one (now, {objective: (good, total)}) snapshot;
    ``report()`` samples, then for each window compares the newest snapshot
    against the newest snapshot at least one window old (falling back to
    the oldest held) — the standard counter-delta estimate. Samples older
    than the slow window (plus one guard sample) are pruned, so memory is
    O(scrapes per slow window)."""

    def __init__(
        self,
        objectives: list[Objective],
        *,
        windows: tuple[float, ...] = (300.0, 3600.0),
        burn_alert: float = 1.0,
        registry=None,
        gauge_prefix: str = "ditl_slo",
        journal=None,
        on_alert=None,
    ):
        if not objectives:
            raise ValueError("need at least one objective")
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(f"windows must be positive seconds: {windows}")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.objectives = list(objectives)
        self.windows = tuple(sorted(float(w) for w in windows))
        self.burn_alert = float(burn_alert)
        self._samples: collections.deque = collections.deque()  # guarded-by: _lock
        # Concurrent scrapes (Prometheus on /metrics while a dashboard hits
        # /slo) reach the one shared monitor from different threads — the
        # handler threads of a ThreadingHTTPServer on replicas and the
        # threaded gateway, the offload-pool workers ("gw-offload") on the
        # evloop gateway, where the loop thread itself never runs handler
        # code. Either way two scrapes can overlap and would mutate the
        # deque mid-iteration in report(); sampling is scrape-path only,
        # so a plain lock costs nothing on the serving hot path.
        self._lock = threading.Lock()
        # Optional Prometheus surface: burn-rate gauges set at report()
        # time into the caller's registry, so /metrics carries the same
        # numbers /slo renders (dashboards alert off either).
        self._registry = registry
        self._gauge_prefix = gauge_prefix
        # Alert-transition hooks (ISSUE 10 satellite): burn alerts used to
        # exist only in the scrape response — a headless fleet never
        # recorded them. On the false->true transition of "every window
        # burning" the monitor journals an ``slo.alert`` event (the pod
        # timeline carries the burn even with no Prometheus anywhere) and
        # fires ``on_alert(objective_name, entry)`` — the anomaly plane's
        # trigger hook. Transitions, not levels: a sustained burn journals
        # once until it clears and re-fires.
        self._journal = journal
        self._on_alert = on_alert
        self._alerting: dict[str, bool] = {}  # guarded-by: _lock

    def sample(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        snap = {o.name: o.good_total() for o in self.objectives}
        with self._lock:
            self._sample_locked(now, snap)

    def _sample_locked(self, now: float, snap: dict) -> None:
        self._samples.append((now, snap))
        horizon = now - self.windows[-1]
        # Keep ONE sample at-or-before the horizon as the slow window's
        # baseline; prune the rest.
        while len(self._samples) >= 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()

    @staticmethod
    def _baseline(samples: list, now: float, window: float):
        """Newest sample at least ``window`` old, else the oldest held
        (a short-lived process grades over its whole lifetime)."""
        cutoff = now - window
        base = samples[0]
        for ts, snap in samples:
            if ts <= cutoff:
                base = (ts, snap)
            else:
                break
        return base

    def any_alerting(self) -> bool:
        """True while ANY objective's multi-window alert is firing, as of
        the last report(). The autoscale planner reads this (ISSUE 12): a
        burning fleet must not be scaled down on a momentarily quiet
        pressure signal."""
        with self._lock:
            return any(self._alerting.values())

    def report(self, now: float | None = None) -> dict:
        """Sample, then render the full burn-rate evaluation (the ``/slo``
        endpoint's JSON body)."""
        now = time.time() if now is None else now
        snap = {o.name: o.good_total() for o in self.objectives}
        with self._lock:
            self._sample_locked(now, snap)
            samples = list(self._samples)  # snapshot: evaluate lock-free
        _, newest = samples[-1]
        out: dict = {
            "windows_s": list(self.windows),
            "burn_alert": self.burn_alert,
            "objectives": {},
        }
        for obj in self.objectives:
            good_now, total_now = newest[obj.name]
            entry: dict = {
                "target": obj.target,
                "error_budget": round(1.0 - obj.target, 6),
                "description": obj.description,
                "total": total_now,
                "windows": {},
            }
            if obj.threshold_s is not None:
                entry["threshold_s"] = obj.threshold_s
            burns: list[float | None] = []
            for window in self.windows:
                base_ts, base = self._baseline(samples, now, window)
                good_then, total_then = base[obj.name]
                d_total = total_now - total_then
                d_bad = (total_now - good_now) - (total_then - good_then)
                error_rate = (d_bad / d_total) if d_total > 0 else 0.0
                burn = (
                    error_rate / (1.0 - obj.target) if d_total > 0 else None
                )
                burns.append(burn)
                entry["windows"][f"{int(window)}s"] = {
                    "requests": d_total,
                    "errors": d_bad,
                    "error_rate": round(error_rate, 6),
                    "burn_rate": None if burn is None else round(burn, 4),
                    "span_s": round(now - base_ts, 3),
                }
                if self._registry is not None:
                    self._registry.gauge(
                        f"{self._gauge_prefix}_{obj.name}_burn_rate_w"
                        f"{int(window)}",
                        f"{obj.name} burn rate over {int(window)}s "
                        "(error rate / error budget)",
                    ).set(0.0 if burn is None else burn)
            # Multi-window rule: every window must be measurably burning.
            entry["alerting"] = bool(burns) and all(
                b is not None and b > self.burn_alert for b in burns
            )
            # Atomic check-and-set under the monitor lock: concurrent
            # report() callers (a scrape racing the anomaly monitor's
            # headless cadence) must not BOTH observe the false->true
            # transition and double-fire the journal/hook.
            with self._lock:
                was = self._alerting.get(obj.name, False)
                self._alerting[obj.name] = entry["alerting"]
            if entry["alerting"] and not was:
                if self._journal is not None:
                    self._journal.event(
                        "slo.alert", objective=obj.name,
                        target=obj.target, burn_alert=self.burn_alert,
                        burn_rates=[
                            None if b is None else round(b, 4) for b in burns
                        ],
                        windows_s=list(self.windows),
                    )
                if self._on_alert is not None:
                    try:
                        self._on_alert(obj.name, entry)
                    except Exception:  # noqa: BLE001 - a broken hook must
                        pass  # not break the scrape that evaluated it
            if self._registry is not None:
                self._registry.gauge(
                    f"{self._gauge_prefix}_{obj.name}_alerting",
                    f"1 when every window burns {obj.name}'s budget faster "
                    f"than {self.burn_alert}x",
                ).set(1.0 if entry["alerting"] else 0.0)
            out["objectives"][obj.name] = entry
        return out


def _latency_objective(name: str, hist, threshold_s: float, target: float,
                       description: str) -> Objective:
    """Latency SLO over a fixed-bucket histogram: good = observations in
    buckets whose upper bound <= threshold (snapped down to the ladder)."""
    count, effective = hist.count_le(threshold_s)
    del count
    if effective is None:
        raise ValueError(
            f"{name}: threshold {threshold_s}s is below the histogram's "
            f"first bucket ({hist.buckets[0]}s) — no bucket can answer it"
        )
    return Objective(
        name=name,
        target=target,
        good_total=lambda: (hist.count_le(threshold_s)[0], hist.count),
        threshold_s=effective,
        description=description
        + (f" (threshold snapped {threshold_s}s -> {effective}s)"
           if effective != threshold_s else ""),
    )


def serving_slo(
    metrics,
    *,
    ttft_s: float = 2.5,
    ttft_target: float = 0.95,
    tpot_s: float = 0.25,
    tpot_target: float = 0.95,
    availability_target: float = 0.999,
    windows: tuple[float, ...] = (300.0, 3600.0),
    burn_alert: float = 1.0,
    journal=None,
    on_alert=None,
) -> BurnRateMonitor:
    """The replica server's SLO set over its ``ServingMetrics`` bundle:
    TTFT and TPOT latency objectives (the engine's harvest-observed
    histograms) plus availability (completed vs queue-full 429s and
    deadline 504s — the failures the SERVER owes; client disconnects and
    cancels are the client's doing and don't burn the budget)."""

    def availability() -> tuple[float, float]:
        bad = metrics.queue_full.value + metrics.deadline_expired.value
        good = metrics.completed.value
        return good, good + bad

    return BurnRateMonitor(
        [
            _latency_objective(
                "ttft", metrics.ttft, ttft_s, ttft_target,
                "submit -> first harvested token",
            ),
            _latency_objective(
                "tpot", metrics.decode_token, tpot_s, tpot_target,
                "per-token decode latency",
            ),
            Objective(
                name="availability",
                target=availability_target,
                good_total=availability,
                description="completed vs server-owed failures "
                            "(queue-full 429s, deadline 504s)",
            ),
        ],
        windows=windows,
        burn_alert=burn_alert,
        registry=metrics.registry,
        journal=journal,
        on_alert=on_alert,
    )


def gateway_slo(
    gw_metrics,
    *,
    e2e_s: float = 10.0,
    e2e_target: float = 0.95,
    availability_target: float = 0.999,
    windows: tuple[float, ...] = (300.0, 3600.0),
    burn_alert: float = 1.0,
    journal=None,
    on_alert=None,
) -> BurnRateMonitor:
    """The gateway's fleet-level SLO set: end-to-end relay latency plus
    availability (relayed-to-completion vs fleet-owed failures: saturation
    429s, no-live-replica 503s, mid-stream aborts). Tenant throttles are
    the tenant's budget, not the fleet's, and are excluded on purpose."""

    def availability() -> tuple[float, float]:
        bad = (gw_metrics.saturated.value + gw_metrics.no_replica.value
               + gw_metrics.stream_aborts.value)
        good = gw_metrics.completed.value
        return good, good + bad

    return BurnRateMonitor(
        [
            _latency_objective(
                "e2e", gw_metrics.e2e, e2e_s, e2e_target,
                "gateway receive -> response relayed",
            ),
            Objective(
                name="availability",
                target=availability_target,
                good_total=availability,
                description="relayed-to-completion vs fleet-owed failures "
                            "(saturation 429s, no-replica 503s, stream "
                            "aborts)",
            ),
        ],
        windows=windows,
        burn_alert=burn_alert,
        registry=gw_metrics.registry,
        journal=journal,
        on_alert=on_alert,
    )
