"""Journal -> Chrome-trace/Perfetto JSON export (ISSUE 6 tentpole leg a).

Renders the merged pod timeline plus request spans into the Chrome trace
event format (the JSON Perfetto and ``chrome://tracing`` both open): one
track (``pid``) per journal source — gateway, each replica server, each
elastic worker — with request spans as complete ``"X"`` events nested per
trace lane, and every non-span journal event (engine ticks, chaos
injections, lifecycle events) as an instant ``"i"`` mark on the process
track. This is the artifact the chunked-prefill refactor gets judged
against: "where did THIS request's 900 ms go" becomes a timeline you open,
not a histogram you squint at.

Mapping:

- ``pid``: 1-based index per journal ``source`` (with ``process_name``
  metadata records naming the track after the source); the kernel pid the
  record carries is preserved in ``args.os_pid``.
- ``tid``: spans of one trace share a lane within their source so parents
  visually contain children; untraced instants ride lane 0.
- ``ts``/``dur``: microseconds (Chrome trace unit) from the journal's
  wall-clock seconds.

CLI (stdlib-only, jax-free like everything under telemetry/):

    python -m ditl_tpu.telemetry.trace_export --dir DIR [--trace ID] \
        [--out trace.json] [--list]
"""

from __future__ import annotations

import json
from typing import Iterable

from ditl_tpu.telemetry.journal import merge_journals, read_journal
from ditl_tpu.telemetry.tracing import RESERVED_KEYS

__all__ = [
    "load_trace_records",
    "spans_for_trace",
    "trace_ids",
    "to_chrome_trace",
    "write_chrome_trace",
]


def load_trace_records(directory: str) -> list[dict]:
    """Every journal record in ``directory`` merged into (ts, source, seq)
    order — spans, instants, and ordinary lifecycle events alike."""
    return merge_journals(directory)


def trace_ids(records: Iterable[dict]) -> dict[str, int]:
    """trace_id -> span count, insertion-ordered by first appearance."""
    out: dict[str, int] = {}
    for rec in records:
        if rec.get("event") == "trace.span" and rec.get("trace"):
            out[rec["trace"]] = out.get(rec["trace"], 0) + 1
    return out


def spans_for_trace(records: Iterable[dict], trace_id: str) -> list[dict]:
    """The span records of ONE trace, ordered by (ts, seq) — the merged
    cross-process story of a single request."""
    spans = [
        r for r in records
        if r.get("event") == "trace.span" and r.get("trace") == trace_id
    ]
    spans.sort(key=lambda r: (r["ts"], r.get("seq", 0)))
    return spans


def _args(rec: dict) -> dict:
    """Everything the span layer doesn't own, plus the trace identity —
    Perfetto shows these in the selection panel."""
    out = {k: v for k, v in rec.items() if k not in RESERVED_KEYS}
    for k in ("trace", "span", "parent"):
        if rec.get(k):
            out[k] = rec[k]
    if "pid" in rec:
        out["os_pid"] = rec["pid"]
    return out


def to_chrome_trace(records: Iterable[dict],
                    trace_id: str | None = None) -> dict:
    """Convert journal records to a Chrome trace object. ``trace_id``
    filters spans/instants to one trace while KEEPING untraced process
    events (ticks, lifecycle) — the backdrop a single request's timeline
    is read against."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    lanes: dict[tuple[str, str], int] = {}
    lanes_per_source: dict[str, int] = {}

    def pid_for(source: str) -> int:
        if source not in pids:
            pids[source] = len(pids) + 1
            events.append({
                "name": "process_name", "ph": "M", "ts": 0,
                "pid": pids[source], "tid": 0,
                "args": {"name": source},
            })
        return pids[source]

    def lane_for(source: str, trace: str) -> int:
        key = (source, trace)
        if key not in lanes:
            lanes_per_source[source] = lanes_per_source.get(source, 0) + 1
            lanes[key] = lanes_per_source[source]
        return lanes[key]

    for rec in records:
        event = rec.get("event", "")
        source = str(rec.get("source", "unknown"))
        rec_trace = rec.get("trace", "")
        if trace_id is not None and rec_trace and rec_trace != trace_id:
            continue
        ts_us = float(rec["ts"]) * 1e6
        if event == "trace.span":
            if trace_id is not None and not rec_trace:
                continue
            events.append({
                "name": str(rec.get("name", "span")),
                "cat": "span",
                "ph": "X",
                "ts": ts_us,
                "dur": max(0.0, float(rec.get("dur_s", 0.0))) * 1e6,
                "pid": pid_for(source),
                "tid": lane_for(source, rec_trace or "untraced"),
                "args": _args(rec),
            })
        else:
            name = str(rec.get("name", event) or event)
            tid = (lane_for(source, rec_trace) if rec_trace else 0)
            events.append({
                "name": name,
                "cat": "instant" if event == "trace.instant" else "journal",
                "ph": "i",
                "s": "t" if rec_trace else "p",
                "ts": ts_us,
                "pid": pid_for(source),
                "tid": tid,
                "args": _args(rec),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(directory: str, out_path: str,
                       trace_id: str | None = None) -> str:
    trace = to_chrome_trace(load_trace_records(directory), trace_id)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return out_path


def main(argv: list[str] | None = None) -> int:
    import argparse
    import os

    parser = argparse.ArgumentParser(
        prog="ditl_tpu.telemetry.trace_export",
        description="Render per-process JSONL journals into Chrome-trace/"
                    "Perfetto JSON (open at ui.perfetto.dev)",
    )
    parser.add_argument("--dir", default="",
                        help="journal directory (events-*.jsonl files)")
    parser.add_argument("--out", default="",
                        help="output path (default: <dir>/trace.json)")
    parser.add_argument("--trace", default="",
                        help="filter spans to one trace_id (untraced "
                        "process events are kept as backdrop)")
    parser.add_argument("--journal", default="",
                        help="convert ONE journal/timeline file instead of "
                        "merging --dir (e.g. pod_timeline.jsonl)")
    parser.add_argument("--list", action="store_true",
                        help="list trace ids (span counts) and exit")
    args = parser.parse_args(argv)

    if not args.dir and not args.journal:
        parser.error("one of --dir or --journal is required")
    records = (read_journal(args.journal) if args.journal
               else load_trace_records(args.dir))
    if args.list:
        ids = trace_ids(records)
        if not ids:
            print("no traces found")
        for tid, count in ids.items():
            print(f"{tid}  {count} span(s)")
        return 0
    out = args.out or os.path.join(
        args.dir or os.path.dirname(args.journal), "trace.json")
    trace = to_chrome_trace(records, args.trace or None)
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace['traceEvents'])} event(s) to {out} "
          f"(open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
