"""Generated metrics catalog (ISSUE 10 satellite).

The repo exposes 130+ ``ditl_*`` metric families, and until this module
they lived only in code — scattered across ServingMetrics, GatewayMetrics,
the flattened /v1/stats gauges, the SLO burn gauges, memwatch, and the
incident counters. :data:`CATALOG` is the single source of truth: every
family's exposed name (with ``<placeholder>`` segments for unbounded
labels like replica ids), its Prometheus type, and a one-line meaning.

Two artifacts hang off it:

- ``docs/metrics.md`` is GENERATED from this table
  (``python -m ditl_tpu.telemetry.catalog --write docs/metrics.md``); the
  drift-guard test asserts the doc matches the table byte-for-byte, so a
  stale doc fails CI instead of rotting.
- the drift-guard test (tests/test_metrics_catalog.py) registers the
  families a live server/gateway/training surface actually creates,
  normalizes dynamic label segments with :func:`normalize_family`, and
  asserts live ⊆ catalog AND required-catalog ⊆ live — a new instrument
  without a catalog row (or a catalog row whose instrument was deleted)
  fails the build.

Entries marked ``optional`` are absent on some backends/configurations by
design (memwatch on statless CPU, multi-LoRA gauges without adapters,
overflow tenant labels) — the absent-not-zero rule; they still must
normalize onto a catalog row when they DO appear.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = [
    "CATALOG",
    "CatalogEntry",
    "catalog_families",
    "main",
    "normalize_family",
    "render_markdown",
    "required_families",
]


@dataclass(frozen=True)
class CatalogEntry:
    family: str  # exposed name (classic text format; counters carry _total)
    type: str  # counter | gauge | histogram
    labels: str  # meaning of <placeholder> segments ("" = none)
    meaning: str
    optional: bool = False  # absent on some backends/configs (absent != 0)


# Dynamic-label normalization: a live family name -> its catalog pattern.
# Rules are applied first-match; anything untouched must match a catalog
# row verbatim.
_NORMALIZE_RULES: tuple[tuple[re.Pattern, str], ...] = (
    (re.compile(r"^(ditl_gateway_replica_)(?!deaths_total$)(.+?)_"
                r"(routed_total|retried_total|"
                r"recent_prefix_cache_hit_ratio|prefix_cache_hit_ratio|"
                r"cold_start_seconds)$"),
     r"\1<id>_\3"),
    (re.compile(r"^(ditl_gateway_action_)(.+?)_"
                r"(planned|executed|refused|failed|dry_run)(_total)$"),
     r"\1<kind>_\3\4"),
    (re.compile(r"^(ditl_gateway_tenant_)(.+?)_"
                r"(admitted_total|throttled_total)$"),
     r"\1<tenant>_\3"),
    (re.compile(r"^(ditl_memory_device)\d+_(.+)$"), r"\1<i>_\2"),
    (re.compile(r"^(ditl_memory_)(.+?)_device\d+_(.+)$"),
     r"\1<replica>_device<i>_\3"),
    (re.compile(r"^(ditl_incidents_trigger_).+(_total)$"), r"\1<kind>\2"),
    (re.compile(r"^(ditl_usage_tenant_)(.+?)_(prompt_tokens_total|"
                r"generated_tokens_total|cached_tokens_saved_total|"
                r"device_seconds_total)$"),
     r"\1<tenant>_\3"),
    (re.compile(r"^(ditl_slo_\w+_burn_rate_w)\d+$"), r"\1<window>"),
)


def normalize_family(name: str) -> str:
    """Map a live family name onto its catalog pattern (identity for
    families without dynamic labels)."""
    for rx, rep in _NORMALIZE_RULES:
        if rx.match(name):
            return rx.sub(rep, name)
    return name


# (family, type, labels, meaning[, optional]) — keep sorted by family.
_ROWS: tuple = (
    # Client-side counters live in the remote-LLM client's own process
    # (client_metrics singleton, client/llm.py), never on a server or
    # gateway scrape surface — optional by construction. Found by the
    # static metric-catalog pass (ISSUE 11): the live drift guard only
    # sees scrapeable surfaces, so these had silently escaped the catalog.
    # Adapter plane (ISSUE 16): registry families live on multi-LoRA
    # serving replicas (infer/adapters.py), publish families on the
    # gateway (gateway/publish.py) — optional on every other surface.
    ("ditl_adapter_evictions_total", "counter", "", "adapter rows evicted, drained, and freed back to the pool", True),
    ("ditl_adapter_load_failures_total", "counter", "", "adapter loads refused (verification/geometry/pool exhaustion) or lost to injected faults", True),
    ("ditl_adapter_loads_total", "counter", "", "adapter hot loads committed into stacked pool rows (publications included)", True),
    ("ditl_adapter_publish_fallbacks_total", "counter", "", "fleet publications aborted mid-walk (chaos/crash) - straggler replicas keep the old adapter until a re-publish converges them", True),
    ("ditl_adapter_publish_hops_failed_total", "counter", "", "per-replica publication hops that failed (the replica kept its previous adapter)", True),
    ("ditl_adapter_publishes_total", "counter", "", "fleet-wide adapter publications the gateway coordinated (any outcome)", True),
    ("ditl_adapter_rows", "gauge", "", "stacked pool rows the registry manages (excluding base row 0)", True),
    ("ditl_adapter_rows_live", "gauge", "", "stacked pool rows currently serving a named adapter", True),
    ("ditl_adapter_swap_seconds", "histogram", "", "hot load/publish swap latency (verify -> install -> row live)", True),
    # Bulk lane (ISSUE 19): families live on a bulk-armed gateway only
    # (gateway/bulk.py registers them on the gateway registry when
    # bulk.dir is set) — optional on every other surface.
    ("ditl_bulk_backlog_items", "gauge", "", "bulk work items not yet terminal across non-terminal jobs (the autoscale planner's scale-up signal)", True),
    ("ditl_bulk_completion_tokens_total", "counter", "", "completion tokens generated by the bulk lane", True),
    ("ditl_bulk_items_completed_total", "counter", "", "bulk work items that reached a terminal journal row", True),
    ("ditl_bulk_items_dispatched_total", "counter", "", "bulk work items dispatched through the relay path (attempts, so retries count again)", True),
    ("ditl_bulk_items_failed_total", "counter", "", "bulk work items terminally failed after exhausting retries", True),
    ("ditl_bulk_items_preempted_total", "counter", "", "bulk dispatch attempts bounced by fleet saturation (429) - the lane yielding to interactive load, working as designed", True),
    ("ditl_bulk_items_retried_total", "counter", "", "bulk dispatch attempts retried after a transient outcome", True),
    ("ditl_bulk_jobs_active", "gauge", "", "bulk jobs currently queued or running", True),
    ("ditl_bulk_jobs_cancelled_total", "counter", "", "bulk jobs cancelled by a client", True),
    ("ditl_bulk_jobs_completed_total", "counter", "", "bulk jobs that ran to completion", True),
    ("ditl_bulk_jobs_failed_total", "counter", "", "bulk jobs terminal with at least one permanently failed item", True),
    ("ditl_bulk_jobs_resumed_total", "counter", "", "incomplete bulk jobs resumed from the journal after a gateway restart", True),
    ("ditl_bulk_jobs_submitted_total", "counter", "", "bulk jobs accepted at submit", True),
    ("ditl_bulk_tokens_per_s", "gauge", "", "recent bulk-lane completion tokens/sec (windowed; 0 when the lane is idle)", True),
    ("ditl_client_deadline_exhausted_total", "counter", "", "remote-LLM calls aborted by the total_timeout_s wall-clock bound", True),
    ("ditl_client_requests_total", "counter", "", "remote-LLM logical calls started", True),
    ("ditl_client_retries_total", "counter", "", "remote-LLM HTTP attempts retried (429/5xx/connection errors)", True),
    ("ditl_client_retry_exhausted_total", "counter", "", "remote-LLM calls that failed after exhausting max_retries", True),
    ("ditl_gateway_429_by_class_batch_total", "counter", "", "requests 429 carrying SLO class batch"),
    ("ditl_gateway_429_by_class_best_effort_total", "counter", "", "requests 429 carrying SLO class best_effort"),
    ("ditl_gateway_429_by_class_default_total", "counter", "", "requests 429 carrying SLO class default"),
    ("ditl_gateway_429_by_class_interactive_total", "counter", "", "requests 429 carrying SLO class interactive"),
    ("ditl_gateway_action_<kind>_dry_run_total", "counter", "action kind (scale_up/scale_down/drain/quarantine)", "autoscale/remediation actions planned-but-logged under autoscale.dry_run", True),
    ("ditl_gateway_action_<kind>_executed_total", "counter", "action kind (scale_up/scale_down/drain/quarantine)", "autoscale/remediation actions executed against the fleet", True),
    ("ditl_gateway_action_<kind>_failed_total", "counter", "action kind (scale_up/scale_down/drain/quarantine)", "autoscale/remediation actions that failed mid-execution (also incident-bundled)", True),
    ("ditl_gateway_action_<kind>_planned_total", "counter", "action kind (scale_up/scale_down/drain/quarantine)", "autoscale/remediation actions the planner produced", True),
    ("ditl_gateway_action_<kind>_refused_total", "counter", "action kind (scale_up/scale_down/drain/quarantine)", "autoscale/remediation actions refused at execute time (bounds/state re-check under the fleet-mutation lock)", True),
    ("ditl_gateway_admission_amnesty_total", "counter", "", "tenants admitted with a fresh (full) token bucket after a gateway restart because the recovery manifest had no snapshot for them (ISSUE 20: the counted restart-amnesty fallback)"),
    ("ditl_gateway_affinity_hits_total", "counter", "", "requests routed to the same replica as the previous request with the same affinity key"),
    ("ditl_gateway_affinity_misses_total", "counter", "", "requests whose affinity key landed on a different replica than last time"),
    ("ditl_gateway_cold_start_429_total", "counter", "", "requests answered 429 with a wake-up Retry-After while serving capacity was parked (scale-to-zero admission)", True),
    ("ditl_gateway_fleet_prefix_cache_hit_ratio", "gauge", "", "token-weighted fleet prefix-cache hit ratio - compare against the affinity hit-rate counters"),
    ("ditl_gateway_fleet_recent_prefix_cache_hit_ratio", "gauge", "", "token-weighted fleet prefix-cache hit ratio over the recent health-poll window"),
    ("ditl_gateway_fleet_saturated_total", "counter", "", "requests 429'd because every replica was saturated"),
    ("ditl_gateway_handoff_attempted_total", "counter", "", "requests evaluated by the KV-handoff transfer-cost model"),
    ("ditl_gateway_handoff_declined_total", "counter", "", "handoffs the cost model declined (re-prefill estimated cheaper than the transfer)"),
    ("ditl_gateway_handoff_fallback_total", "counter", "", "accepted handoffs that failed mid-leg and fell back to plain relay (the decode replica re-prefills)"),
    ("ditl_gateway_handoff_shipped_total", "counter", "", "prefill->decode KV handoffs shipped to the decode replica"),
    ("ditl_gateway_hedges_total", "counter", "", "hedged duplicate requests fired"),
    ("ditl_gateway_loop_accept_backlog_drops_total", "counter", "", "client connects refused at accept because gateway.evloop_max_connections was reached (evloop data plane)"),
    ("ditl_gateway_loop_offload_busy_workers", "gauge", "", "offload-pool workers currently running a handler - pinned at pool size while queue wait grows = pool starvation, not a blocked loop"),
    ("ditl_gateway_loop_offload_queue_seconds", "histogram", "", "handler offload queue wait (loop submit -> worker pickup) - grows when the pool, not the loop, is the bottleneck"),
    ("ditl_gateway_loop_offload_workers", "gauge", "", "configured offload-pool size (gateway.evloop_offload_workers; occupancy denominator)"),
    ("ditl_gateway_loop_open_connections", "gauge", "", "client connections currently owned by the evloop data plane (any state)"),
    ("ditl_gateway_loop_open_sse_streams", "gauge", "", "detached SSE relays the event loop is currently pumping (no thread parked per stream)"),
    ("ditl_gateway_loop_ready_queue_depth", "gauge", "", "fds the last selector wakeup reported ready - sustained depth means the loop is the bottleneck"),
    ("ditl_gateway_loop_tick_p95_s", "gauge", "", "p95 event-loop tick over the last 512 ticks - the loop-stall early-warning signal (troubleshooting 35)"),
    ("ditl_gateway_loop_tick_seconds", "histogram", "", "one selector wakeup: dispatch every ready fd + drain the worker mailbox"),
    ("ditl_gateway_no_replica_total", "counter", "", "requests failed with no live replica"),
    ("ditl_gateway_pool_discards", "gauge", "", "pooled upstream connections discarded (stale socket, age/idle cap, mid-request error, or fleet-mutation invalidation; lifetime, stats mirror)"),
    ("ditl_gateway_pool_hits", "gauge", "", "pooled upstream connections reused across relays/polls/probes (lifetime, stats mirror)"),
    ("ditl_gateway_pool_idle", "gauge", "", "idle kept-alive upstream connections currently parked in the pool"),
    ("ditl_gateway_pool_misses", "gauge", "", "upstream hops that had to open a fresh connection (lifetime, stats mirror)"),
    ("ditl_gateway_recovery_adopted_total", "counter", "", "still-alive replica subprocesses adopted (pid + /health vetted) by a --recover incarnation instead of being restarted (ISSUE 20)"),
    ("ditl_gateway_recovery_relaunched_total", "counter", "", "manifest replicas a --recover incarnation could NOT adopt (dead pid or no /health answer) and left for a fresh-port relaunch (ISSUE 20; nonzero on an up-to-date manifest means replicas died with the gateway)"),
    ("ditl_gateway_recovery_runs_total", "counter", "", "gateway crash-recovery passes executed at startup (--recover with a readable manifest, ISSUE 20)"),
    ("ditl_gateway_relayed_by_class_batch_total", "counter", "", "requests relayed carrying SLO class batch"),
    ("ditl_gateway_relayed_by_class_best_effort_total", "counter", "", "requests relayed carrying SLO class best_effort"),
    ("ditl_gateway_relayed_by_class_default_total", "counter", "", "requests relayed carrying SLO class default"),
    ("ditl_gateway_relayed_by_class_interactive_total", "counter", "", "requests relayed carrying SLO class interactive"),
    ("ditl_gateway_replica_<id>_cold_start_seconds", "gauge", "replica id", "measured time-to-first-ready the replica stamped on /health - the scale-to-zero wake-budget input", True),
    ("ditl_gateway_replica_<id>_prefix_cache_hit_ratio", "gauge", "replica id", "measured engine prefix-cache hit ratio of replica r0 (lifetime, from its last health poll)"),
    ("ditl_gateway_replica_<id>_recent_prefix_cache_hit_ratio", "gauge", "replica id", "windowed (last few health polls) prefix-cache hit ratio of replica r0 - the spill-steering input"),
    ("ditl_gateway_replica_<id>_retried_total", "counter", "replica id", "requests retried for replica r0"),
    ("ditl_gateway_replica_<id>_routed_total", "counter", "replica id", "requests routed for replica r0"),
    ("ditl_gateway_replica_deaths_total", "counter", "", "replica died->drain->relaunch cycles the supervisor ran (the anomaly plane's death-rate input, ISSUE 10)"),
    ("ditl_gateway_replicas_active", "gauge", "", "replicas participating in serving (not parked by a scale-down, not quarantined)"),
    ("ditl_gateway_replicas_draining", "gauge", "", "replicas currently draining"),
    ("ditl_gateway_replicas_live", "gauge", "", "replicas currently routable"),
    ("ditl_gateway_replicas_quarantined", "gauge", "", "replicas quarantined by death-storm remediation"),
    ("ditl_gateway_request_e2e_seconds", "histogram", "", "gateway receive -> response relayed"),
    ("ditl_gateway_requests_completed_total", "counter", "", "requests relayed to completion"),
    ("ditl_gateway_requests_total", "counter", "", "requests received by the gateway"),
    ("ditl_gateway_retries_total", "counter", "", "proxy attempts retried on another replica (replica death/busy)"),
    ("ditl_gateway_role_decode_heavy_routed_total", "counter", "", "requests routed on decode_heavy-role replicas"),
    ("ditl_gateway_role_decode_heavy_spilled_total", "counter", "", "requests spilled on decode_heavy-role replicas"),
    ("ditl_gateway_role_hybrid_replicas_live", "gauge", "", "live hybrid-role replicas"),
    ("ditl_gateway_role_hybrid_routed_total", "counter", "", "requests routed on hybrid-role replicas"),
    ("ditl_gateway_role_hybrid_slot_pressure", "gauge", "", "max active_slots/capacity across hybrid-role replicas"),
    ("ditl_gateway_role_hybrid_spilled_total", "counter", "", "requests spilled on hybrid-role replicas"),
    ("ditl_gateway_role_hybrid_tpot_p95_s", "gauge", "", "worst per-replica tpot p95 across hybrid-role replicas (lifetime histograms, health-polled)"),
    ("ditl_gateway_role_hybrid_ttft_p95_s", "gauge", "", "worst per-replica ttft p95 across hybrid-role replicas (lifetime histograms, health-polled)"),
    ("ditl_gateway_role_prefill_heavy_routed_total", "counter", "", "requests routed on prefill_heavy-role replicas"),
    ("ditl_gateway_role_prefill_heavy_spilled_total", "counter", "", "requests spilled on prefill_heavy-role replicas"),
    ("ditl_gateway_routed_by_class_batch_total", "counter", "", "requests routed carrying SLO class batch"),
    ("ditl_gateway_routed_by_class_best_effort_total", "counter", "", "requests routed carrying SLO class best_effort"),
    ("ditl_gateway_routed_by_class_default_total", "counter", "", "requests routed carrying SLO class default"),
    ("ditl_gateway_routed_by_class_interactive_total", "counter", "", "requests routed carrying SLO class interactive"),
    ("ditl_gateway_stream_aborts_total", "counter", "", "streams cut mid-flight by a dying replica (not retryable)"),
    ("ditl_gateway_tenant_<tenant>_admitted_total", "counter", "tenant label", "requests admitted for tenant t0"),
    ("ditl_gateway_tenant_<tenant>_throttled_total", "counter", "tenant label", "requests throttled for tenant t0"),
    ("ditl_gateway_tenant_other_admitted_total", "counter", "overflow label", "admissions for tenants beyond the per-family cap", True),
    ("ditl_gateway_tenant_other_throttled_total", "counter", "overflow label", "throttles for tenants beyond the per-family cap", True),
    ("ditl_gateway_throttled_total", "counter", "", "requests rejected by tenant admission"),
    ("ditl_gateway_up", "gauge", "", "1 when the gateway is scraping"),
    ("ditl_incidents_suppressed_total", "counter", "", "anomaly triggers deduped/cooled down without a bundle"),
    ("ditl_incidents_total", "counter", "", "incident bundles assembled"),
    ("ditl_incidents_trigger_<kind>_total", "counter", "anomaly kind", "incident bundles triggered by serving.deadline_storm"),
    ("ditl_loop_lag_seconds", "histogram", "", "event-loop heartbeat age while busy, watchdog-sampled - how long the loop has been stuck inside one iteration (armed by telemetry.loop_stall_threshold_s)", True),
    ("ditl_loop_stalls_total", "counter", "", "loop stalls the watchdog convicted (lag crossed telemetry.loop_stall_threshold_s; each journals loop.stall with the convicting stack)", True),
    ("ditl_memory_<replica>_device<i>_bytes_in_use", "gauge", "replica id + device index", "replica HBM in use, re-namespaced on the gateway scrape", True),
    ("ditl_memory_<replica>_device<i>_bytes_limit", "gauge", "replica id + device index", "replica HBM limit, re-namespaced on the gateway scrape", True),
    ("ditl_memory_<replica>_device<i>_largest_alloc_size", "gauge", "replica id + device index", "replica largest allocation, re-namespaced on the gateway scrape", True),
    ("ditl_memory_<replica>_device<i>_peak_bytes_in_use", "gauge", "replica id + device index", "replica HBM high-watermark, re-namespaced on the gateway scrape", True),
    ("ditl_memory_device<i>_bytes_in_use", "gauge", "device index", "device 0 allocator bytes_in_use (absent on statless backends)", True),
    ("ditl_memory_device<i>_bytes_limit", "gauge", "device index", "device 0 allocator bytes_limit (absent on statless backends)", True),
    ("ditl_memory_device<i>_largest_alloc_size", "gauge", "device index", "device 0 allocator largest_alloc_size (absent on statless backends)", True),
    ("ditl_memory_device<i>_peak_bytes_in_use", "gauge", "device index", "device 0 allocator peak_bytes_in_use (absent on statless backends)", True),
    ("ditl_prof_samples_total", "counter", "", "wall-clock stack samples the sampling profiler took across all threads (armed by telemetry.prof_hz or /profile)", True),
    ("ditl_prof_stacks", "gauge", "", "distinct collapsed stacks currently held by the sampling profiler (bounded by telemetry.prof_max_stacks)", True),
    ("ditl_prof_stacks_evicted_total", "counter", "", "collapsed stacks evicted oldest-first at the telemetry.prof_max_stacks cap - non-zero means the flame graph has a truncated tail", True),
    ("ditl_serving_adapters", "gauge", "", "LoRA adapters resident (multi-LoRA serving)", True),
    ("ditl_serving_admission_degrade_windows_total", "counter", "", "tick windows that engaged the anti-thrash admission degrade"),
    ("ditl_serving_admission_degraded", "gauge", "", "1 while the optimistic-admission anti-thrash degrade is engaged"),
    ("ditl_serving_admission_degrades", "gauge", "", "lifetime anti-thrash degrade windows (stats mirror)"),
    ("ditl_serving_client_disconnects_total", "counter", "", "in-flight generations cancelled because the client vanished mid-stream"),
    ("ditl_serving_deadline_expired_total", "counter", "", "requests evicted from the queue/slots at their deadline (expired work stops consuming engine ticks)"),
    ("ditl_serving_decode_chunk", "gauge", "", "decode tokens per scheduler tick"),
    ("ditl_serving_decode_token_seconds", "histogram", "", "per-token decode latency (harvest interval / chunk tokens)"),
    ("ditl_serving_draining", "gauge", "", "1 while the server is draining (SIGTERM / rolling restart)"),
    ("ditl_serving_grammar_masked_tokens_total", "counter", "", "generated tokens decoded under an FSM grammar mask"),
    ("ditl_serving_guided_fsm_capacity", "gauge", "", "grammar FSM table rows available"),
    ("ditl_serving_guided_fsm_rows_used", "gauge", "", "grammar FSM table rows in use"),
    ("ditl_serving_guided_grammars_registered", "gauge", "", "distinct grammars registered"),
    ("ditl_serving_host_tier_bytes_used", "gauge", "", "host-RAM tier KV bytes resident", True),
    ("ditl_serving_host_tier_capacity_bytes", "gauge", "", "host-RAM tier size cap (kvtier.host_tier_mb)", True),
    ("ditl_serving_host_tier_corrupt_dropped", "gauge", "", "host-tier entries dropped on crc mismatch (stats mirror)", True),
    ("ditl_serving_host_tier_corrupt_entries_total", "counter", "", "host-tier entries whose crc32 failed at swap-in — detected, dropped, and re-prefilled; never served"),
    ("ditl_serving_host_tier_dropped", "gauge", "", "host-tier spill pages refused at the cap (stats mirror)", True),
    ("ditl_serving_host_tier_dropped_pages_total", "counter", "", "spill pages dropped (tier cap, oversized entry, or an injected kvtier.spill fault)"),
    ("ditl_serving_host_tier_entries", "gauge", "", "host-RAM tier entries resident", True),
    ("ditl_serving_host_tier_evictions_total", "counter", "", "host-tier entries LRU-evicted under the size cap"),
    ("ditl_serving_host_tier_nodes", "gauge", "", "host-tier chain nodes interned (the never-recycled key space)", True),
    ("ditl_serving_host_tier_spilled", "gauge", "", "lifetime pages spilled into the host tier (stats mirror)", True),
    ("ditl_serving_host_tier_spilled_pages_total", "counter", "", "LRU-evicted published pages spilled into the host-RAM tier"),
    ("ditl_serving_host_tier_swap_in_seconds", "histogram", "", "host-tier swap-in latency per admission (crc verify + device_put + republish of the matched run)"),
    ("ditl_serving_host_tier_swapped_in", "gauge", "", "lifetime pages swapped back in from the host tier (stats mirror)", True),
    ("ditl_serving_host_tier_swapped_pages_total", "counter", "", "host-tier pages swapped back into the device pool on an admission miss"),
    ("ditl_serving_inflight", "gauge", "", "HTTP requests currently in flight"),
    ("ditl_serving_interference_max_by_class_batch", "gauge", "", "worst interference stall absorbed by a batch victim (s)", True),
    ("ditl_serving_interference_max_by_class_best_effort", "gauge", "", "worst interference stall absorbed by a best_effort victim (s)", True),
    ("ditl_serving_interference_max_by_class_interactive", "gauge", "", "worst interference stall absorbed by an interactive victim (s)", True),
    ("ditl_serving_interference_max_s", "gauge", "", "largest single prefill-interference stall observed (s)"),
    ("ditl_serving_kv_bytes_per_token", "gauge", "", "KV bytes one token occupies in the page pools - the handoff cost model's size input", True),
    ("ditl_serving_kv_handoff_imports_total", "counter", "", "prefill->decode KV blobs imported by this replica"),
    ("ditl_serving_kv_handoff_rejected_total", "counter", "", "KV handoff blobs rejected (torn/short read, crc mismatch, or geometry mismatch) — reject-don't-install"),
    ("ditl_serving_kv_handoff_tokens_total", "counter", "", "prompt tokens installed from shipped prefill-handoff pages"),
    ("ditl_serving_kv_transfer_imported_bytes", "gauge", "", "lifetime KV handoff bytes imported", True),
    ("ditl_serving_kv_transfer_put_mbps", "gauge", "", "measured device_put bandwidth over KV imports - the handoff cost model's transfer input", True),
    ("ditl_serving_lockstep_speculative", "gauge", "", "1 when lock-step speculative serving is armed"),
    ("ditl_serving_lockstep_speculative_acceptance", "gauge", "", "lock-step speculative acceptance EMA"),
    ("ditl_serving_max_context", "gauge", "", "per-slot KV context cap (tokens)"),
    ("ditl_serving_max_tick_prefill_tokens", "gauge", "", "largest prefill token spend any single tick made"),
    ("ditl_serving_n_slots", "gauge", "", "decode slots"),
    ("ditl_serving_page_size", "gauge", "", "KV page size (tokens)"),
    ("ditl_serving_pages_cached_evictable", "gauge", "", "published prefix pages reclaimable by LRU"),
    ("ditl_serving_pages_free", "gauge", "", "free KV pages"),
    ("ditl_serving_pages_total", "gauge", "", "KV pages in the pool (sentinel excluded)"),
    ("ditl_serving_pod", "gauge", "", "1 on a pod-serving coordinator (tick-broadcast driver)", True),
    ("ditl_serving_preemptions_total", "counter", "", "optimistic-admission preemptions (pages reclaimed mid-flight)"),
    ("ditl_serving_prefill_tok_per_s", "gauge", "", "measured lifetime prefill throughput - the re-prefill side of the handoff cost model", True),
    ("ditl_serving_prefix_cache_evictions_total", "counter", "", "published prefix pages reclaimed by LRU eviction under pool pressure"),
    ("ditl_serving_prefix_cache_hit_ratio", "gauge", "", "measured hit tokens / (hit + miss) tokens — the number the gateway affinity router's score is validated against"),
    ("ditl_serving_prefix_cache_hit_tokens_handoff_total", "counter", "", "prompt tokens reused via the handoff tier (pages shipped by a prefill->decode handoff)"),
    ("ditl_serving_prefix_cache_hit_tokens_hbm_total", "counter", "", "prompt tokens reused via the hbm tier (published pages resident in the device pool)"),
    ("ditl_serving_prefix_cache_hit_tokens_host_total", "counter", "", "prompt tokens reused via the host tier (pages swapped back in from the host-RAM tier)"),
    ("ditl_serving_prefix_cache_hit_tokens_total", "counter", "", "prompt tokens whose KV was reused from the prefix cache at slot admission (paged content-hash match or registered prefix)"),
    ("ditl_serving_prefix_cache_miss_tokens_total", "counter", "", "prompt tokens the engine prefilled because no cached KV covered them"),
    ("ditl_serving_queue_by_class_batch", "gauge", "", "queued batch-class requests"),
    ("ditl_serving_queue_by_class_best_effort", "gauge", "", "queued best_effort-class requests"),
    ("ditl_serving_queue_by_class_interactive", "gauge", "", "queued interactive-class requests"),
    ("ditl_serving_queue_depth", "gauge", "", "requests waiting for a slot"),
    ("ditl_serving_queue_full_total", "counter", "", "submissions rejected QueueFull (HTTP 429)"),
    ("ditl_serving_request_e2e_seconds", "histogram", "", "submit -> request finished"),
    ("ditl_serving_request_queue_wait_seconds", "histogram", "", "submit -> slot admission"),
    ("ditl_serving_request_ttft_batch_seconds", "histogram", "", "TTFT of batch-class requests"),
    ("ditl_serving_request_ttft_best_effort_seconds", "histogram", "", "TTFT of best_effort-class requests"),
    ("ditl_serving_request_ttft_cache_hit_seconds", "histogram", "", "TTFT of requests whose prompt hit the prefix cache (>= 1 reused token)"),
    ("ditl_serving_request_ttft_cache_miss_seconds", "histogram", "", "TTFT of requests whose prompt missed the prefix cache entirely"),
    ("ditl_serving_request_ttft_interactive_seconds", "histogram", "", "TTFT of interactive-class requests"),
    ("ditl_serving_request_ttft_seconds", "histogram", "", "submit -> first generated token harvested"),
    ("ditl_serving_requests_admitted_total", "counter", "", "requests admitted into a slot"),
    ("ditl_serving_requests_completed_total", "counter", "", "requests finished"),
    ("ditl_serving_requests_total", "counter", "", "requests accepted by submit"),
    ("ditl_serving_resume_prefill_tokens", "gauge", "", "tokens re-prefilled resuming preempted requests"),
    ("ditl_serving_slots_busy", "gauge", "", "occupied slots"),
    ("ditl_serving_slots_prefilling", "gauge", "", "slots running chunked prefill"),
    ("ditl_serving_spec_accepted_tokens_total", "counter", "", "speculative drafted tokens accepted by verify forwards"),
    ("ditl_serving_spec_rejected_tokens_total", "counter", "", "speculative drafted tokens rejected by verify forwards"),
    ("ditl_serving_speculative_acceptance_ema", "gauge", "", "measured speculative acceptance EMA (absent until measured)", True),
    ("ditl_serving_speculative_k", "gauge", "", "drafted tokens per speculative round"),
    ("ditl_serving_speculative_plain_step_ms", "gauge", "", "measured plain decode tick cost (absent until measured)", True),
    ("ditl_serving_speculative_rounds_per_tick", "gauge", "", "verify rounds per speculative tick"),
    ("ditl_serving_speculative_spec_round_ms", "gauge", "", "measured speculative round cost (absent until measured)", True),
    ("ditl_serving_speculative_spec_ticks", "gauge", "", "ticks that ran speculatively"),
    ("ditl_serving_speculative_threshold", "gauge", "", "predicted-acceptance threshold for speculating"),
    ("ditl_serving_speculative_ticks", "gauge", "", "ticks counted by the speculation decision path"),
    ("ditl_serving_staged", "gauge", "", "requests staged for the next pod tick broadcast", True),
    ("ditl_serving_token_budget", "gauge", "", "per-tick token budget (0 = unbudgeted)"),
    ("ditl_serving_tokens_generated_total", "counter", "", "tokens generated (all requests)"),
    ("ditl_serving_tpot_interference_batch_seconds", "histogram", "", "per-tick decode delay absorbed by batch-class victims because the tick also ran another request's prefill"),
    ("ditl_serving_tpot_interference_best_effort_seconds", "histogram", "", "per-tick decode delay absorbed by best_effort-class victims because the tick also ran another request's prefill"),
    ("ditl_serving_tpot_interference_interactive_seconds", "histogram", "", "per-tick decode delay absorbed by interactive-class victims because the tick also ran another request's prefill"),
    ("ditl_serving_tpot_interference_seconds", "histogram", "", "per-tick decode delay a victim request absorbed because the tick also ran another request's prefill chunk(s) — the scheduler-interference signal behind chunked-prefill tuning (ISSUE 6)"),
    ("ditl_serving_up", "gauge", "", "1 when the replica server is scraping"),
    ("ditl_slo_availability_alerting", "gauge", "", "1 when every window burns availability's budget faster than 1.0x"),
    ("ditl_slo_availability_burn_rate_w<window>", "gauge", "window seconds", "availability burn rate over 300s (error rate / error budget)"),
    ("ditl_slo_e2e_alerting", "gauge", "", "1 when every window burns e2e's budget faster than 1.0x"),
    ("ditl_slo_e2e_burn_rate_w<window>", "gauge", "window seconds", "e2e burn rate over 300s (error rate / error budget)"),
    ("ditl_slo_tpot_alerting", "gauge", "", "1 when every window burns tpot's budget faster than 1.0x"),
    ("ditl_slo_tpot_burn_rate_w<window>", "gauge", "window seconds", "tpot burn rate over 300s (error rate / error budget)"),
    ("ditl_slo_ttft_alerting", "gauge", "", "1 when every window burns ttft's budget faster than 1.0x"),
    ("ditl_slo_ttft_burn_rate_w<window>", "gauge", "window seconds", "ttft burn rate over 300s (error rate / error budget)"),
    ("ditl_usage_requests_200_total", "counter", "", "terminal requests metered with outcome 200", True),
    ("ditl_usage_requests_429_total", "counter", "", "terminal requests metered with outcome 429", True),
    ("ditl_usage_requests_503_total", "counter", "", "terminal requests metered with outcome 503", True),
    ("ditl_usage_requests_504_total", "counter", "", "terminal requests metered with outcome 504", True),
    ("ditl_usage_requests_adapter_total", "counter", "", "adapter-plane owner-billing flush rows (HBM residency + gather attribution; no client request behind them)", True),
    ("ditl_usage_requests_cancel_total", "counter", "", "terminal requests metered with outcome cancel", True),
    ("ditl_usage_requests_other_total", "counter", "", "terminal requests metered with an out-of-vocabulary outcome", True),
    ("ditl_usage_requests_total", "counter", "", "terminal requests metered by the per-tenant usage meter (ISSUE 15)", True),
    ("ditl_usage_tenant_<tenant>_cached_tokens_saved_total", "counter", "tenant label (overflow folds into `other`)", "prompt tokens served from cached KV (all tiers) attributed to the tenant", True),
    ("ditl_usage_tenant_<tenant>_device_seconds_total", "counter", "tenant label (overflow folds into `other`)", "estimated device-seconds (prefill wall + decode-tick share) attributed to the tenant", True),
    ("ditl_usage_tenant_<tenant>_generated_tokens_total", "counter", "tenant label (overflow folds into `other`)", "generated tokens attributed to the tenant", True),
    ("ditl_usage_tenant_<tenant>_prompt_tokens_total", "counter", "tenant label (overflow folds into `other`)", "prompt tokens attributed to the tenant", True),
)

CATALOG: tuple[CatalogEntry, ...] = tuple(
    CatalogEntry(*row) for row in _ROWS
)


def catalog_families() -> dict[str, CatalogEntry]:
    return {e.family: e for e in CATALOG}


def required_families() -> set[str]:
    """Families the drift guard requires a live run to actually register
    (everything not marked optional)."""
    return {e.family for e in CATALOG if not e.optional}


def render_markdown() -> str:
    """docs/metrics.md, generated whole. Regenerate with
    ``python -m ditl_tpu.telemetry.catalog --write docs/metrics.md``."""
    lines = [
        "# Metrics catalog",
        "",
        "<!-- GENERATED by `python -m ditl_tpu.telemetry.catalog --write "
        "docs/metrics.md` — edit telemetry/catalog.py, not this file. -->",
        "",
        "Every `ditl_*` Prometheus family the system exposes, across the "
        "replica server's `/metrics`, the gateway's `/metrics`, and the "
        "training leg's instruments. `<placeholders>` mark dynamic label "
        "segments sanitized into the family name (the registry is "
        "label-free by design). Families marked *optional* are absent on "
        "some backends or configurations — absent, never zero-valued "
        "lies. The drift-guard test "
        "(tests/test_metrics_catalog.py) pins this table against what a "
        "live run actually registers, in both directions.",
        "",
        "| family | type | dynamic labels | meaning |",
        "|---|---|---|---|",
    ]
    for e in CATALOG:
        meaning = e.meaning + (" *(optional)*" if e.optional else "")
        lines.append(
            f"| `{e.family}` | {e.type} | {e.labels or '—'} | {meaning} |"
        )
    lines.append("")
    lines.append(f"{len(CATALOG)} families "
                 f"({sum(1 for e in CATALOG if not e.optional)} required, "
                 f"{sum(1 for e in CATALOG if e.optional)} optional).")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ditl_tpu.telemetry.catalog",
        description="render / check the generated metrics catalog",
    )
    parser.add_argument("--write", default="",
                        help="write the generated markdown to PATH")
    parser.add_argument("--check", default="",
                        help="exit 1 unless PATH matches the generated "
                        "markdown (the drift guard's doc half)")
    args = parser.parse_args(argv)
    body = render_markdown()
    if args.write:
        with open(args.write, "w") as f:
            f.write(body)
        print(f"wrote {len(CATALOG)} families to {args.write}")
        return 0
    if args.check:
        try:
            with open(args.check) as f:
                current = f.read()
        except OSError as e:
            print(f"error: cannot read {args.check}: {e}")
            return 1
        if current != body:
            print(f"{args.check} is stale — regenerate with "
                  "python -m ditl_tpu.telemetry.catalog --write "
                  f"{args.check}")
            return 1
        print(f"{args.check} matches the catalog")
        return 0
    print(body, end="")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
