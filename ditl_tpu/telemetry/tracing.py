"""End-to-end request tracing (ISSUE 6 tentpole): a jax-free span layer
threaded through every hop a serving request takes — gateway relay (retries
and hedged attempts as sibling spans), ``infer/server.py`` request handling,
and the continuous engine's request lifecycle (queue-wait -> admission ->
prefill chunk(s) -> decode chunks -> harvest -> stream-write).

Span model:

- A **trace** is one client request's end-to-end story, identified by a
  32-hex ``trace_id``. Every process touching the request appends its own
  spans (tagged with that trace_id) to its OWN per-process JSONL journal
  (telemetry/journal.py) — no cross-process coordination, the same rule the
  event journal already follows. ``trace_export.py`` merges by trace_id.
- A **span** is one timed hop (16-hex ``span_id``, optional ``parent``
  span_id). Spans are written as ONE journal line at ``end()`` carrying the
  start ``ts`` and measured ``dur_s`` — a SIGKILLed process loses only its
  open spans, never corrupts closed ones.
- **Propagation** over HTTP rides the W3C ``traceparent`` header
  (``00-<trace_id>-<span_id>-01``): the gateway stamps each relay attempt's
  span context on the upstream request, the replica's server continues the
  trace, and the engine parents its lifecycle spans under the server span —
  so the merged trace nests across process boundaries.
- **Instants** (``trace.instant`` records, e.g. the engine's per-tick
  marker) are zero-duration points on a process's track.

Cost discipline: a ``Tracer`` with no journal is **unarmed** — span writes
are skipped entirely, but span/trace IDs are still generated so propagation
works through an unarmed hop (a gateway without a journal still hands the
replica a coherent trace). All clocks are wall (``time.time``) because the
merged timeline spans processes; durations measured by the caller may come
from monotonic clocks and are passed through as-is.
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass
from typing import Any

from ditl_tpu.telemetry.journal import EventJournal

__all__ = [
    "NULL_TRACER",
    "Span",
    "SpanContext",
    "Tracer",
    "format_traceparent",
    "new_request_id",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "resolve_request_id",
    "sanitize_request_id",
]

TRACEPARENT_HEADER = "traceparent"
REQUEST_ID_HEADER = "X-Request-Id"

# Record keys owned by the span layer / journal; user attrs must not shadow
# them (shadowing would corrupt the export's field contract silently).
RESERVED_KEYS = frozenset(
    {"ts", "seq", "source", "pid", "event", "name", "trace", "span",
     "parent", "dur_s"}
)

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)
_REQUEST_ID_SAFE = re.compile(r"[^A-Za-z0-9._:-]")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def new_request_id() -> str:
    return "req-" + os.urandom(8).hex()


def sanitize_request_id(raw: str | None) -> str | None:
    """A client-supplied X-Request-Id is echoed back verbatim into a
    response HEADER, so it must never smuggle CR/LF (header injection) or
    unbounded bytes: strip to a safe charset, cap the length, and reject
    empty results (the caller then generates one)."""
    if not raw:
        return None
    cleaned = _REQUEST_ID_SAFE.sub("", raw)[:128]
    return cleaned or None


def resolve_request_id(raw: str | None) -> str:
    """The one sanitize-or-generate rule both the gateway and the server
    apply to an incoming ``X-Request-Id`` header."""
    return sanitize_request_id(raw) or new_request_id()


@dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span (what ``traceparent`` carries)."""

    trace_id: str
    span_id: str


def format_traceparent(ctx: "SpanContext | Span") -> str:
    if isinstance(ctx, Span):
        ctx = ctx.context
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: str | None) -> SpanContext | None:
    """Parse a W3C ``traceparent`` header; None on anything malformed
    (wrong version handling per spec: version ff is invalid, other unknown
    versions are accepted on the version-00 field layout). All-zero ids are
    invalid per spec."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id)


class Span:
    """One timed hop. Mutable attrs accumulate via ``annotate`` and are
    written once at ``end()`` (idempotent — the first end wins)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "attrs",
                 "_tracer", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: str, t0: float, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs
        self._tracer = tracer
        self._ended = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)

    def annotate(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs: Any) -> None:
        """Instant child event on this span's trace."""
        self._tracer.instant(name, parent=self, **attrs)

    def end(self, t_end: float | None = None, **attrs: Any) -> None:
        """Write the span (one journal line). ``t_end`` overrides the end
        wall clock (callers that measured the hop on a monotonic clock pass
        ``t0 + measured_dur``). Safe to call twice — only the first writes."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self._tracer._write_span(self, t_end if t_end is not None
                                 else time.time())

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.attrs.setdefault("error", type(exc).__name__)
        self.end()


class Tracer:
    """Span factory over one process's ``EventJournal``. ``journal=None``
    leaves the tracer unarmed: spans still mint real ids (propagation keeps
    working through an unarmed hop) but nothing is written."""

    def __init__(self, journal: EventJournal | None = None):
        self.journal = journal

    @property
    def armed(self) -> bool:
        return self.journal is not None

    def start_span(
        self,
        name: str,
        parent: "Span | SpanContext | None" = None,
        *,
        trace_id: str | None = None,
        t0: float | None = None,
        **attrs: Any,
    ) -> Span:
        """Open a span. ``parent`` chains it (and inherits the trace);
        ``trace_id`` forces a trace for parentless spans; neither ->
        a fresh trace (this span is the root). ``t0`` backdates the start
        (wall clock) for spans created after the work they describe."""
        if parent is not None:
            p_trace = parent.trace_id
            p_span = parent.span_id
        else:
            p_trace = trace_id or new_trace_id()
            p_span = ""
        bad = RESERVED_KEYS.intersection(attrs)
        if bad:
            raise ValueError(f"span attrs shadow reserved keys: {sorted(bad)}")
        return Span(
            self, name, p_trace, new_span_id(), p_span,
            time.time() if t0 is None else float(t0), dict(attrs),
        )

    def instant(
        self,
        name: str,
        parent: "Span | SpanContext | None" = None,
        **attrs: Any,
    ) -> None:
        """Zero-duration point event on this process's track; with
        ``parent`` it is tagged onto that span's trace."""
        if self.journal is None:
            return
        bad = RESERVED_KEYS.intersection(attrs)
        if bad:
            raise ValueError(f"instant attrs shadow reserved keys: "
                             f"{sorted(bad)}")
        rec: dict[str, Any] = {"name": name, **attrs}
        if parent is not None:
            rec["trace"] = parent.trace_id
            rec["parent"] = parent.span_id
        self.journal.event("trace.instant", **rec)

    def _write_span(self, span: Span, t_end: float) -> None:
        if self.journal is None:
            return
        self.journal.event(
            "trace.span",
            _ts=span.t0,
            name=span.name,
            trace=span.trace_id,
            span=span.span_id,
            parent=span.parent_id,
            dur_s=round(max(0.0, t_end - span.t0), 6),
            **span.attrs,
        )


NULL_TRACER = Tracer(None)
