"""Loss + jitted/sharded train and eval steps (L1/L5).

The reference's intended-but-dead loss loop (``process_batch``, ref
``src/utils.py:12-23``) fabricated random logits and cross-entropied them
against sentiment labels, never updating anything. Here the step is real:
next-token cross-entropy over the local model, value_and_grad, optax update —
compiled once with ``jax.jit`` against explicit NamedShardings so GSPMD emits
the DP gradient all-reduce / FSDP all-gather+reduce-scatter / TP collectives
implied by the mesh, and donated so state is updated in place in HBM.

Gradient accumulation (``TrainConfig.grad_accum_steps``) runs microbatches
through ``lax.scan`` inside the compiled step — device-resident, no host
round-trips between microbatches.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ditl_tpu.config import ModelConfig, TrainConfig
from ditl_tpu.models import llama
from ditl_tpu.parallel.sharding import DEFAULT_RULES, named_sharding_tree
from ditl_tpu.train.state import TrainState, make_optimizer, state_logical_axes

__all__ = [
    "loss_fn",
    "make_train_step",
    "make_multi_step",
    "make_eval_step",
    "batch_logical_axes",
]


def loss_fn(
    params: Any,
    batch: dict[str, jax.Array],
    cfg: ModelConfig,
    *,
    mesh=None,
    rules=None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Masked next-token cross-entropy (float32 logits), plus the MoE router
    load-balancing aux term when the model is sparse.

    ``cfg.loss_impl == "fused"`` routes through the blockwise fused
    lm-head+CE (ops/fused_ce.py) — same value, no (B, S, V) logits tensor."""
    if cfg.loss_impl not in ("naive", "fused"):
        raise ValueError(f"unknown loss_impl {cfg.loss_impl!r} (naive|fused)")
    targets = batch["input_ids"][:, 1:]
    mask = batch["loss_mask"][:, 1:].astype(jnp.float32)
    n_tokens = jnp.maximum(mask.sum(), 1.0)
    fused = cfg.loss_impl == "fused"
    out, aux = llama.forward(
        params,
        batch["input_ids"],
        cfg,
        positions=batch.get("positions"),
        segment_ids=batch.get("segment_ids"),
        mesh=mesh,
        rules=rules,
        with_aux=True,
        return_hidden=fused,
    )
    if fused:
        from ditl_tpu.ops.fused_ce import fused_cross_entropy

        d = out.shape[-1]
        nll_sum = fused_cross_entropy(
            out[:, :-1].reshape(-1, d),
            llama.head_weights(params, cfg),
            targets.reshape(-1).astype(jnp.int32),
            mask.reshape(-1),
            block_tokens=cfg.loss_block_tokens,
            compute_dtype=jnp.dtype(cfg.dtype),
        )
        ce = nll_sum / n_tokens
    else:
        logits = out[:, :-1]
        logz = jax.nn.logsumexp(logits, axis=-1)
        target_logit = jnp.take_along_axis(
            logits, targets[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        nll = (logz - target_logit) * mask
        ce = nll.sum() / n_tokens
    loss = ce + cfg.router_aux_coef * aux if cfg.num_experts > 0 else ce
    return loss, {"loss": ce, "n_tokens": mask.sum()}


def batch_logical_axes(example_batch: dict[str, Any]) -> dict[str, tuple]:
    return {k: ("batch",) + (None,) * (v.ndim - 1) for k, v in example_batch.items()}


def _build_step_fn(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    mesh,
    rules: dict,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """The un-jitted single train step (loss -> grads -> optax update)."""
    tx = None

    def get_tx(params):
        nonlocal tx
        if tx is None:
            tx = make_optimizer(train_cfg, params)
        return tx

    accum = train_cfg.grad_accum_steps

    def single_loss(params, batch):
        # Cast float32 master params to the compute dtype ONCE per step:
        # per-use casts inside the layers re-read the 4-byte masters at
        # every matmul (fwd and bwd), costing ~2% step time at 350M on v5e.
        # Gradients flow back through the cast (bf16 cotangents cast to
        # f32), which is the precision the bf16 matmuls produced anyway —
        # measured loss parity in BASELINE.md.
        cd = jnp.dtype(model_cfg.dtype)
        if cd != jnp.float32:
            def cast(path, p):
                # Norm scales stay f32: the model contract computes norms in
                # float32 (llama.rms_norm) and they never pass through a
                # matmul, so rounding them would be a pure precision loss —
                # and would make train numerics diverge from eval's.
                if any(getattr(k, "key", None) and "norm" in k.key for k in path):
                    return p
                return p.astype(cd) if p.dtype == jnp.float32 else p

            params = jax.tree_util.tree_map_with_path(cast, params)
        return loss_fn(params, batch, model_cfg, mesh=mesh, rules=rules)

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        tx = get_tx(state.params)
        if accum > 1:
            # (B, ...) -> (accum, B/accum, ...): scan microbatches on device.
            micro = jax.tree.map(
                lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]), batch
            )

            def micro_step(carry, mb):
                grads_acc, loss_acc, tok_acc = carry
                (loss, aux), grads = jax.value_and_grad(single_loss, has_aux=True)(
                    state.params, mb
                )
                grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                return (grads_acc, loss_acc + loss, tok_acc + aux["n_tokens"]), None

            zero_grads = jax.tree.map(jnp.zeros_like, state.params)
            (grads, loss_sum, tokens), _ = jax.lax.scan(
                micro_step, (zero_grads, 0.0, 0.0), micro
            )
            grads = jax.tree.map(lambda g: g / accum, grads)
            loss = loss_sum / accum
        else:
            (loss, aux), grads = jax.value_and_grad(single_loss, has_aux=True)(
                state.params, batch
            )
            tokens = aux["n_tokens"]
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(
            lambda p, u: (p + u.astype(p.dtype)), state.params, updates
        )
        grad_norm = optax_global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt)
        metrics = {"loss": loss, "n_tokens": tokens, "grad_norm": grad_norm}
        if train_cfg.fault_nan_step > 0:
            # Anomaly-plane drill (ISSUE 10): a real device NaN in the
            # REPORTED loss at exactly this step — it rides the compiled
            # metrics to the host flush like a genuine divergence would,
            # without perturbing gradients or parameters.
            metrics["loss"] = jnp.where(
                new_state.step == train_cfg.fault_nan_step,
                jnp.nan, metrics["loss"],
            )
        return new_state, metrics

    return step


def _shardings_for(model_cfg, train_cfg, mesh, example_batch, rules):
    from jax.sharding import NamedSharding, PartitionSpec as P

    state_shardings = named_sharding_tree(
        mesh, state_logical_axes(model_cfg, train_cfg), rules
    )
    batch_shardings = named_sharding_tree(mesh, batch_logical_axes(example_batch), rules)
    replicated = NamedSharding(mesh, P())
    metric_shardings = {
        "loss": replicated, "n_tokens": replicated, "grad_norm": replicated
    }
    return state_shardings, batch_shardings, metric_shardings


def make_train_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    mesh,
    example_batch: dict[str, Any],
    rules: dict | None = None,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the compiled train step with explicit in/out shardings. When the
    mesh has a pipeline axis (stage > 1), the stage-sharded rule table is
    selected automatically (parallel/pipeline.py)."""
    rules = rules if rules is not None else _default_rules(mesh)
    step = _build_step_fn(model_cfg, train_cfg, mesh, rules)
    state_sh, batch_sh, metric_sh = _shardings_for(
        model_cfg, train_cfg, mesh, example_batch, rules
    )
    return jax.jit(
        step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, metric_sh),
        donate_argnums=(0,),
    )


def make_multi_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    mesh,
    example_batch: dict[str, Any],
    n_steps: int,
    rules: dict | None = None,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Compiled ``n_steps`` optimizer steps per call: a ``lax.scan`` over a
    stacked batch window, so the device runs autonomously for the whole window
    with zero host dispatch between steps.

    Host-side per-step dispatch is pure overhead on TPU (the device idles
    while the host round-trips; tens of ms/step through remote transports).
    The reference's per-example host loop (ref
    ``src/distributed_inference.py:64-69``) is the extreme version of that
    anti-pattern. Input batches are stacked on a leading window dim
    ``(n_steps, B, ...)``; returned metrics carry the same leading dim (the
    caller logs the last row / aggregates)."""
    rules = rules if rules is not None else _default_rules(mesh)
    step = _build_step_fn(model_cfg, train_cfg, mesh, rules)

    def multi(state: TrainState, batches: dict) -> tuple[TrainState, dict]:
        return jax.lax.scan(step, state, batches)

    state_sh, batch_sh, metric_sh = _shardings_for(
        model_cfg, train_cfg, mesh, example_batch, rules
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    def window(sh):
        return jax.tree.map(lambda s: NamedSharding(mesh, P(None, *s.spec)), sh)

    return jax.jit(
        multi,
        in_shardings=(state_sh, window(batch_sh)),
        out_shardings=(state_sh, window(metric_sh)),
        donate_argnums=(0,),
    )


def _default_rules(mesh) -> dict:
    if mesh is not None and mesh.shape.get("stage", 1) > 1:
        from ditl_tpu.parallel.pipeline import PIPELINE_RULES

        return PIPELINE_RULES
    return DEFAULT_RULES


def optax_global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def make_eval_step(model_cfg: ModelConfig, mesh, rules: dict | None = None):
    """Compiled forward-only step returning per-batch mean NLL."""
    rules = rules if rules is not None else _default_rules(mesh)

    @jax.jit
    def eval_step(params, batch):
        loss, aux = loss_fn(params, batch, model_cfg, mesh=mesh, rules=rules)
        return aux

    return eval_step
