"""Adapter-only checkpoint export (ISSUE 16): the trainer half of the
train->serve publication protocol.

A LoRA fine-tune's publishable state is just the adapter leaves — a few MB
next to the frozen base — so publication does NOT ride the full orbax
checkpoint: :func:`export_adapter` device_gets only the ``lora`` subtree
and commits it in the :mod:`ditl_tpu.utils.adapterfmt` layout (npz + meta
+ PR 5-style crc manifest, manifest last, atomic ``LATEST`` pointer). A
gateway publisher polling ``<publish_dir>/<name>/LATEST`` then verifies
and fans the version out to a live fleet (gateway/publish.py) with no
restart and no torn reads: a SIGKILL mid-export leaves either the old
LATEST or a complete new version.

Wired into the train loop via ``adapter.publish_dir`` /
``adapter.publish_every`` (config.AdapterConfig); callable directly for
offline export of any params tree that carries a lora subtree.
"""

from __future__ import annotations

import os
from typing import Any

import jax

from ditl_tpu.utils import adapterfmt
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["export_adapter", "lora_host_arrays"]


def lora_host_arrays(params: dict[str, Any]) -> dict[str, Any]:
    """The flat ``target.leaf`` -> host ndarray view of a params tree's
    adapter leaves (single-adapter (L, d, r) trees only — a stacked
    serving pool is not a publishable training artifact)."""
    lora = (params.get("layers") or {}).get("lora")
    if not lora:
        raise ValueError("params tree carries no layers/lora subtree")
    flat: dict[str, Any] = {}
    for target in sorted(lora):
        for leaf in sorted(lora[target]):
            arr = lora[target][leaf]
            if getattr(arr, "ndim", 0) != 3:
                raise ValueError(
                    f"lora leaf {target}.{leaf} has ndim "
                    f"{getattr(arr, 'ndim', None)}, want 3 (L, ., .) — "
                    f"stacked multi-adapter trees are a serving artifact, "
                    f"not an exportable adapter")
            flat[f"{target}.{leaf}"] = arr
    import numpy as np

    return {k: np.asarray(v) for k, v in
            zip(flat, jax.device_get(list(flat.values())))}


def export_adapter(publish_dir: str, name: str, step: int,
                   params: dict[str, Any], cfg) -> str:
    """Commit ``params``' adapter leaves as version
    ``<publish_dir>/<name>/step_<N>`` and flip the ``LATEST`` pointer.
    Returns the committed version dir."""
    arrays = lora_host_arrays(params)
    root = os.path.join(publish_dir, name)
    version = os.path.join(root, f"step_{int(step):08d}")
    adapterfmt.write_adapter_dir(
        version, name=name, step=step, arrays=arrays,
        meta={
            "lora_rank": cfg.lora_rank,
            "lora_alpha": cfg.lora_alpha,
            "targets": sorted({k.split(".", 1)[0] for k in arrays}),
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "dtype": str(cfg.param_dtype),
        },
    )
    adapterfmt.write_latest(root, version)
    logger.info("exported adapter %s step %d -> %s", name, step, version)
    return version
