"""Metrics / observability (SURVEY.md §5).

The reference logs per-example prompt/response/label lines on rank 0 (ref
``src/distributed_inference.py:71-76``). Here the unit of observability is the
train step, and the headline numbers are the BASELINE.json metrics:
**tokens/sec/chip** and **step-time p50**. Device metrics arrive as jax.Arrays;
they are only synced to host at ``log_every`` boundaries so the metric path
never stalls the device pipeline — and that boundary sync is ONE
``jax.device_get`` over every pending step's metrics, not one transfer per
step or per key.

Per-step phase breakdown (ISSUE 3): each flushed JSONL row carries
``data_wait_s`` (host time blocked on the data pipeline, passed in by the
trainer) and ``dispatch_s`` (host wall inside the step call — dispatch is
async, so this is host work, not device time); each flush records its own
blocking-sync wall as ``sync_s`` on the row that triggered it. The summary
totals the three, which is where "where did the wall clock go" starts before
the goodput report (telemetry/goodput.py) finishes it.
"""

from __future__ import annotations

import json
import statistics
import time
from typing import Any

from ditl_tpu.annotations import hot_path
from ditl_tpu.runtime.distributed import is_coordinator
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["MetricsLogger"]


class MetricsLogger:
    def __init__(
        self,
        log_every: int = 10,
        n_chips: int | None = None,
        metrics_file: str = "",
        anatomy=None,
        on_host_metrics=None,
    ):
        """``metrics_file``: optional coordinator-only JSONL scalar stream
        (one object per STEP WINDOW — every pending entry is written at each
        flush, not just the newest; the flush used to drop all interior
        steps of a log_every window, ISSUE 3 satellite) — the
        TensorBoard-scalar equivalent without a TF dependency; any dashboard
        can tail it.

        ``anatomy``: optional ``telemetry.perf.StepAnatomy`` fed from the
        same phase clocks this logger already keeps (ISSUE 7): ``data_wait``
        and ``host_dispatch`` at each end_step, ``device_compute`` at each
        flush sync — the trainer adds the matching wall spans and the
        checkpoint bucket.

        ``on_host_metrics``: optional ``(step, host_dict, step_time_s)``
        callback invoked once per flushed window AFTER the flush's own
        bookkeeping completes (ISSUE 10) — the one place loss/grad_norm
        are already host floats, so the anomaly plane's training
        detectors ride the existing log_every sync and add ZERO blocking
        transfers (tier-1-pinned). A callback exception (the non-finite
        crash) propagates only after the pending queue is cleared, so the
        close() flush never re-syncs."""
        import jax

        self.anatomy = anatomy
        self.on_host_metrics = on_host_metrics
        self.log_every = max(1, log_every)
        self.n_chips = n_chips if n_chips is not None else jax.device_count()
        self.step_times: list[float] = []
        self.tokens_per_sec_chip: list[float] = []
        self._last_t: float | None = None
        # (step, metrics, n_steps, dt, data_wait_s) per un-flushed window.
        self._pending: list[tuple[int, Any, int, float | None, float]] = []
        self._metrics_fh = None
        # Phase totals (host wall seconds) across the run.
        self.data_wait_s = 0.0
        self.dispatch_s = 0.0
        self.sync_s = 0.0
        if metrics_file and is_coordinator():
            self._metrics_fh = open(metrics_file, "a", buffering=1)

    @hot_path
    def start_step(self) -> None:
        self._last_t = time.perf_counter()

    @hot_path
    def end_step(
        self, step: int, device_metrics: Any, n_steps: int = 1,
        data_wait_s: float = 0.0, excluded_s: float = 0.0,
    ) -> None:
        """Record wall time; stash device metrics without forcing a sync.
        ``n_steps > 1`` when one call ran a whole compiled step window
        (train/step.make_multi_step): wall time is divided per step, and
        ``device_metrics['n_tokens']`` is expected to cover the window.
        ``data_wait_s``: host time spent waiting on the data pipeline for
        this window (phase breakdown column). ``excluded_s``: wall inside
        the start/end interval that belongs to another accounting bucket
        (the trainer passes its measured profiler work) — subtracted from
        the ANATOMY's host_dispatch feed so conservation against the
        profiler-excluded wall holds; the phase columns keep the historical
        full-interval semantics."""
        now = time.perf_counter()
        dt = None
        if self._last_t is not None:
            dt = (now - self._last_t) / max(1, n_steps)
            self.step_times.append(dt)
            self.dispatch_s += now - self._last_t
            if self.anatomy is not None:
                self.anatomy.add(
                    "host_dispatch", now - self._last_t - excluded_s
                )
        self._last_t = None
        self.data_wait_s += data_wait_s
        if self.anatomy is not None:
            self.anatomy.add("data_wait", data_wait_s)
        self._pending.append(
            (step, device_metrics, max(1, n_steps), dt, data_wait_s)
        )
        if step % self.log_every < n_steps:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        import jax

        # ONE blocking transfer for every pending window's metrics — the
        # only device sync on the metrics path, and its wall time is the
        # "device-blocked" phase (the host catching up to the async-
        # dispatched step stream).
        t0 = time.perf_counter()
        host_all = jax.device_get([m for _, m, _, _, _ in self._pending])
        sync_s = time.perf_counter() - t0
        self.sync_s += sync_s
        if self.anatomy is not None:
            self.anatomy.add("device_compute", sync_s)
        last_i = len(self._pending) - 1
        flushed: list[tuple[int, dict, float]] = []
        for i, (step, _, n_steps, dt, data_wait_s) in enumerate(self._pending):
            host = {k: float(v) for k, v in host_all[i].items()}
            if dt is None:
                continue
            flushed.append((step, host, dt))
            tps_chip = host.get("n_tokens", 0.0) / (dt * n_steps) / self.n_chips
            self.tokens_per_sec_chip.append(tps_chip)
            if i == last_i and is_coordinator():
                logger.info(
                    "step %d: loss=%.4f grad_norm=%.3f step_time=%.3fs "
                    "tokens/sec/chip=%.1f",
                    step,
                    host.get("loss", float("nan")),
                    host.get("grad_norm", float("nan")),
                    dt,
                    tps_chip,
                )
            if self._metrics_fh is not None:
                row = {
                    "step": step,
                    "step_time_s": round(dt, 6),
                    "tokens_per_sec_per_chip": round(tps_chip, 2),
                    "data_wait_s": round(data_wait_s, 6),
                    "dispatch_s": round(dt * n_steps, 6),
                    **{k: round(v, 6) for k, v in host.items()},
                }
                if i == last_i:
                    # The sync belongs to the flush, not any single step;
                    # carried on the row that triggered it.
                    row["sync_s"] = round(sync_s, 6)
                self._metrics_fh.write(json.dumps(row, sort_keys=True) + "\n")
        self._pending.clear()
        if self.on_host_metrics is not None:
            # After clear(): a callback that raises (the non-finite-loss
            # crash, ISSUE 10) must not leave pending rows for close() to
            # re-flush — that would add a second blocking transfer.
            for step, host, dt in flushed:
                self.on_host_metrics(step, host, dt)

    def close(self) -> None:
        self.flush()
        if self._metrics_fh is not None:
            self._metrics_fh.close()
            self._metrics_fh = None

    def phase_totals(self) -> dict[str, float]:
        """Cumulative host-wall phase breakdown: data-wait / host dispatch /
        device-blocked (flush sync)."""
        return {
            "data_wait_s": round(self.data_wait_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "device_blocked_s": round(self.sync_s, 6),
        }

    def summary(self) -> dict[str, float]:
        """BASELINE.md numbers. p50 over steps after compile warm-up."""
        times = self.step_times[1:] if len(self.step_times) > 1 else self.step_times
        tps = self.tokens_per_sec_chip[1:] if len(self.tokens_per_sec_chip) > 1 else self.tokens_per_sec_chip
        out: dict[str, float] = {}
        if times:
            out["step_time_p50_s"] = statistics.median(times)
        if tps:
            out["tokens_per_sec_per_chip_p50"] = statistics.median(tps)
        out.update({f"phase_{k}": v for k, v in self.phase_totals().items()})
        return out

    def summary_json(self) -> str:
        return json.dumps(self.summary(), sort_keys=True)
