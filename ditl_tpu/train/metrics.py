"""Metrics / observability (SURVEY.md §5).

The reference logs per-example prompt/response/label lines on rank 0 (ref
``src/distributed_inference.py:71-76``). Here the unit of observability is the
train step, and the headline numbers are the BASELINE.json metrics:
**tokens/sec/chip** and **step-time p50**. Device metrics arrive as jax.Arrays;
they are only synced to host at ``log_every`` boundaries so the metric path
never stalls the device pipeline.
"""

from __future__ import annotations

import json
import statistics
import time
from typing import Any

from ditl_tpu.runtime.distributed import is_coordinator
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["MetricsLogger"]


class MetricsLogger:
    def __init__(
        self,
        log_every: int = 10,
        n_chips: int | None = None,
        metrics_file: str = "",
    ):
        """``metrics_file``: optional coordinator-only JSONL scalar stream
        (one ``{"step": ..., "loss": ..., ...}`` object per flush) — the
        TensorBoard-scalar equivalent without a TF dependency; any dashboard
        can tail it."""
        import jax

        self.log_every = max(1, log_every)
        self.n_chips = n_chips if n_chips is not None else jax.device_count()
        self.step_times: list[float] = []
        self.tokens_per_sec_chip: list[float] = []
        self._last_t: float | None = None
        self._pending: list[tuple[int, Any, int]] = []  # (step, metrics, n_steps)
        self._metrics_fh = None
        if metrics_file and is_coordinator():
            self._metrics_fh = open(metrics_file, "a", buffering=1)

    def start_step(self) -> None:
        self._last_t = time.perf_counter()

    def end_step(self, step: int, device_metrics: Any, n_steps: int = 1) -> None:
        """Record wall time; stash device metrics without forcing a sync.
        ``n_steps > 1`` when one call ran a whole compiled step window
        (train/step.make_multi_step): wall time is divided per step, and
        ``device_metrics['n_tokens']`` is expected to cover the window."""
        now = time.perf_counter()
        if self._last_t is not None:
            self.step_times.append((now - self._last_t) / max(1, n_steps))
        self._last_t = None
        self._pending.append((step, device_metrics, max(1, n_steps)))
        if step % self.log_every < n_steps:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        step, metrics, n_steps = self._pending[-1]
        host = {k: float(v) for k, v in metrics.items()}  # device sync point
        if self.step_times:
            dt = self.step_times[-1]
            tps_chip = host.get("n_tokens", 0.0) / (dt * n_steps) / self.n_chips
            self.tokens_per_sec_chip.append(tps_chip)
            if is_coordinator():
                logger.info(
                    "step %d: loss=%.4f grad_norm=%.3f step_time=%.3fs "
                    "tokens/sec/chip=%.1f",
                    step,
                    host.get("loss", float("nan")),
                    host.get("grad_norm", float("nan")),
                    dt,
                    tps_chip,
                )
            if self._metrics_fh is not None:
                self._metrics_fh.write(
                    json.dumps(
                        {
                            "step": step,
                            "step_time_s": round(dt, 6),
                            "tokens_per_sec_per_chip": round(tps_chip, 2),
                            **{k: round(v, 6) for k, v in host.items()},
                        },
                        sort_keys=True,
                    )
                    + "\n"
                )
        self._pending.clear()

    def close(self) -> None:
        self.flush()
        if self._metrics_fh is not None:
            self._metrics_fh.close()
            self._metrics_fh = None

    def summary(self) -> dict[str, float]:
        """BASELINE.md numbers. p50 over steps after compile warm-up."""
        times = self.step_times[1:] if len(self.step_times) > 1 else self.step_times
        tps = self.tokens_per_sec_chip[1:] if len(self.tokens_per_sec_chip) > 1 else self.tokens_per_sec_chip
        out: dict[str, float] = {}
        if times:
            out["step_time_p50_s"] = statistics.median(times)
        if tps:
            out["tokens_per_sec_per_chip_p50"] = statistics.median(tps)
        return out

    def summary_json(self) -> str:
        return json.dumps(self.summary(), sort_keys=True)
