"""End-to-end trainer (L5) — the TPU-native analog of the reference's
``main()`` (ref ``src/distributed_inference.py:43-84``), upgraded from a fake
per-example device op to a real sharded fine-tune:

  setup_logging -> init_runtime -> mesh -> consistency check -> data pipeline
  -> sharded state init -> compiled train loop (metrics, checkpoints, optional
  process-0 API eval) -> clean teardown.

Every host runs this identical program (SPMD); they differ only in which data
shards and array shards they hold.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np

from ditl_tpu.chaos import arm_chaos
from ditl_tpu.client.eval_loop import run_api_eval
from ditl_tpu.client.llm import LLMClient
from ditl_tpu.config import Config
from ditl_tpu.data.dataset import load_text_dataset
from ditl_tpu.data.loader import DataPipeline
from ditl_tpu.data.tokenizer import get_tokenizer
from ditl_tpu.models import llama
from ditl_tpu.parallel.sharding import named_sharding_tree
from ditl_tpu.runtime.consistency import check_cross_host_consistency
from ditl_tpu.runtime.distributed import (
    barrier,
    init_runtime,
    is_coordinator,
    shutdown_runtime,
)
from ditl_tpu.runtime.elastic import emit_heartbeat
from ditl_tpu.runtime.mesh import build_mesh
from ditl_tpu.telemetry import (
    STEP_RING,
    Anomaly,
    AnomalyPlane,
    EventJournal,
    FlightRecorder,
    GoodputTracker,
    IncidentManager,
    MemoryWatcher,
    StepAnatomy,
    Tracer,
    TrainingDetector,
    lost_work_from_journal,
    read_journal,
    worker_journal_path,
)
from ditl_tpu.telemetry.anomaly import NonFiniteMetricError
from ditl_tpu.train.checkpoint import CheckpointManager, DataIterState
from ditl_tpu.train.metrics import MetricsLogger
from ditl_tpu.train.state import TrainState, create_train_state, state_logical_axes
from ditl_tpu.train.step import make_eval_step, make_multi_step, make_train_step
from ditl_tpu.utils.logging import get_logger, setup_logging
from ditl_tpu.utils.profiling import StepProfiler

logger = get_logger(__name__)

__all__ = ["train"]


def _params_from_hf_checkpoint(path: str, model_cfg, current_params, param_shardings):
    """Convert a local HF checkpoint and merge it over the live param tree.

    Subtrees the checkpoint cannot provide (LoRA adapters) keep their fresh
    init; everything else is validated against the model config (a silently
    wrong vocab/hidden size would otherwise train on garbage gathers) and
    device_put leaf-wise onto its existing sharding.
    """
    from ditl_tpu.models.convert import load_hf_model

    logger.info("initializing params from HF checkpoint %s", path)
    np_params, hf_cfg = load_hf_model(path)
    mismatches = [
        f"{f}: checkpoint {getattr(hf_cfg, f)} != model {getattr(model_cfg, f)}"
        for f in (
            "vocab_size", "hidden_size", "intermediate_size", "num_layers",
            "num_heads", "num_kv_heads", "head_dim", "num_experts",
            "tie_embeddings",
        )
        if getattr(hf_cfg, f) != getattr(model_cfg, f)
    ]
    if mismatches:
        raise ValueError(
            f"HF checkpoint {path} does not match the model config: "
            + "; ".join(mismatches)
        )

    def merge(hf_sub, cur_sub, shard_sub):
        if isinstance(cur_sub, dict):
            return {
                k: merge(hf_sub.get(k) if hf_sub else None, v, shard_sub[k])
                for k, v in cur_sub.items()
            }
        if hf_sub is None:  # e.g. LoRA adapters: keep fresh init
            return cur_sub
        return jax.device_put(hf_sub.astype(model_cfg.param_dtype), shard_sub)

    return merge(np_params, current_params, param_shardings)


def _windows(it, size: int):
    """Group an iterator into lists of up to ``size`` items."""
    import itertools

    while True:
        window = list(itertools.islice(it, size))
        if not window:
            return
        yield window


def _timed_iter(it, on_wait):
    """Pass-through iterator reporting the host wall spent blocked in each
    ``next()`` to ``on_wait`` — the data-wait phase of the step breakdown
    (prefetch usually makes this ~0; when it isn't, the pipeline is the
    bottleneck and this is the number that says so)."""
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            on_wait(time.perf_counter() - t0)
            return
        on_wait(time.perf_counter() - t0)
        yield item


def _run_validation(eval_step, params, val_batches, mesh) -> float:
    """Token-weighted mean NLL over the pre-materialized held-out batches
    (host numpy; shipped to the mesh per pass)."""
    from ditl_tpu.data.loader import make_global_batch

    tot_nll = tot_tok = 0.0
    for host_batch in val_batches:
        batch = make_global_batch(mesh, host_batch)
        aux = eval_step(params, batch)
        n = float(aux["n_tokens"])
        tot_nll += float(aux["loss"]) * n
        tot_tok += n
    return tot_nll / max(tot_tok, 1.0)


def _crossed(step: int, n_advanced: int, every: int) -> bool:
    """True if the last ``n_advanced`` steps ending at ``step`` crossed a
    multiple of ``every`` — cadence checks that stay correct when the loop
    advances in windows (steps_per_call > 1), where ``step % every == 0``
    would fire only when a window boundary happens to align."""
    return every > 0 and step > 0 and (step // every) > ((step - n_advanced) // every)


def train(config: Config) -> dict[str, Any]:
    """Run the full fine-tune. Returns summary metrics (also logged)."""
    t_start = time.time()
    # Always-on goodput accounting (telemetry/goodput.py): pure host wall
    # clocks, zero device syncs. Every second of this run lands in a bucket
    # (productive step / compile / data-wait / checkpoint / eval / profiler
    # / restart lost-work) or the measured "other" remainder.
    tracker = GoodputTracker()
    tracker.start()
    t_setup0 = time.perf_counter()
    setup_excl = 0.0  # setup time already attributed to a finer bucket
    init_runtime(config.runtime)
    setup_logging(config.runtime.log_level)
    journal: EventJournal | None = None
    if config.train.telemetry_dir:
        journal = EventJournal(
            worker_journal_path(
                config.train.telemetry_dir, jax.process_index()
            ),
            source=f"worker-{jax.process_index()}",
            max_bytes=config.telemetry.journal_max_bytes(),
        )
        journal.event("worker.start")
    # Chaos plane (ditl_tpu/chaos/, ISSUE 5): armed pod-wide from the
    # identical config (the fingerprint covers chaos.*); per-worker
    # targeting via rule `proc=N`. Injections journal into this worker's
    # event stream so the merged pod timeline shows inject -> death ->
    # relaunch -> recovery in causal order; fire counts persist under
    # telemetry_dir so `max=N` caps survive the kills they inject.
    arm_chaos(
        config.chaos,
        journal=journal,
        process_id=jax.process_index(),
        state_dir=config.chaos.journal_dir or config.train.telemetry_dir,
    )
    mesh = build_mesh(config.mesh)
    model_cfg = config.model  # preset resolution happens in launch.build_config
    # Adapter publication (ISSUE 16): misconfiguration fails HERE, before
    # any compile — a publish cadence with nowhere to write (or no LoRA to
    # slice out) would otherwise surface as a mid-run crash at the first
    # cadence crossing.
    if config.adapter.publish_every > 0:
        if not config.adapter.publish_dir:
            raise ValueError(
                "adapter.publish_every is set but adapter.publish_dir is "
                "empty: the trainer has nowhere to commit adapter "
                "checkpoints")
        if model_cfg.lora_rank <= 0:
            raise ValueError(
                "adapter.publish_every needs model.lora_rank > 0: "
                "adapter-only publication exports the LoRA slice of the "
                "params, and a full fine-tune has none")

    tokenizer = get_tokenizer(config.data.tokenizer)
    if model_cfg.vocab_size < tokenizer.vocab_size:
        raise ValueError(
            f"model vocab {model_cfg.vocab_size} < tokenizer vocab {tokenizer.vocab_size}"
        )
    dataset = load_text_dataset(config.data)
    if (config.data.eval_fraction > 0) != (config.train.val_every > 0):
        raise ValueError(
            "data.eval_fraction and train.val_every must be set together "
            f"(got eval_fraction={config.data.eval_fraction}, "
            f"val_every={config.train.val_every}): one without the other "
            "either wastes held-out data or never validates"
        )
    val_dataset = None
    if config.data.eval_fraction > 0:
        # Deterministic seeded permutation before the split: every host
        # computes the same boundary, and label-ordered corpora (HF imdb is
        # stored label-sorted) don't produce a single-class holdout.
        n_val = max(1, int(len(dataset) * config.data.eval_fraction))
        n_train = len(dataset) - n_val
        if n_train < 1:
            raise ValueError(
                f"eval_fraction {config.data.eval_fraction} leaves no training data"
            )
        from ditl_tpu.data.dataset import TextDataset

        perm = np.random.default_rng(config.data.seed).permutation(len(dataset))
        texts = [dataset.texts[i] for i in perm]
        labels = [dataset.labels[i] for i in perm]
        val_dataset = TextDataset(texts[n_train:], labels[n_train:])
        dataset = TextDataset(texts[:n_train], labels[:n_train])
    # Consistency check runs AFTER data loading so a host that silently fell
    # back to the synthetic corpus (hub hiccup) is caught before any
    # collective, not after a divergent epoch hangs one (SURVEY.md §5).
    check_cross_host_consistency(
        config,
        extra={
            "dataset_len": len(dataset),
            "dataset_head": [dataset[i]["text"][:64] for i in range(min(3, len(dataset)))],
        },
    )
    pipeline = DataPipeline(dataset, tokenizer, config.data, mesh)
    logger.info(
        "dataset: %d examples, %d steps/epoch (host batch %d, global %d)",
        len(dataset),
        pipeline.steps_per_epoch,
        pipeline.host_batch_size,
        config.data.batch_size,
    )

    # Sharded-from-birth state init: jit with out_shardings so every param is
    # created directly on its mesh shards (a 70B state never fits one chip).
    # Rule table must match the train step's (stage-sharded when pipelined).
    from ditl_tpu.train.step import _default_rules

    rules = _default_rules(mesh)
    state_shardings = named_sharding_tree(
        mesh, state_logical_axes(model_cfg, config.train), rules
    )
    rng = jax.random.key(config.train.seed)
    with mesh:
        init_fn = jax.jit(
            lambda r: create_train_state(r, model_cfg, config.train),
            out_shardings=state_shardings,
        )
        state = init_fn(rng)
    n_params = llama.num_params(state.params)
    logger.info("model %s: %.2fM params", model_cfg.name, n_params / 1e6)

    # Checkpoint manager + resume.
    ckpt: CheckpointManager | None = None
    data_iter = DataIterState()
    resumed = False
    if config.train.checkpoint_dir:
        ckpt = CheckpointManager(
            config.train.checkpoint_dir,
            max_to_keep=config.train.keep_checkpoints,
            save_every=config.train.checkpoint_every,
            # Commit/quarantine/fallback events land in this worker's
            # journal — the kill-mid-save drill asserts them in order.
            journal=journal,
        )
        if config.train.resume:
            abstract = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                jax.eval_shape(lambda: state),
                state_shardings,
            )
            t_restore0 = time.perf_counter()
            restored = ckpt.restore_latest(abstract)
            dt_restore = time.perf_counter() - t_restore0
            tracker.add("checkpoint_restore", dt_restore)
            setup_excl += dt_restore
            if restored is not None:
                state, data_iter = restored
                resumed = True
                logger.info(
                    "restored checkpoint: resuming from step %d "
                    "(epoch %d, batch offset %d)",
                    int(state.step), data_iter.epoch, data_iter.step_in_epoch,
                )
                if journal is not None:
                    # Restart lost-work: the previous generation's journal
                    # (same per-process file, appended across generations)
                    # brackets the span between the checkpoint we resumed at
                    # and its last sign of life.
                    lost = lost_work_from_journal(
                        read_journal(journal.path),
                        data_iter.global_step, t_start,
                    )
                    tracker.add("restart_lost_work", lost)
                    journal.event(
                        "worker.resume", step=data_iter.global_step,
                        lost_work_s=round(lost, 6),
                    )

    if config.train.init_from_hf and not resumed:
        # Overwrite the random base weights with a converted HF checkpoint
        # (skipped on resume — the Orbax checkpoint supersedes it). Leaf-wise
        # device_put onto each param's existing sharding; the full model is
        # never resident on one chip.
        state = state.replace(
            params=_params_from_hf_checkpoint(
                config.train.init_from_hf, model_cfg, state.params,
                state_shardings.params,
            )
        )

    val_batches = None
    if val_dataset is not None and config.train.val_every > 0:
        import dataclasses as _dc
        import itertools as _it

        val_pipeline = DataPipeline(
            val_dataset,
            tokenizer,
            _dc.replace(config.data, shuffle=False),
            mesh,
        )
        # Materialize the validation window ONCE as HOST batches: shuffle is
        # off, so they are identical every run — re-tokenizing/packing the
        # holdout at each val_every would stall training — but keeping them
        # in host RAM (not HBM) means validation costs no standing device
        # memory; each pass device_puts them transiently. This is also the
        # only accurate emptiness check for the packed path (document counts
        # don't predict packed batch counts).
        val_batches = list(
            _it.islice(val_pipeline._host_batches(0), config.train.val_batches)
        )
        if not val_batches:
            raise ValueError(
                f"eval_fraction {config.data.eval_fraction} holds out too few "
                f"tokens for even one validation batch (batch {config.data.batch_size}"
                f" x seq {config.data.seq_len}); increase it or shrink the batch"
            )

    example = next(iter(pipeline.epoch(0)))
    train_step = make_train_step(model_cfg, config.train, mesh, example)
    eval_step = None
    spc = max(1, config.train.steps_per_call)
    train_multi = (
        make_multi_step(model_cfg, config.train, mesh, example, spc)
        if spc > 1
        else None
    )

    # Step-time anatomy (telemetry/perf.py, ISSUE 7): the per-step wall
    # decomposition the goodput report is too coarse for. Attached to the
    # MetricsLogger AFTER the compile window (goodput attributes that whole
    # window to compile; anatomy describes warm steps only) and conserved
    # against the independently measured step-path wall to 5% in tier-1.
    anatomy = StepAnatomy()
    # HBM accounting (telemetry/memwatch.py): per-window allocator samples
    # (high-watermark gauges) + a journaled live-buffer top-k dump when an
    # OOM-class failure unwinds the loop. No-op on statless backends (CPU).
    memwatch = MemoryWatcher(
        journal=journal, topk=config.telemetry.memory_topk,
    )
    # Flight recorder + anomaly plane (ISSUE 10): the per-step ring and the
    # non-finite/spike/explosion detectors ride the EXISTING log_every host
    # flush (train/metrics.py on_host_metrics) — always on, zero device
    # syncs beyond the flush the metrics path already pays (tier-1-pinned).
    # Incident bundles are assembled only when telemetry.incident_dir is
    # set; a fatal detection (non-finite loss/grad) dumps its bundle and
    # THEN crashes the run, so the evidence precedes the stack trace.
    flight = FlightRecorder(config.telemetry.flight_ring_size)
    incidents: IncidentManager | None = None
    if config.telemetry.incident_dir:
        import os as _os

        incidents = IncidentManager(
            # Per-worker subdirectory: SPMD replicates the loss, so a NaN
            # fires the fatal detector in EVERY worker at once — each
            # writes (and GCs, and sweeps tmp dirs) in its own directory
            # rather than racing peers in a shared one.
            _os.path.join(config.telemetry.incident_dir,
                          f"worker-{jax.process_index()}"),
            flight=flight,
            metrics_render=memwatch.registry.render,
            journal_dir=config.train.telemetry_dir,
            registry=memwatch.registry,
            config_snapshot=config.to_dict(),
            memwatch_dump=memwatch.report,
            source=f"worker-{jax.process_index()}",
            **config.telemetry.incident_kwargs(),
        )
    anomaly_plane = AnomalyPlane(incidents=incidents, journal=journal)
    # Continuous sampling profiler (ISSUE 18): armed by telemetry.prof_hz,
    # off by default. Phase-tagged across the step loop so StepAnatomy's
    # host_dispatch bucket gains stack attribution in the summary, and
    # incident bundles embed the collapsed profile (profile.txt).
    sampler = None
    if config.telemetry.prof_hz > 0:
        from ditl_tpu.telemetry.prof import SamplingProfiler

        sampler = SamplingProfiler(
            hz=config.telemetry.prof_hz,
            max_stacks=config.telemetry.prof_max_stacks,
            registry=memwatch.registry,
        )
        sampler.arm_phases()  # this (the step-loop) thread
        sampler.start()
    train_detector = TrainingDetector(
        **config.telemetry.training_detector_kwargs()
    )
    _fatal: list[Anomaly] = []
    _in_teardown = [False]

    def _fatal_error() -> NonFiniteMetricError:
        return NonFiniteMetricError(
            f"non-finite training metric at step "
            f"{_fatal[0].detail.get('step', '?')}: "
            f"{_fatal[0].kind} {_fatal[0].detail}"
        )

    def _on_host_metrics(step: int, host: dict, dt: float) -> None:
        flight.ring(STEP_RING).record(
            step=step,
            loss=host.get("loss"),
            grad_norm=host.get("grad_norm"),
            n_tokens=host.get("n_tokens"),
            step_time_s=round(dt, 6),
        )
        for anomaly in train_detector.observe_step(
            step, host.get("loss"), host.get("grad_norm")
        ):
            anomaly_plane.trigger(anomaly)
            if anomaly.severity == "fatal":
                _fatal.append(anomaly)
        if _fatal and not _in_teardown[0]:
            # Bundle already assembled above; now crash the run the way a
            # real divergence would have a few steps later — loudly, with
            # the black box on disk. NOT raised during teardown: the
            # catch-up flush inside metrics.close() runs in the finally
            # block, where raising would skip the rest of teardown (and
            # the end-of-training barrier) and mask any original
            # exception — a tail-window detection raises AFTER teardown
            # instead (below).
            raise _fatal_error()

    metrics = MetricsLogger(
        log_every=config.train.log_every,
        metrics_file=config.train.metrics_file,
        on_host_metrics=_on_host_metrics,
    )
    profiler = StepProfiler(
        config.train.profile_dir,
        config.train.profile_start_step,
        config.train.profile_num_steps,
        # ISSUE 6 satellite: a journaled run records the xprof capture
        # window as a `profiler.capture` span on the training-leg timeline
        # (not only as a goodput bucket).
        tracer=Tracer(journal) if journal is not None else None,
    )
    client = LLMClient(config.api)
    total_steps = config.train.total_steps
    global_step = data_iter.global_step
    def beat(step: int) -> None:
        """Publish liveness for the pod controller (runtime/elastic.py)."""
        if config.train.heartbeat_dir:
            emit_heartbeat(config.train.heartbeat_dir, jax.process_index(), step)

    # First heartbeat BEFORE the first step: first-step compile can dominate
    # wall time, and the pod controller must read "alive, still compiling"
    # rather than "never came up".
    beat(global_step)
    step_metrics = None
    last_val_loss = None
    last_saved = None
    epoch = data_iter.epoch

    # Everything before the loop is startup (minus spans already attributed
    # to finer buckets, e.g. checkpoint restore).
    tracker.add("startup", time.perf_counter() - t_setup0 - setup_excl)
    data_wait_acc = [0.0]  # host wall blocked in the data iterator, per window

    def _note_wait(dt: float) -> None:
        data_wait_acc[0] += dt
        tracker.add("data_wait", dt)

    first_window = True
    try:
        for epoch in range(data_iter.epoch, config.data.num_epochs):
            # Resume skips already-consumed batches at the sampler level.
            start = data_iter.step_in_epoch if epoch == data_iter.epoch else 0
            batch_iter = _timed_iter(
                iter(pipeline.epoch(epoch, start_step=start)), _note_wait
            )
            step_in_epoch = start
            for window in _windows(batch_iter, spc):
                if global_step >= total_steps:
                    break
                window = window[: total_steps - global_step]
                t_window0 = time.perf_counter()
                metrics.start_step()
                # Profiler work (start_trace, and maybe_stop's
                # effects_barrier + trace write) happens INSIDE the window
                # interval — timed explicitly and subtracted from the
                # window wall below, or it would be double-counted into
                # compile/productive_step and break conservation.
                profiler.maybe_start(global_step)
                prof_s = time.perf_counter() - t_window0
                if sampler is not None:
                    # Tag the dispatch window: samples landing here
                    # attribute StepAnatomy's host_dispatch bucket to
                    # real frames in the summary (one attribute write).
                    sampler.set_phase("host_dispatch")
                with profiler.annotate(global_step):
                    if train_multi is not None and len(window) == spc:
                        # One device program runs the whole window: zero host
                        # dispatch between steps (train/step.make_multi_step).
                        import jax.numpy as jnp

                        stacked = jax.tree.map(
                            lambda *xs: jnp.stack(xs, axis=0), *window
                        )
                        state, ms = train_multi(state, stacked)
                        step_metrics = {k: v[-1] for k, v in ms.items()}
                        window_metrics = dict(
                            step_metrics, n_tokens=ms["n_tokens"].sum()
                        )
                    else:  # window shorter than spc (epoch tail): single steps
                        window_tokens = None
                        for batch in window:
                            state, step_metrics = train_step(state, batch)
                            window_tokens = (
                                step_metrics["n_tokens"]
                                if window_tokens is None
                                else window_tokens + step_metrics["n_tokens"]
                            )
                        window_metrics = dict(step_metrics, n_tokens=window_tokens)
                t_prof = time.perf_counter()
                profiler.maybe_stop(global_step + len(window) - 1)
                prof_s += time.perf_counter() - t_prof
                tracker.add("profiler", prof_s)
                global_step += len(window)
                step_in_epoch += len(window)
                window_wait, data_wait_acc[0] = data_wait_acc[0], 0.0
                metrics.end_step(
                    global_step - 1, window_metrics, n_steps=len(window),
                    data_wait_s=window_wait,
                    # Profiler work inside the window interval has its own
                    # goodput bucket AND is subtracted from the anatomy
                    # wall below — exclude it from the anatomy's dispatch
                    # feed too, or a capture window would break the 5%
                    # conservation invariant.
                    excluded_s=prof_s,
                )
                if sampler is not None:
                    sampler.set_phase(None)
                # Window wall (dispatch + any flush sync inside end_step;
                # data wait happened before the window body, profiler work
                # is subtracted — both have their own buckets): the FIRST
                # compiled window is compile-dominated, so it is attributed
                # to the compile badput bucket whole — the same convention
                # bench.py and summary() use when they drop the warm-up
                # step from p50.
                dt_window = time.perf_counter() - t_window0 - prof_s
                if first_window:
                    tracker.add("compile", dt_window)
                    first_window = False
                    # Anatomy starts AFTER the compile window: from here on
                    # the MetricsLogger feeds host_dispatch / data_wait /
                    # device_compute and the trainer adds the matching wall.
                    metrics.anatomy = anatomy
                else:
                    tracker.add_step(dt_window, len(window))
                    anatomy.add_wall(window_wait + dt_window, len(window))
                if config.telemetry.memory_sample_every and _crossed(
                    global_step, len(window),
                    config.telemetry.memory_sample_every,
                ):
                    memwatch.sample()
                if journal is not None and _crossed(
                    global_step, len(window), config.train.log_every
                ):
                    journal.event("train.progress", step=global_step)
                beat(global_step)
                position = DataIterState(epoch, step_in_epoch, global_step)
                if ckpt is not None and ckpt.should_save(global_step, len(window)):
                    t_ck0 = time.perf_counter()
                    with tracker.span("checkpoint_save"):
                        ckpt.save(global_step, state, position)
                    dt_ck = time.perf_counter() - t_ck0
                    # The blocking portion of the async save interleaves the
                    # step stream — the anatomy's checkpoint_overlap bucket
                    # (the async remainder overlaps device compute for free).
                    anatomy.add("checkpoint_overlap", dt_ck)
                    anatomy.add_wall(dt_ck)
                    if journal is not None:
                        journal.event("checkpoint.save", step=global_step)
                    last_saved = global_step
                if is_coordinator() and _crossed(
                    global_step, len(window), config.adapter.publish_every
                ):
                    # Live train->serve publication (ISSUE 16): commit the
                    # LoRA-only slice as a manifest-verified adapter
                    # checkpoint (npz + crc manifest written LAST + atomic
                    # LATEST flip) — the unit gateway/publish.py verifies
                    # and walks onto a serving fleet. LoRA leaves are tiny
                    # and replicated, so only the coordinator writes; the
                    # wall rides the checkpoint_save goodput bucket.
                    from ditl_tpu.train.adapter_export import export_adapter

                    with tracker.span("checkpoint_save"):
                        vdir = export_adapter(
                            config.adapter.publish_dir,
                            config.adapter.publish_name,
                            global_step, state.params, model_cfg,
                        )
                    if journal is not None:
                        journal.event("adapter.export", step=global_step,
                                      directory=vdir)
                    logger.info("published adapter checkpoint %s", vdir)
                if val_batches is not None and _crossed(
                    global_step, len(window), config.train.val_every
                ):
                    if eval_step is None:
                        eval_step = make_eval_step(model_cfg, mesh)
                    with tracker.span("eval"):
                        last_val_loss = _run_validation(
                            eval_step, state.params, val_batches, mesh
                        )
                    if is_coordinator():
                        logger.info(
                            "step %d: val_loss=%.4f", global_step, last_val_loss
                        )
                if _crossed(global_step, len(window), config.train.eval_every):
                    idx = np.arange(min(config.train.eval_samples, len(dataset)))
                    with tracker.span("eval"):
                        run_api_eval(
                            client,
                            [dataset[int(i)]["text"] for i in idx],
                            [dataset[int(i)]["label"] for i in idx],
                            max_samples=config.train.eval_samples,
                        )
                if _crossed(
                    global_step, len(window), config.train.val_every
                ) or _crossed(global_step, len(window), config.train.eval_every):
                    # Validation / remote-API eval can dwarf a step window;
                    # re-arm the stall watchdog so a long (healthy) eval
                    # isn't read as a wedged worker.
                    beat(global_step)
                if (
                    config.train.fault_kill_step > 0
                    and not resumed
                    and global_step >= config.train.fault_kill_step
                    and config.train.fault_kill_process
                    in (-1, jax.process_index())
                ):
                    # SIGKILL drill (host-crash simulation): bypasses every
                    # Python-level handler, so only a process-level
                    # supervisor (launch --supervise) can bring us back.
                    import os as _os
                    import signal as _signal

                    logger.error(
                        "fault_kill_step: SIGKILLing self at step %d",
                        global_step,
                    )
                    if journal is not None:
                        # Line-buffered: the event is on disk before the
                        # uncatchable kill — the timeline's first entry of
                        # the death sequence.
                        journal.event("worker.sigkill_self", step=global_step)
                    _os.kill(_os.getpid(), _signal.SIGKILL)
                if (
                    config.train.fault_inject_step > 0
                    and not resumed
                    and global_step >= config.train.fault_inject_step
                ):
                    # Recovery drill (after the save check above, so the
                    # supervisor has a checkpoint to resume from): only on a
                    # first run — a resumed run must complete.
                    raise RuntimeError(
                        f"injected fault at step {global_step} "
                        "(train.fault_inject_step)"
                    )
            if global_step >= total_steps:
                break
        # The catch-up flush after the loop blocks on the last window's
        # device work — step-path wall like any in-loop flush, so the
        # anatomy counts the interval (its sync feeds device_compute via
        # the logger hook) and conservation holds.
        t_flush0 = time.perf_counter()
        metrics.flush()
        anatomy.add_wall(time.perf_counter() - t_flush0)
        if ckpt is not None and last_saved != global_step:
            with tracker.span("checkpoint_save"):
                ckpt.save(global_step, state, DataIterState(epoch, 0, global_step))
                ckpt.wait()
            if journal is not None:
                journal.event("checkpoint.save", step=global_step)
    except Exception as e:
        # OOM post-mortem (ISSUE 7): journal the live-buffer top-k dump
        # BEFORE the finally teardown releases the step's working set, so
        # the record shows what was actually holding HBM. Non-OOM failures
        # pass through untouched.
        from ditl_tpu.telemetry.memwatch import is_oom_error

        if is_oom_error(e):
            import contextlib as _ctx

            with _ctx.suppress(Exception):
                memwatch.sample()
                memwatch.oom_dump(e)
            # OOM is an anomaly-plane trigger source (ISSUE 10): the bundle
            # freezes the step ring + the memwatch top-k alongside the
            # journaled oom_dump, before the teardown releases buffers.
            anomaly_plane.trigger(Anomaly(
                "train.oom", severity="fatal",
                detail={"step": global_step,
                        "error": f"{type(e).__name__}: {str(e)[:500]}"},
            ))
        raise
    finally:
        _in_teardown[0] = True  # tail-window flushes detect but never raise
        if sampler is not None:
            sampler.stop()
        metrics.close()
        with tracker.span("profiler"):
            profiler.close()
        if ckpt is not None:
            ckpt.close()
        if journal is not None:
            journal.event("worker.exit", step=global_step)
            journal.close()
        barrier("end-of-training")

    # A fatal detection surfaced only by the teardown's catch-up flush
    # (NaN in the final, un-flushed window): teardown completed cleanly
    # above — crash NOW, with the bundle already on disk.
    if _fatal:
        raise _fatal_error()

    summary = metrics.summary()
    summary["final_loss"] = (
        float(jax.device_get(step_metrics["loss"]))
        if step_metrics is not None
        else float("nan")
    )
    summary["steps"] = global_step
    if last_val_loss is not None:
        summary["val_loss"] = last_val_loss
    summary["params_m"] = n_params / 1e6
    summary["wall_s"] = time.time() - t_start
    # Goodput report: where the wall clock went, conservation-checked (the
    # tier-1 test asserts buckets + other sum to total within 1%).
    summary["goodput"] = tracker.report()
    # Step-time anatomy (ISSUE 7): the warm-step wall decomposed into
    # data-wait / host-dispatch / device-compute / checkpoint-overlap,
    # conservation-checked against the measured step-path wall to 5%.
    summary["step_anatomy"] = anatomy.report()
    # Stack attribution (ISSUE 18): when the sampling profiler was armed,
    # the anatomy's host_dispatch bucket names its hot frames — "dispatch
    # is slow" becomes "dispatch is slow IN THIS FUNCTION".
    if sampler is not None:
        frames = sampler.phase_top("host_dispatch", 5)
        if frames:
            summary["step_anatomy"]["host_dispatch_frames"] = frames
        summary["profile"] = {
            "samples": sampler.samples,
            "distinct_stacks": len(sampler.snapshot()),
            "evicted": sampler.evicted,
            "hz": sampler.hz,
        }
    # Anomaly-plane accounting (ISSUE 10): what fired and how many bundles
    # were assembled — a completed-but-noisy run is visible in its summary.
    if anomaly_plane.detected:
        summary["anomalies"] = dict(sorted(anomaly_plane.detected.items()))
    if incidents is not None:
        summary["incidents"] = incidents.created
    mem = memwatch.report()
    if mem:
        summary["memory"] = mem
    if is_coordinator():
        logger.info("training done: %s", summary)
        logger.info("goodput report: %s", summary["goodput"])
        logger.info("step anatomy: %s", summary["step_anatomy"])
    shutdown_runtime()
    return summary
