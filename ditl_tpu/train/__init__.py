from ditl_tpu.train.state import TrainState, create_train_state, state_logical_axes  # noqa: F401
from ditl_tpu.train.step import loss_fn, make_eval_step, make_train_step  # noqa: F401
from ditl_tpu.train.metrics import MetricsLogger  # noqa: F401
