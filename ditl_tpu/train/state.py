"""Train state: parameters + optimizer state + step counter.

The reference has no train state at all — no model, no optimizer, nothing is
ever updated or saved (SURVEY.md §2: the loss helper is dead code). This module
is the real thing: an optax AdamW state whose every leaf carries the same
logical sharding as its parameter, so FSDP shards optimizer moments alongside
weights (ZeRO-style) for free.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import optax

from ditl_tpu.config import ModelConfig, TrainConfig
from ditl_tpu.models import llama

__all__ = ["TrainState", "create_train_state", "make_optimizer", "state_logical_axes"]


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def lora_mask(params: Any) -> Any:
    """True for trainable leaves. With LoRA enabled, only adapter params train
    (base weights frozen) — optimizer state for frozen leaves is zero-sized."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def trainable(path) -> bool:
        return any(getattr(k, "key", None) == "lora" for k in path)

    has_lora = any(trainable(path) for path, _ in flat)
    if not has_lora:
        return jax.tree.map(lambda _: True, params)
    return jax.tree_util.tree_map_with_path(lambda path, _: trainable(path), params)


def make_optimizer(cfg: TrainConfig, params: Any) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    if cfg.optimizer == "adamw":
        opt = optax.adamw(
            schedule,
            b1=cfg.beta1,
            b2=cfg.beta2,
            weight_decay=cfg.weight_decay,
            # bf16 first moment halves its HBM footprint/traffic; the
            # variance (nu) stays f32 — it is the precision-sensitive one
            # (sqrt of tiny values).
            mu_dtype=cfg.adam_mu_dtype,
        )
    elif cfg.optimizer == "adafactor":
        # Factored second moment: O(rows+cols) statistics instead of a full
        # parameter-shaped moment — the classic TPU big-model optimizer.
        # Factored stats are vectors, so they restore replicated (the
        # state_logical_axes ndim guard); that is by design, they're tiny.
        opt = optax.adafactor(
            learning_rate=schedule, weight_decay_rate=cfg.weight_decay or None
        )
    elif cfg.optimizer == "lion":
        opt = optax.lion(
            schedule, b1=cfg.beta1, b2=cfg.beta2,
            weight_decay=cfg.weight_decay, mu_dtype=cfg.adam_mu_dtype,
        )
    elif cfg.optimizer == "sgd":
        opt = optax.sgd(schedule, momentum=cfg.beta1)
    else:
        raise ValueError(
            f"unknown optimizer {cfg.optimizer!r} (adamw|adafactor|lion|sgd)"
        )
    tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), opt)
    mask = lora_mask(params)
    if not all(jax.tree.leaves(mask)):
        # Freeze non-LoRA leaves: their updates are hard zeros (optax.masked
        # would pass raw gradients through for unmasked leaves, which is the
        # opposite of freezing).
        labels = jax.tree.map(lambda t: "train" if t else "freeze", mask)
        tx = optax.multi_transform({"train": tx, "freeze": optax.set_to_zero()}, labels)
    return tx


def create_train_state(
    rng: jax.Array, model_cfg: ModelConfig, train_cfg: TrainConfig
) -> TrainState:
    import jax.numpy as jnp

    params = llama.init_params(rng, model_cfg)
    tx = make_optimizer(train_cfg, params)
    opt_state = tx.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def state_logical_axes(model_cfg: ModelConfig, train_cfg: TrainConfig) -> Any:
    """Logical-axis tree matching ``create_train_state``'s output structure.

    Optimizer-state leaves inherit the logical axes of the parameter they
    shadow (adam moments are parameter-shaped), found by path-suffix matching:
    the leaf at ``opt_state/.../1/mu/embed/embedding`` gets the axes of
    ``params/embed/embedding``. Anything that isn't parameter-shadowing
    (step counts, schedule state) is replicated. Built by abstract evaluation,
    so no real parameters are ever allocated.
    """
    import jax.numpy as jnp

    param_axes = llama.param_logical_axes(model_cfg)
    axes_leaves, _ = jax.tree_util.tree_flatten_with_path(
        param_axes, is_leaf=lambda x: isinstance(x, tuple)
    )
    # param path (tuple of dict keys) -> logical axes
    by_path = {
        tuple(k.key for k in path): axes for path, axes in axes_leaves
    }

    def abstract_state():
        params = llama.init_params(jax.random.key(0), model_cfg)
        tx = make_optimizer(train_cfg, params)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
        )

    shapes = jax.eval_shape(abstract_state)

    def leaf_axes(path, leaf):
        dict_keys = tuple(k.key for k in path if hasattr(k, "key") and isinstance(k.key, str))
        for start in range(len(dict_keys)):
            if dict_keys[start:] in by_path:
                axes = by_path[dict_keys[start:]]
                if len(axes) == leaf.ndim:
                    return axes
        return tuple([None] * leaf.ndim)

    opt_axes = jax.tree_util.tree_map_with_path(leaf_axes, shapes.opt_state)
    return TrainState(step=(), params=param_axes, opt_state=opt_axes)
