"""Orbax checkpoint / resume (SURVEY.md §5 'Checkpoint / resume: absent' in the
reference — nothing existed to save; required here for the 70B/v5p-128 north
star, where preemption without resumable state means losing days of work).

Saves the full sharded TrainState plus the data-iterator position (epoch,
step-within-epoch) so resume continues the exact epoch-seeded shuffle the
``ShardedSampler`` would have produced — the distributed-sampler reproducibility
contract extends across restarts. Saves are async (Orbax writes in the
background while training continues) and multi-host-safe (each host writes its
addressable shards; Orbax coordinates the commit).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["CheckpointManager", "DataIterState"]


@dataclasses.dataclass
class DataIterState:
    epoch: int = 0
    step_in_epoch: int = 0
    global_step: int = 0


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3, save_every: int = 0):
        import orbax.checkpoint as ocp

        self.save_every = save_every
        # Register the item handlers up front so a FRESH manager (the
        # serving path restores from checkpoints it never wrote) can answer
        # item_metadata()/restore() without the hand-built
        # f"{dir}/{step}/state" + bare-Checkpointer traversal this class
        # used to carry (VERDICT r5 weak #3). Exactly ONE handler per item:
        # the composite handler finalizes saves once per registered
        # (item, handler) pair, so a second "state" handler would
        # double-finalize every save.
        registry = ocp.handlers.DefaultCheckpointHandlerRegistry()
        state_handler = ocp.StandardCheckpointHandler()
        registry.add("state", ocp.args.StandardSave, state_handler)
        registry.add("state", ocp.args.StandardRestore, state_handler)
        json_handler = ocp.JsonCheckpointHandler()
        registry.add("data_iter", ocp.args.JsonSave, json_handler)
        registry.add("data_iter", ocp.args.JsonRestore, json_handler)
        self._mgr = ocp.CheckpointManager(
            directory,
            handler_registry=registry,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )
        # The manager owns path handling (epath) — no version-probing here.
        self.directory = self._mgr.directory

    def should_save(self, step: int, n_advanced: int = 1) -> bool:
        """True if the last ``n_advanced`` steps ending at ``step`` crossed a
        save boundary — stays correct when the trainer advances in compiled
        step windows (train.steps_per_call > 1), where an exact-multiple check
        would only fire on aligned window boundaries."""
        return (
            self.save_every > 0
            and step > 0
            and (step // self.save_every) > ((step - n_advanced) // self.save_every)
        )

    def save(self, step: int, state: Any, data_iter: DataIterState) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                data_iter=ocp.args.JsonSave(dataclasses.asdict(data_iter)),
            ),
        )
        logger.info("checkpoint save queued at step %d", step)

    def restore_latest(self, abstract_state: Any) -> tuple[Any, DataIterState] | None:
        """Restore the newest checkpoint, sharded per ``abstract_state``
        (a jax.eval_shape tree with shardings attached). Returns None if no
        checkpoint exists."""
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                data_iter=ocp.args.JsonRestore(),
            ),
        )
        data_iter = DataIterState(**restored["data_iter"])
        logger.info("restored checkpoint at step %d", step)
        return restored["state"], data_iter

    def restore_latest_params(self, abstract_params: Any = None) -> Any | None:
        """Restore ONLY the ``params`` subtree of the newest checkpoint — the
        serving path (infer/server.py). Partial restore means the optimizer
        moments (2x the params for AdamW) are never read off storage, which is
        the difference between serving a 70B checkpoint and OOMing on it.
        ``abstract_params`` (a ``jax.eval_shape`` tree) is validated against
        the checkpoint metadata so a preset/checkpoint mismatch fails loudly
        here, not as a shape error mid-forward.

        Multi-process serving: when ``abstract_params`` leaves carry
        shardings (``jax.ShapeDtypeStruct(..., sharding=...)``), each process
        restores only its addressable shards of the global arrays — the
        cross-process mirror of how the checkpoint was written. Without
        shardings the restore yields host numpy (single-process serving)."""
        import jax
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None
        # Manager-API route (no hand-built "{dir}/{step}/state" paths): a
        # READ-ONLY manager over the same directory whose "state" handler is
        # the PyTree one — partial restore is a PyTree-handler feature, and
        # the writing manager must keep exactly one handler per item (see
        # __init__). Read-only also means this reader can never garbage-
        # collect steps out from under the writer.
        registry = ocp.handlers.DefaultCheckpointHandlerRegistry()
        registry.add("state", ocp.args.PyTreeRestore,
                     ocp.PyTreeCheckpointHandler())
        reader = ocp.CheckpointManager(
            self.directory,
            handler_registry=registry,
            options=ocp.CheckpointManagerOptions(read_only=True),
        )
        try:
            return self._restore_params_via(reader, step, abstract_params)
        finally:
            reader.close()

    def _restore_params_via(self, reader, step: int, abstract_params):
        import jax
        import orbax.checkpoint as ocp

        meta = reader.item_metadata(step)["state"]
        # Orbax < 0.9 returns the metadata TREE directly; newer wraps it.
        meta_tree = meta if isinstance(meta, dict) else meta.tree
        if "params" not in meta_tree:
            raise ValueError(
                f"checkpoint step {step} in {self.directory} has no "
                "'params' subtree"
            )
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
            {"params": meta_tree["params"]},
        )
        restore_args = None
        if abstract_params is not None:
            expect = {
                jax.tree_util.keystr(p): l.shape
                for p, l in jax.tree_util.tree_leaves_with_path(abstract_params)
            }
            got = {
                jax.tree_util.keystr(p): l.shape
                for p, l in jax.tree_util.tree_leaves_with_path(abstract["params"])
            }
            if expect != got:
                missing = sorted(set(expect) - set(got))
                extra = sorted(set(got) - set(expect))
                shape_diff = sorted(
                    k for k in expect.keys() & got.keys() if expect[k] != got[k]
                )
                raise ValueError(
                    f"checkpoint at step {step} does not match the model config: "
                    f"missing={missing[:3]} extra={extra[:3]} shape_mismatch="
                    f"{[(k, expect[k], got[k]) for k in shape_diff[:3]]}"
                )
            if any(
                getattr(l, "sharding", None) is not None
                for l in jax.tree_util.tree_leaves(abstract_params)
            ):
                restore_args = {
                    "params": jax.tree.map(
                        lambda meta, user: ocp.ArrayRestoreArgs(
                            sharding=user.sharding,
                            global_shape=meta.shape,
                            dtype=meta.dtype,
                        )
                        if getattr(user, "sharding", None) is not None
                        else ocp.RestoreArgs(),
                        abstract["params"],
                        abstract_params,
                    )
                }
        try:
            restore = ocp.args.PyTreeRestore(
                item=abstract, restore_args=restore_args, partial_restore=True
            )
        except TypeError:
            # Older orbax spells partial restore as "transforms={}": only the
            # item's keys are read, everything else is dropped unread. That
            # spelling requires explicit restore_args for every leaf.
            restore = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args
                or jax.tree.map(lambda _: ocp.RestoreArgs(), abstract),
                transforms={},
            )
        restored = reader.restore(step, args=ocp.args.Composite(state=restore))
        logger.info("restored params (only) from checkpoint at step %d", step)
        return restored["state"]["params"]

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
