"""Orbax checkpoint / resume (SURVEY.md §5 'Checkpoint / resume: absent' in the
reference — nothing existed to save; required here for the 70B/v5p-128 north
star, where preemption without resumable state means losing days of work).

Saves the full sharded TrainState plus the data-iterator position (epoch,
step-within-epoch) so resume continues the exact epoch-seeded shuffle the
``ShardedSampler`` would have produced — the distributed-sampler reproducibility
contract extends across restarts. Saves are async (Orbax writes in the
background while training continues) and multi-host-safe (each host writes its
addressable shards; Orbax coordinates the commit).

**Crash consistency (ISSUE 5):** Orbax's finalize-rename makes a *clean*
interrupted save invisible, but it cannot see bit rot, truncation after
commit, or a SIGKILL landing mid-finalize on a filesystem without atomic
directory rename. This module therefore adds its own integrity layer:

- at commit, a per-item manifest (``ditl_manifest.json``: relpath ->
  size + crc32 for every file under the step dir) is written atomically
  into the step dir;
- ``restore_latest`` / ``restore_latest_params`` verify the newest step
  against its manifest first, QUARANTINE torn/corrupt steps (moved whole
  into ``<dir>/quarantine/`` — never deleted, an operator can autopsy) and
  leftover ``*.orbax-checkpoint-tmp*`` wreckage from a killed save, and
  fall back to the newest step that verifies — zero manual cleanup;
- every quarantine/fallback is journaled (telemetry/journal.py), which is
  what the kill-mid-save chaos drill asserts in causal order.

A step with NO manifest (written by an older build) is "legacy": restore is
attempted, and only a failing read quarantines it — old checkpoint dirs
keep resuming.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any

from ditl_tpu.chaos import maybe_inject
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["CheckpointManager", "DataIterState", "MANIFEST_NAME"]

MANIFEST_NAME = "ditl_manifest.json"


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


@dataclasses.dataclass
class DataIterState:
    epoch: int = 0
    step_in_epoch: int = 0
    global_step: int = 0


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager`` adding the
    crash-consistency layer (module docstring). ``journal`` (an
    ``EventJournal``) records commit/quarantine/fallback events into the
    caller's timeline."""

    def __init__(self, directory: str, max_to_keep: int = 3, save_every: int = 0,
                 journal=None):
        import orbax.checkpoint as ocp

        self.save_every = save_every
        self._journal = journal
        # Steps whose async save has been issued but whose integrity
        # manifest is not yet on disk (written once the save finishes).
        self._pending_manifest: list[int] = []
        self._manifest_thread: threading.Thread | None = None
        # Register the item handlers up front so a FRESH manager (the
        # serving path restores from checkpoints it never wrote) can answer
        # item_metadata()/restore() without the hand-built
        # f"{dir}/{step}/state" + bare-Checkpointer traversal this class
        # used to carry (VERDICT r5 weak #3). Exactly ONE handler per item:
        # the composite handler finalizes saves once per registered
        # (item, handler) pair, so a second "state" handler would
        # double-finalize every save.
        registry = ocp.handlers.DefaultCheckpointHandlerRegistry()
        state_handler = ocp.StandardCheckpointHandler()
        registry.add("state", ocp.args.StandardSave, state_handler)
        registry.add("state", ocp.args.StandardRestore, state_handler)
        json_handler = ocp.JsonCheckpointHandler()
        registry.add("data_iter", ocp.args.JsonSave, json_handler)
        registry.add("data_iter", ocp.args.JsonRestore, json_handler)
        self._mgr = ocp.CheckpointManager(
            directory,
            handler_registry=registry,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )
        # The manager owns path handling (epath) — no version-probing here.
        self.directory = self._mgr.directory

    def should_save(self, step: int, n_advanced: int = 1) -> bool:
        """True if the last ``n_advanced`` steps ending at ``step`` crossed a
        save boundary — stays correct when the trainer advances in compiled
        step windows (train.steps_per_call > 1), where an exact-multiple check
        would only fire on aligned window boundaries."""
        return (
            self.save_every > 0
            and step > 0
            and (step // self.save_every) > ((step - n_advanced) // self.save_every)
        )

    # -- crash-consistency layer --------------------------------------------

    def _jevent(self, event: str, **attrs) -> None:
        if self._journal is not None:
            self._journal.event(event, **attrs)

    def _is_primary(self) -> bool:
        """Exactly one process writes manifests / quarantines (shared fs);
        every process VERIFIES."""
        try:
            import jax

            return jax.process_index() == 0
        except Exception:
            return True

    def _step_path(self, step: int) -> str:
        return os.path.join(str(self.directory), str(step))

    def _list_steps(self) -> list[int]:
        """Finalized step dirs, newest first — read from the filesystem, not
        the Orbax manager's cache, so a quarantine is visible immediately."""
        try:
            names = os.listdir(str(self.directory))
        except OSError:
            return []
        return sorted((int(n) for n in names if n.isdigit()), reverse=True)

    def _write_manifest(self, step: int) -> None:
        d = self._step_path(step)
        if not os.path.isdir(d):
            return  # save never finalized (or already quarantined)
        files: dict[str, dict] = {}
        for root, _dirs, names in os.walk(d):
            for name in names:
                if name == MANIFEST_NAME:
                    continue
                path = os.path.join(root, name)
                rel = os.path.relpath(path, d)
                try:
                    files[rel] = {
                        "size": os.path.getsize(path),
                        "crc32": _file_crc32(path),
                    }
                except OSError:
                    return  # step mutating under us (gc?): skip, stay legacy
        tmp = os.path.join(d, f"{MANIFEST_NAME}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump({"step": step, "files": files}, f, sort_keys=True)
        os.replace(tmp, os.path.join(d, MANIFEST_NAME))
        self._jevent("checkpoint.commit", step=step, n_files=len(files))

    def _flush_manifests(self, sync: bool = True) -> None:
        """Manifest every save that has finished since the last flush.
        Called where the manager already synchronizes (next save / wait /
        close), so saves stay async: the manifest lands at the first
        barrier after the commit, and a crash in the gap just leaves a
        legacy-status step (restore still verifies it by reading).

        ``sync=False`` (the next-save path): the checksum walk re-reads
        every checkpoint byte, so it runs on a background thread instead
        of stalling the training thread beyond Orbax's own barrier — by
        the following save interval the thread has long finished (the
        join is free). Restore/wait/close use ``sync=True``: manifests
        must be ON DISK before verify_step reads them."""
        if self._manifest_thread is not None:
            self._manifest_thread.join()
            self._manifest_thread = None
        if not self._pending_manifest:
            return
        self._mgr.wait_until_finished()
        pending, self._pending_manifest = self._pending_manifest, []
        if not self._is_primary():
            return
        if sync:
            for step in pending:
                self._write_manifest(step)
            return

        def _write_all():
            for step in pending:
                self._write_manifest(step)

        self._manifest_thread = threading.Thread(
            target=_write_all, name="ckpt-manifest", daemon=True
        )
        self._manifest_thread.start()

    def verify_step(self, step: int) -> str:
        """``"verified"`` (manifest matches), ``"corrupt"`` (manifest
        present but a file is missing/resized/bit-flipped), or ``"legacy"``
        (no manifest — an older build wrote it; restore decides by
        reading)."""
        d = self._step_path(step)
        mpath = os.path.join(d, MANIFEST_NAME)
        if not os.path.exists(mpath):
            return "legacy"
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (OSError, ValueError, KeyError, TypeError):
            return "corrupt"
        for rel, meta in files.items():
            path = os.path.join(d, rel)
            try:
                if os.path.getsize(path) != meta["size"]:
                    return "corrupt"
                if _file_crc32(path) != meta["crc32"]:
                    return "corrupt"
            except OSError:
                return "corrupt"
        return "verified"

    def quarantine_step(self, step: int, reason: str) -> str | None:
        """Move a torn/corrupt step dir whole into ``<dir>/quarantine/`` —
        out of the restore scan, preserved for autopsy. Multi-host safe: a
        concurrent peer's rename winning is the same outcome (ENOENT =
        already quarantined)."""
        return self._quarantine_path(self._step_path(step), reason, step=step)

    def _quarantine_path(self, src: str, reason: str,
                         step: int | None = None) -> str | None:
        qdir = os.path.join(str(self.directory), "quarantine")
        name = os.path.basename(src.rstrip(os.sep))
        dest = os.path.join(qdir, name)
        if os.path.exists(dest):
            dest = f"{dest}.{int(time.time() * 1000)}"
        try:
            os.makedirs(qdir, exist_ok=True)
            os.rename(src, dest)
        except OSError:
            return None  # a peer got there first (or src vanished)
        logger.warning(
            "checkpoint quarantined: %s -> %s (%s)", src, dest, reason
        )
        self._jevent("checkpoint.quarantine", step=step, reason=reason,
                     path=dest)
        # The writing manager caches its step list at construction; a step
        # quarantined out from under it would crash the NEXT save's
        # max_to_keep GC scan (reading metadata of a dir that moved).
        try:
            self._mgr.reload()
        except Exception:
            logger.exception("orbax manager reload after quarantine failed")
        return dest

    def _sweep_tmp_dirs(self) -> None:
        """Quarantine leftover ``*.orbax-checkpoint-tmp*`` wreckage — the
        footprint of a save that was mid-write when its process died
        (SIGKILL). Orbax never lists them as steps, but they hold disk and
        confuse operators; sweeping them is the 'zero manual cleanup' half
        of the kill-mid-save contract."""
        try:
            names = os.listdir(str(self.directory))
        except OSError:
            return
        for name in names:
            if "orbax-checkpoint-tmp" in name:
                self._quarantine_path(
                    os.path.join(str(self.directory), name),
                    "torn save (process died mid-write)",
                )

    def _apply_save_fault(self, fault, step: int) -> None:
        """Chaos drill support: make the just-issued save COMMIT, manifest
        it, then tear one file — the deterministic spelling of 'the process
        died mid-save / the storage lied'. ``kill`` then SIGKILLs self
        (journal already has chaos.inject + checkpoint.commit on disk);
        ``corrupt`` returns, leaving a silently corrupt newest step the
        next restore must detect and fall back from."""
        self._mgr.wait_until_finished()
        if self._manifest_thread is not None:
            # Drills want deterministic disk state at the kill: older
            # steps' manifests must not be mid-write when it lands.
            self._manifest_thread.join()
            self._manifest_thread = None
        self._pending_manifest = [s for s in self._pending_manifest
                                  if s != step]
        self._write_manifest(step)
        d = self._step_path(step)
        victim, vsize = None, -1
        for root, _dirs, names in os.walk(d):
            for name in sorted(names):
                if name == MANIFEST_NAME:
                    continue
                p = os.path.join(root, name)
                size = os.path.getsize(p)
                if size > vsize:
                    victim, vsize = p, size
        if victim is not None:
            with open(victim, "r+b") as f:
                f.truncate(max(0, vsize // 2))
            logger.error(
                "chaos: tore checkpoint step %d (%s truncated %d -> %d)",
                step, os.path.relpath(victim, d), vsize, max(0, vsize // 2),
            )
            self._jevent("checkpoint.torn", step=step,
                         file=os.path.relpath(victim, d))
        if fault.action == "kill":
            fault.kill_now()

    # -- save / restore ------------------------------------------------------

    def save(self, step: int, state: Any, data_iter: DataIterState) -> None:
        import orbax.checkpoint as ocp

        # Previous async save is done by now (Orbax serializes saves);
        # manifest it before committing new work (checksums run off-thread).
        self._flush_manifests(sync=False)
        fault = maybe_inject("ckpt.save", step=step, handles=("kill",))
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                data_iter=ocp.args.JsonSave(dataclasses.asdict(data_iter)),
            ),
        )
        self._pending_manifest.append(step)
        if fault is not None and fault.action in ("kill", "corrupt"):
            self._apply_save_fault(fault, step)
        logger.info("checkpoint save queued at step %d", step)

    def _restore_newest_verified(self, restore_fn):
        """The fallback walk both restore entry points share (module
        docstring): newest -> oldest, verify each step against its
        manifest, quarantine corrupt steps, attempt ``restore_fn(step)``,
        re-raise when VERIFIED bytes fail to restore (intact bytes mean a
        config mismatch or code bug — falling back would silently serve
        an older state than asked for), quarantine failing legacy steps.
        Returns ``(step, result, fell_back)``, or None when no restorable
        step remains."""
        fell_back = False
        for step in self._list_steps():
            status = self.verify_step(step)
            if status == "corrupt":
                self.quarantine_step(step, "integrity manifest mismatch")
                fell_back = True
                continue
            try:
                out = restore_fn(step)
            except Exception as e:
                if status == "verified":
                    raise  # intact bytes: the failure is not corruption
                self.quarantine_step(
                    step, f"restore failed: {type(e).__name__}: {e}"
                )
                fell_back = True
                continue
            return step, out, fell_back
        return None

    def restore_latest(self, abstract_state: Any) -> tuple[Any, DataIterState] | None:
        """Restore the newest VERIFIED checkpoint, sharded per
        ``abstract_state`` (a jax.eval_shape tree with shardings attached).
        Torn/corrupt newer steps are quarantined and skipped (module
        docstring); a step whose bytes verify but whose restore raises is a
        REAL error (config mismatch, code bug) and re-raises. Returns None
        if no restorable checkpoint exists."""
        import orbax.checkpoint as ocp

        maybe_inject("ckpt.restore")
        self._flush_manifests()
        self._sweep_tmp_dirs()
        hit = self._restore_newest_verified(
            lambda step: self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    data_iter=ocp.args.JsonRestore(),
                ),
            )
        )
        if hit is None:
            return None
        step, restored, fell_back = hit
        data_iter = DataIterState(**restored["data_iter"])
        logger.info(
            "restored checkpoint at step %d%s", step,
            " (fell back past quarantined step(s))" if fell_back else "",
        )
        self._jevent(
            "checkpoint.fallback_restore" if fell_back
            else "checkpoint.restore",
            step=step,
        )
        return restored["state"], data_iter

    def restore_latest_params(self, abstract_params: Any = None) -> Any | None:
        """Restore ONLY the ``params`` subtree of the newest checkpoint — the
        serving path (infer/server.py). Partial restore means the optimizer
        moments (2x the params for AdamW) are never read off storage, which is
        the difference between serving a 70B checkpoint and OOMing on it.
        ``abstract_params`` (a ``jax.eval_shape`` tree) is validated against
        the checkpoint metadata so a preset/checkpoint mismatch fails loudly
        here, not as a shape error mid-forward.

        Multi-process serving: when ``abstract_params`` leaves carry
        shardings (``jax.ShapeDtypeStruct(..., sharding=...)``), each process
        restores only its addressable shards of the global arrays — the
        cross-process mirror of how the checkpoint was written. Without
        shardings the restore yields host numpy (single-process serving)."""
        import orbax.checkpoint as ocp

        maybe_inject("ckpt.restore")
        self._flush_manifests()
        self._sweep_tmp_dirs()
        steps = self._list_steps()
        if not steps:
            return None
        # Manager-API route (no hand-built "{dir}/{step}/state" paths): a
        # READ-ONLY manager over the same directory whose "state" handler is
        # the PyTree one — partial restore is a PyTree-handler feature, and
        # the writing manager must keep exactly one handler per item (see
        # __init__). Read-only also means this reader can never garbage-
        # collect steps out from under the writer.
        registry = ocp.handlers.DefaultCheckpointHandlerRegistry()
        registry.add("state", ocp.args.PyTreeRestore,
                     ocp.PyTreeCheckpointHandler())
        reader = ocp.CheckpointManager(
            self.directory,
            handler_registry=registry,
            options=ocp.CheckpointManagerOptions(read_only=True),
        )
        try:
            hit = self._restore_newest_verified(
                lambda step: self._restore_params_via(
                    reader, step, abstract_params
                )
            )
            if hit is None:
                return None
            step, params, fell_back = hit
            self._jevent(
                "checkpoint.fallback_restore" if fell_back
                else "checkpoint.restore",
                step=step, params_only=True,
            )
            return params
        finally:
            reader.close()

    def _restore_params_via(self, reader, step: int, abstract_params):
        import jax
        import orbax.checkpoint as ocp

        meta = reader.item_metadata(step)["state"]
        # Orbax < 0.9 returns the metadata TREE directly; newer wraps it.
        meta_tree = meta if isinstance(meta, dict) else meta.tree
        if "params" not in meta_tree:
            raise ValueError(
                f"checkpoint step {step} in {self.directory} has no "
                "'params' subtree"
            )
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
            {"params": meta_tree["params"]},
        )
        restore_args = None
        if abstract_params is not None:
            expect = {
                jax.tree_util.keystr(p): l.shape
                for p, l in jax.tree_util.tree_leaves_with_path(abstract_params)
            }
            got = {
                jax.tree_util.keystr(p): l.shape
                for p, l in jax.tree_util.tree_leaves_with_path(abstract["params"])
            }
            if expect != got:
                missing = sorted(set(expect) - set(got))
                extra = sorted(set(got) - set(expect))
                shape_diff = sorted(
                    k for k in expect.keys() & got.keys() if expect[k] != got[k]
                )
                raise ValueError(
                    f"checkpoint at step {step} does not match the model config: "
                    f"missing={missing[:3]} extra={extra[:3]} shape_mismatch="
                    f"{[(k, expect[k], got[k]) for k in shape_diff[:3]]}"
                )
            if any(
                getattr(l, "sharding", None) is not None
                for l in jax.tree_util.tree_leaves(abstract_params)
            ):
                restore_args = {
                    "params": jax.tree.map(
                        lambda meta, user: ocp.ArrayRestoreArgs(
                            sharding=user.sharding,
                            global_shape=meta.shape,
                            dtype=meta.dtype,
                        )
                        if getattr(user, "sharding", None) is not None
                        else ocp.RestoreArgs(),
                        abstract["params"],
                        abstract_params,
                    )
                }
        try:
            restore = ocp.args.PyTreeRestore(
                item=abstract, restore_args=restore_args, partial_restore=True
            )
        except TypeError:
            # Older orbax spells partial restore as "transforms={}": only the
            # item's keys are read, everything else is dropped unread. That
            # spelling requires explicit restore_args for every leaf.
            restore = ocp.args.PyTreeRestore(
                item=abstract,
                restore_args=restore_args
                or jax.tree.map(lambda _: ocp.RestoreArgs(), abstract),
                transforms={},
            )
        restored = reader.restore(step, args=ocp.args.Composite(state=restore))
        logger.info("restored params (only) from checkpoint at step %d", step)
        return restored["state"]["params"]

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def close(self) -> None:
        try:
            self._flush_manifests()
        except Exception:
            # Close must succeed even when a final manifest cannot be
            # written (fs gone mid-teardown); the step just stays legacy.
            logger.exception("manifest flush failed during close")
        self._mgr.close()
