"""CLI: ``python -m ditl_tpu.analysis [--rule R]... [--json]``.

Exit codes: 0 clean, 1 violations, 2 usage/unknown-rule. Runs jax-free
(tier-1 pins it): the whole pass is ast over source text plus one lazy
import of the jax-free metric catalog.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ditl_tpu.analysis import RULES, run

ANALYSIS_SCHEMA = 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ditl_tpu.analysis",
        description="ditl_tpu invariant lint: static passes over the "
        "package tree (see docs/design.md 'Static analysis & "
        "invariant lint').",
    )
    parser.add_argument(
        "--rule", action="append", default=None, metavar="RULE",
        help="run only this rule (repeatable); default: all",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--root", default=None, metavar="DIR",
        help="package directory to analyze (default: the installed "
        "ditl_tpu package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}: {RULES[rid].doc}")
        return 0

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))
    try:
        diags = run(root, rules=args.rule)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "schema": ANALYSIS_SCHEMA,
            "root": root,
            "rules": sorted(args.rule) if args.rule else sorted(RULES),
            "clean": not diags,
            "violations": len(diags),
            "diagnostics": [d.as_dict() for d in diags],
        }, indent=2, sort_keys=True))
    else:
        for d in diags:
            print(d.format())
        n_rules = len(args.rule) if args.rule else len(RULES)
        if diags:
            print(f"\n{len(diags)} violation(s) across {n_rules} rule(s)")
        else:
            print(f"clean: {n_rules} rule(s), 0 violations")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
