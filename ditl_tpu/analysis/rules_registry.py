"""registry-mirror + metric-catalog: string registries stay single-source.

registry-mirror covers the two registries history shows drifting:

- **SLO classes.** ``infer/continuous.SLO_CLASSES`` is canonical (it IS
  the scheduler's rank order); ``gateway/admission.SLO_CLASS_NAMES`` and
  ``telemetry/serving.SLO_CLASS_NAMES`` are deliberate copies — the
  jax-free zones cannot import the engine module, so the invariant is
  EQUALITY (names and order), checked here instead of by the three-way
  runtime mirror test each suite re-declares.
- **Chaos sites.** ``chaos/plane.SITES`` is canonical. Every literal site
  passed to ``maybe_inject("<site>")`` anywhere in the tree must be a
  registered key (a typo'd seam silently never fires — the exact failure
  ``parse_rules`` learned to reject on the RULE side; this closes the
  CALL side), and every registered key must be consulted somewhere (a
  dead registry entry advertises a drill that tests nothing).

metric-catalog statically harvests metric-family literals from
``registry.counter/gauge/histogram(...)`` calls (resolving module-level
constant prefixes through f-strings) and asserts each is a family the
generated catalog (``telemetry/catalog.py``) knows — the no-server-needed
half of the live two-way drift guard in tests/test_metrics_catalog.py.
Dynamically-built names (per-replica, per-window) are unresolvable
statically and are skipped; the live guard still covers them.
"""

from __future__ import annotations

import ast

from ditl_tpu.analysis.core import (
    Diagnostic,
    Project,
    SourceFile,
    call_name,
    module_literal,
    rule,
)


def _literal_diag(project: Project, spec, what: str):
    rel, name = spec
    f = project.by_rel.get(rel)
    if f is None:
        return None, Diagnostic(
            "registry-mirror", f"{project.package}/{rel}", 1,
            f"{what} registry file {rel!r} is missing",
        )
    lit = module_literal(f, name)
    if lit is None:
        return None, Diagnostic(
            "registry-mirror", f.display, 1,
            f"{what} registry {name!r} not found as a module-level "
            f"literal in {rel}",
        )
    return (f, lit), None


@rule(
    "registry-mirror",
    "SLO-class mirrors must equal the canonical engine registry; chaos "
    "site literals at call sites must be registered in chaos/plane.SITES "
    "(and every registered site must be consulted)",
)
def check_registry_mirror(project: Project) -> list[Diagnostic]:
    s = project.settings
    out: list[Diagnostic] = []

    # -- SLO class mirrors -------------------------------------------------
    canon, err = _literal_diag(project, s.slo_canonical, "canonical SLO")
    if err is not None:
        out.append(err)
    else:
        (_, (canon_vals, _)) = canon
        for spec in s.slo_mirrors:
            mirror, err = _literal_diag(project, spec, "mirror SLO")
            if err is not None:
                out.append(err)
                continue
            (mf, (vals, lineno)) = mirror
            if tuple(vals) != tuple(canon_vals):
                out.append(Diagnostic(
                    "registry-mirror", mf.display, lineno,
                    f"{spec[1]} = {tuple(vals)!r} drifted from canonical "
                    f"{s.slo_canonical[0]}:{s.slo_canonical[1]} = "
                    f"{tuple(canon_vals)!r} (names AND order are "
                    "semantic: the tuple is the scheduler rank order)",
                ))

    # -- chaos sites: call-site literals <-> registry keys, both ways ------
    reg, err = _literal_diag(project, s.chaos_registry, "chaos-site")
    if err is not None:
        out.append(err)
        return out
    (reg_file, (site_keys, reg_line)) = reg
    sites = set(site_keys)
    consulted: set[str] = set()
    for f in project.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in s.chaos_consult_funcs:
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            consulted.add(arg.value)
            if arg.value not in sites:
                out.append(Diagnostic(
                    "registry-mirror", f.display, node.lineno,
                    f"chaos site {arg.value!r} is not registered in "
                    f"{s.chaos_registry[0]}:{s.chaos_registry[1]} — the "
                    "seam would silently never fire",
                ))
    for site in site_keys:
        if site not in consulted:
            out.append(Diagnostic(
                "registry-mirror", reg_file.display, reg_line,
                f"chaos site {site!r} is registered but no "
                f"{'/'.join(s.chaos_consult_funcs)} call consults it — "
                "a drill against it tests nothing",
            ))
    return out


# -- metric-catalog ---------------------------------------------------------


def _const_strings(f: SourceFile) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (f-string prefix
    resolution)."""
    out: dict[str, str] = {}
    for node in f.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve_name_arg(arg: ast.AST, consts: dict[str, str]) -> str | None:
    """A metric-name argument as a concrete string, or None when it is
    built dynamically (skipped; the live drift guard covers those)."""
    if isinstance(arg, ast.Constant):
        return arg.value if isinstance(arg.value, str) else None
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    if isinstance(arg, ast.JoinedStr):
        parts: list[str] = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            elif isinstance(piece, ast.FormattedValue):
                if (
                    isinstance(piece.value, ast.Name)
                    and piece.value.id in consts
                    and piece.conversion == -1
                    and piece.format_spec is None
                ):
                    parts.append(consts[piece.value.id])
                else:
                    return None
            else:
                return None
        return "".join(parts)
    return None


@rule(
    "metric-catalog",
    "metric-family literals registered via counter()/gauge()/histogram() "
    "must be families the generated catalog (telemetry/catalog.py) knows",
)
def check_metric_catalog(project: Project) -> list[Diagnostic]:
    s = project.settings
    if not s.catalog_module:
        return []
    # Lazy, jax-free import: the catalog is the single canonical family
    # registry (with its normalize rules); re-declaring it here would be
    # exactly the mirror drift this module polices.
    import importlib

    try:
        catalog = importlib.import_module(s.catalog_module)
    except ImportError:
        return [Diagnostic(
            "metric-catalog", s.catalog_module, 1,
            f"catalog module {s.catalog_module!r} is not importable",
        )]
    families = set(catalog.catalog_families())
    normalize = catalog.normalize_family
    out: list[Diagnostic] = []
    for f in project.files:
        consts = _const_strings(f)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            method = call_name(node)
            if method not in s.metric_methods:
                continue
            if not isinstance(node.func, ast.Attribute):
                continue  # bare counter(...) is not a registry call
            if not node.args:
                continue
            name = _resolve_name_arg(node.args[0], consts)
            if name is None:
                continue
            exposed = f"{name}_total" if method == "counter" else name
            if normalize(exposed) not in families:
                out.append(Diagnostic(
                    "metric-catalog", f.display, node.lineno,
                    f"metric family {exposed!r} is not in the generated "
                    "catalog (telemetry/catalog.py); add the row and "
                    "regenerate docs/metrics.md, or the docs drift",
                ))
    return out
