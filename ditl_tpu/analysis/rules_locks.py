"""lock-discipline: ``# guarded-by: <lock>`` attributes stay under their
lock.

The two lock races that reached review (the SLO alert double-fire, the
Retry-After deque snapshotted against concurrent appends) were both the
same shape: state with an owning lock touched on one path that forgot the
``with``. The fix each time was a code change plus a prose comment; this
rule turns the prose into a checked contract. Annotate the attribute's
defining assignment::

    self._samples = collections.deque()  # guarded-by: _lock

and every other read/write of ``self._samples`` in that class must sit
lexically inside ``with self._lock:`` (or ``with self._lock as ...:``,
or alongside other context managers in one ``with``). Exemptions:

- the defining method itself (construction happens before any thread can
  see the object);
- methods named ``*_locked`` — the existing convention for "caller holds
  the lock" (the suffix already tells a human; now it tells the
  analyzer);
- a reasoned pragma, for deliberate unguarded touches (benign racy
  fast-path reads a la double-checked locking).
"""

from __future__ import annotations

import ast
import re

from ditl_tpu.analysis.core import Diagnostic, Project, SourceFile, rule

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _guarded_attrs(f: SourceFile, cls: ast.ClassDef):
    """{attr: (lock, defining_function_id)} from trailing ``# guarded-by``
    comments on ``self.X = ...`` (in methods) and on class-body
    annotations (handler-style classes that declare attributes at class
    scope)."""
    guarded: dict[str, tuple[str, int | None]] = {}

    def note(attr: str, lineno: int, fn_id: int | None):
        if lineno <= len(f.lines):
            m = GUARDED_RE.search(f.lines[lineno - 1])
            if m:
                guarded[attr] = (m.group(1), fn_id)

    for item in cls.body:
        if isinstance(item, ast.AnnAssign):
            if isinstance(item.target, ast.Name):
                note(item.target.id, item.lineno, None)
        elif isinstance(item, ast.Assign):
            for t in item.targets:
                if isinstance(t, ast.Name):
                    note(t.id, item.lineno, None)
        elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for node in ast.walk(item):
                attr = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        attr = _self_attr(t) or attr
                elif isinstance(node, ast.AnnAssign):
                    attr = _self_attr(node.target)
                elif isinstance(node, ast.AugAssign):
                    attr = _self_attr(node.target)
                if attr is not None:
                    note(attr, node.lineno, id(item))
    return guarded


def _with_locks(node: ast.With) -> set[str]:
    """Lock attribute names this ``with`` acquires (``with self._lock:``,
    possibly among other items)."""
    out = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
        # with self._lock.acquire_timeout(...) style: take the base attr.
        elif isinstance(item.context_expr, ast.Call):
            base = item.context_expr.func
            if isinstance(base, ast.Attribute):
                attr = _self_attr(base.value)
                if attr is not None:
                    out.add(attr)
    return out


def _check_method(
    f: SourceFile,
    cls: ast.ClassDef,
    fn: ast.FunctionDef,
    guarded: dict[str, tuple[str, int | None]],
) -> list[Diagnostic]:
    out: list[Diagnostic] = []

    def visit(node: ast.AST, held: frozenset[str]):
        if isinstance(node, ast.With):
            inner = held | _with_locks(node)
            for item in node.items:
                visit(item.context_expr, held)
            for child in node.body:
                visit(child, inner)
            return
        attr = _self_attr(node)
        if attr is not None and attr in guarded:
            lock, _ = guarded[attr]
            if lock not in held:
                out.append(Diagnostic(
                    "lock-discipline", f.display, node.lineno,
                    f"{cls.name}.{attr} is guarded-by {lock} but touched "
                    f"outside `with self.{lock}` (in {fn.name}); hold the "
                    "lock, rename the method *_locked if the caller "
                    "holds it, or pragma a deliberate racy read",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset())
    return out


@rule(
    "lock-discipline",
    "attributes annotated `# guarded-by: <lock>` may only be accessed "
    "inside `with self.<lock>` in their class (methods named *_locked "
    "are caller-holds-lock by convention)",
)
def check_lock_discipline(project: Project) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for f in project.files:
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_attrs(f, cls)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if fn.name.endswith("_locked"):
                    continue
                # The defining method (construction) is exempt for the
                # attributes it defines; other guarded attrs still apply.
                scoped = {
                    attr: spec
                    for attr, spec in guarded.items()
                    if spec[1] != id(fn)
                }
                if scoped:
                    out.extend(_check_method(f, cls, fn, scoped))
    return out
