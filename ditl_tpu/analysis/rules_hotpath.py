"""blocking-transfer: no device syncs inside ``@hot_path`` functions.

The zero-device-sync contract is the repo's most-re-litigated invariant:
the PR 3 flush fix (one ``float()`` sync per metrics key -> one
``device_get`` per flush), the PR 10 five-device_get pin (the whole flight
plane armed adds ZERO blocking transfers), the harvest-batching comment in
continuous.py ("each separate fetch is a full round trip"). Those pins are
runtime monkeypatch counters; this rule makes the contract lexical — mark
the function ``@hot_path`` and every blocking spelling inside it is a
violation:

- ``jax.device_get(...)`` (any spelling ending in ``device_get``)
- ``<x>.block_until_ready()`` / ``<x>.item()``
- ``float(x)`` / ``int(x)`` / ``np.asarray(x)`` where ``x`` is a bare
  name, attribute, or subscript — the spellings that silently sync when
  ``x`` is a device array. A cast of a value that is provably host-side
  (a registry counter, a clock delta held in a local) earns a reasoned
  pragma; the pragma is the documentation that someone CHECKED.
"""

from __future__ import annotations

import ast

from ditl_tpu.analysis.core import (
    Diagnostic,
    Project,
    SourceFile,
    call_name,
    dotted,
    rule,
)

_CAST_FUNCS = {"float", "int"}
_ASARRAY_BASES = {"np", "numpy", "onp"}
_SYNC_METHODS = {"block_until_ready", "item"}


def _is_hot_path(fn: ast.AST, marker: str) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        name = dec.attr if isinstance(dec, ast.Attribute) else (
            dec.id if isinstance(dec, ast.Name) else ""
        )
        if name == marker:
            return True
    return False


def _variable_like(node: ast.AST) -> bool:
    """Arguments that could be a device array reference: a name, an
    attribute chain, or a subscript. Constants and call results of host
    helpers are not flagged (``int(len(q))``, ``float(time.time())``)."""
    return isinstance(node, (ast.Name, ast.Attribute, ast.Subscript))


def _check_body(
    f: SourceFile, fn: ast.FunctionDef, qualname: str
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name == "device_get":
            out.append(Diagnostic(
                "blocking-transfer", f.display, node.lineno,
                f"jax.device_get inside @hot_path {qualname}: batch the "
                "fetch outside the hot path (PR 3 flush discipline)",
            ))
        elif (
            name in _SYNC_METHODS
            and isinstance(node.func, ast.Attribute)
            and not node.args
            and not node.keywords
        ):
            out.append(Diagnostic(
                "blocking-transfer", f.display, node.lineno,
                f".{name}() inside @hot_path {qualname}: blocks until "
                "the device materializes the value",
            ))
        elif (
            name in _CAST_FUNCS
            and isinstance(node.func, ast.Name)
            and len(node.args) == 1
            and _variable_like(node.args[0])
        ):
            arg = dotted(node.args[0]) or "<expr>"
            out.append(Diagnostic(
                "blocking-transfer", f.display, node.lineno,
                f"{name}({arg}) inside @hot_path {qualname}: a device "
                "array here is a hidden sync; if the value is provably "
                "host-side, say so with a pragma",
            ))
        elif (
            name == "asarray"
            and isinstance(node.func, ast.Attribute)
            and dotted(node.func.value) in _ASARRAY_BASES
            and node.args
            and _variable_like(node.args[0])
        ):
            arg = dotted(node.args[0]) or "<expr>"
            out.append(Diagnostic(
                "blocking-transfer", f.display, node.lineno,
                f"np.asarray({arg}) inside @hot_path {qualname}: "
                "device->host copy on the no-sync path",
            ))
    return out


@rule(
    "blocking-transfer",
    "functions marked @hot_path must not contain blocking device-transfer "
    "spellings (device_get / block_until_ready / item / "
    "float/int/np.asarray on variables)",
)
def check_blocking_transfer(project: Project) -> list[Diagnostic]:
    marker = project.settings.hot_path_decorator
    out: list[Diagnostic] = []
    for f in project.files:
        # Methods get their class name in the message; everything else is
        # reported bare.
        method_ids: set[int] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    method_ids.add(id(item))
                    if _is_hot_path(item, marker):
                        out.extend(_check_body(
                            f, item, f"{node.name}.{item.name}"
                        ))
        for node in ast.walk(f.tree):
            if _is_hot_path(node, marker) and id(node) not in method_ids:
                out.extend(_check_body(f, node, node.name))
    return out
