"""config-drift: every ``*Config`` knob is reachable and documented.

Two drifts this kills:

- **Unreachable sections.** ``parse_overrides`` reaches exactly the
  dataclass fields of the sections hung off ``Config`` — a new
  ``FooConfig`` that never becomes a ``Config`` field is dead weight the
  CLI cannot set (``foo.bar=x`` raises "unknown config section").
- **Undocumented knobs.** A field that appears in no documentation is a
  knob operators discover by reading source — ISSUE 11 calls these out
  as a standing violation class. A field counts as documented when its
  name appears in docs/design.md (the config reference appendix is the
  natural home) or when its ``field(metadata={"doc": ...})`` carries the
  one-liner inline.
"""

from __future__ import annotations

import ast
import re

from ditl_tpu.analysis.core import Diagnostic, Project, rule


def _has_doc_metadata(value: ast.AST | None) -> bool:
    """``field(..., metadata={"doc": "..."} )`` on the default value."""
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    if name != "field":
        return False
    for kw in value.keywords:
        if kw.arg == "metadata" and isinstance(kw.value, ast.Dict):
            for key in kw.value.keys:
                if isinstance(key, ast.Constant) and key.value == "doc":
                    return True
    return False


@rule(
    "config-drift",
    "every *Config dataclass must be reachable by the dotted-override "
    "parser, and every field must be mentioned in the docs or carry "
    "field metadata doc",
)
def check_config_drift(project: Project) -> list[Diagnostic]:
    s = project.settings
    f = project.by_rel.get(s.config_module)
    if f is None:
        return [Diagnostic(
            "config-drift", f"{project.package}/{s.config_module}", 1,
            f"config module {s.config_module!r} not found",
        )]
    docs = project.doc_text()
    out: list[Diagnostic] = []
    config_classes = [
        node for node in f.tree.body
        if isinstance(node, ast.ClassDef) and node.name.endswith("Config")
    ]
    root = next(
        (c for c in config_classes if c.name == "Config"), None
    )
    # Section annotations on the root Config: which *Config types the
    # dotted parser can reach (`section.key=value`).
    reachable_types: set[str] = set()
    if root is not None:
        for item in root.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.annotation, ast.Name
            ):
                reachable_types.add(item.annotation.id)
    for cls in config_classes:
        if cls.name != "Config" and cls.name not in reachable_types:
            out.append(Diagnostic(
                "config-drift", f.display, cls.lineno,
                f"{cls.name} is not a field of Config — no dotted "
                "override (`section.key=value`) can reach it",
            ))
        for item in cls.body:
            if not (
                isinstance(item, ast.AnnAssign)
                and isinstance(item.target, ast.Name)
            ):
                continue
            name = item.target.id
            if _has_doc_metadata(item.value):
                continue
            if re.search(rf"\b{re.escape(name)}\b", docs):
                continue
            out.append(Diagnostic(
                "config-drift", f.display, item.lineno,
                f"{cls.name}.{name} is not mentioned in "
                f"{'/'.join(s.docs)} and has no field metadata doc — "
                "an operator cannot discover this knob",
            ))
    return out
