"""import-layering: the jax-free zones stay jax-free, transitively.

The gateway/telemetry/chaos/client layers (and this analyzer) are jax-free
on import by design — a gateway is a thin front process, a pragma'd lazy
import is a deliberate exception, and one stray top-level ``import jax``
(or an innocent-looking internal import whose TRANSITIVE closure reaches
jax) silently makes the whole layer un-runnable without an accelerator
runtime. tests/test_tracing.py pinned this with a subprocess smoke since
ISSUE 6; this rule proves it over the module-level import graph instead —
every module, every chain, no interpreter launch — and the smoke stays as
the belt-and-suspenders check.

Checked per zone module:
- module-level ``import jax`` / ``from jax import ...`` (direct);
- module-level internal imports whose transitive module-level closure
  reaches a forbidden module (the chain is printed);
- function-level (lazy) forbidden imports — allowed, but only with a
  reasoned pragma (they are invisible to the import-time smoke, so the
  exception must be auditable in the source).

``if TYPE_CHECKING:`` blocks are excluded — they never execute.
"""

from __future__ import annotations

import ast

from ditl_tpu.analysis.core import Diagnostic, Project, SourceFile, rule


def _is_type_checking(test: ast.AST) -> bool:
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
        isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
    )


def _module_level_imports(f: SourceFile):
    """(node, lineno) for every import executed at module import time:
    top-level statements, including those under plain if/try at module
    scope and in class bodies, excluding TYPE_CHECKING guards and
    function bodies."""
    out = []

    def walk(stmts):
        for node in stmts:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                out.append(node)
            elif isinstance(node, ast.If):
                if _is_type_checking(node.test):
                    walk(node.orelse)
                else:
                    walk(node.body)
                    walk(node.orelse)
            elif isinstance(node, ast.Try):
                walk(node.body)
                for h in node.handlers:
                    walk(h.body)
                walk(node.orelse)
                walk(node.finalbody)
            elif isinstance(node, ast.ClassDef):
                walk(node.body)
            elif isinstance(node, (ast.With,)):
                walk(node.body)

    walk(f.tree.body)
    return out


def _type_checking_imports(f: SourceFile) -> set[int]:
    """Imports under ``if TYPE_CHECKING:`` anywhere — they never execute,
    so they are neither module-level nor lazy."""
    out: set[int] = set()
    for node in ast.walk(f.tree):
        if isinstance(node, ast.If) and _is_type_checking(node.test):
            for child in node.body:
                for sub in ast.walk(child):
                    if isinstance(sub, (ast.Import, ast.ImportFrom)):
                        out.add(id(sub))
    return out


def _function_level_imports(f: SourceFile):
    """Imports NOT in the module-level set (lazy, inside function
    bodies); TYPE_CHECKING-guarded imports are excluded entirely."""
    skip = set(map(id, _module_level_imports(f)))
    skip |= _type_checking_imports(f)
    return [
        node
        for node in ast.walk(f.tree)
        if isinstance(node, (ast.Import, ast.ImportFrom))
        and id(node) not in skip
    ]


def _targets(f: SourceFile, node, project: Project) -> list[str]:
    """Dotted module names one import statement pulls in (absolute)."""
    if isinstance(node, ast.Import):
        return [alias.name for alias in node.names]
    # ImportFrom: resolve relative level against this module's package.
    base = node.module or ""
    if node.level:
        parts = f.module.split(".")
        if not f.rel.endswith("__init__.py"):
            parts = parts[:-1]
        parts = parts[: len(parts) - (node.level - 1)]
        base = ".".join(parts + ([node.module] if node.module else []))
    out = [base] if base else []
    # `from pkg.sub import name` imports pkg.sub.name when it is a module.
    for alias in node.names:
        cand = f"{base}.{alias.name}" if base else alias.name
        if cand in project.by_module:
            out.append(cand)
    return out


def _build_graph(project: Project):
    """module -> list[(target, lineno)] over module-level imports, plus
    implicit parent-package edges (importing a.b.c executes a and a.b)."""
    graph: dict[str, list[tuple[str, int]]] = {}
    for f in project.files:
        edges: list[tuple[str, int]] = []
        for node in _module_level_imports(f):
            for target in _targets(f, node, project):
                edges.append((target, node.lineno))
        parts = f.module.split(".")
        for i in range(1, len(parts)):
            parent = ".".join(parts[:i])
            if parent in project.by_module:
                edges.append((parent, 1))
        graph[f.module] = edges
    return graph


def _forbidden_root(name: str, forbidden: tuple[str, ...]) -> str | None:
    root = name.split(".")[0]
    return root if root in forbidden else None


def _taint_chains(project: Project, graph) -> dict[str, list[str]]:
    """module -> shortest chain [module, ..., 'jax'] for every internal
    module whose module-level closure reaches a forbidden import."""
    s = project.settings
    chains: dict[str, list[str]] = {}
    # Seed: modules with a direct forbidden module-level import.
    for mod, edges in graph.items():
        for target, _ in edges:
            root = _forbidden_root(target, s.forbidden_imports)
            if root is not None:
                chains.setdefault(mod, [mod, root])
    # Propagate backwards over internal edges to a fixpoint (graph is
    # small; repeated sweeps beat building a reverse index).
    changed = True
    while changed:
        changed = False
        for mod, edges in graph.items():
            if mod in chains:
                continue
            for target, _ in edges:
                if target in chains:
                    chains[mod] = [mod, *chains[target]]
                    changed = True
                    break
    return chains


@rule(
    "import-layering",
    "jax-free zones (telemetry/gateway/chaos/client/analysis) must not "
    "reach jax/jaxlib through module-level imports, transitively; lazy "
    "in-function imports need a reasoned pragma",
)
def check_import_layering(project: Project) -> list[Diagnostic]:
    s = project.settings
    zones = tuple(
        f"{project.package}.{z}" for z in s.jax_free_zones
    )
    graph = _build_graph(project)
    chains = _taint_chains(project, graph)
    out: list[Diagnostic] = []
    for f in project.files:
        in_zone = any(
            f.module == z or f.module.startswith(z + ".") for z in zones
        )
        if not in_zone:
            continue
        for node in _module_level_imports(f):
            for target in _targets(f, node, project):
                root = _forbidden_root(target, s.forbidden_imports)
                if root is not None:
                    out.append(Diagnostic(
                        "import-layering", f.display, node.lineno,
                        f"module-level import of {root!r} in jax-free "
                        f"zone module {f.module}",
                    ))
                elif target in chains and target != f.module:
                    chain = " -> ".join(chains[target])
                    out.append(Diagnostic(
                        "import-layering", f.display, node.lineno,
                        f"import of {target!r} pulls a forbidden module "
                        f"into jax-free zone {f.module}: {chain}",
                    ))
        for node in _function_level_imports(f):
            for target in _targets(f, node, project):
                root = _forbidden_root(target, s.forbidden_imports)
                if root is not None:
                    out.append(Diagnostic(
                        "import-layering", f.display, node.lineno,
                        f"lazy {root!r} import inside jax-free zone "
                        f"module {f.module}: allowed only with "
                        "`# ditl: allow(import-layering) -- <reason>`",
                    ))
    return out
