"""thread-hygiene: no thread or executor that can wedge process exit.

Every long-lived thread in this tree is ``daemon=True`` plus an explicit
join/stop path, and both gateway fan-out pools learned (twice, in PR 7
review rounds) that an executor without ``shutdown`` in a ``finally``
re-wedges exactly the path it was built to bound. Checked:

- ``threading.Thread(...)`` must pass ``daemon=`` explicitly, or the
  created thread must have a visible ``.join(`` path in the same file
  (matched on the variable/attribute it is assigned to). An anonymous
  non-daemon ``Thread(...).start()`` is always a violation — nothing can
  ever join it.
- ``ThreadPoolExecutor``/``ProcessPoolExecutor`` must be used as a
  context manager (``with``) or have ``<name>.shutdown(`` inside some
  ``finally`` block of the same file.
"""

from __future__ import annotations

import ast
import re

from ditl_tpu.analysis.core import (
    Diagnostic,
    Project,
    SourceFile,
    call_name,
    rule,
)

_EXECUTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}


def _assigned_name(f: SourceFile, node: ast.Call) -> str | None:
    """The simple name/attr a call's result is bound to, found by scanning
    assignments whose value is (or contains at top level) this call."""
    for stmt in ast.walk(f.tree):
        if isinstance(stmt, ast.Assign) and stmt.value is node:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is node:
            if isinstance(stmt.target, ast.Name):
                return stmt.target.id
            if isinstance(stmt.target, ast.Attribute):
                return stmt.target.attr
    return None


def _finally_sources(f: SourceFile) -> str:
    chunks = []
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                chunks.append(f.segment(stmt))
    return "\n".join(chunks)


def _with_context_ids(f: SourceFile) -> set[int]:
    out = set()
    for node in ast.walk(f.tree):
        if isinstance(node, ast.With):
            for item in node.items:
                out.add(id(item.context_expr))
    return out


@rule(
    "thread-hygiene",
    "threading.Thread needs daemon= or a join path; executors need a "
    "`with` block or shutdown() in a finally",
)
def check_thread_hygiene(project: Project) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for f in project.files:
        finally_src = None
        with_ids = None
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "Thread":
                if any(kw.arg == "daemon" for kw in node.keywords):
                    continue
                bound = _assigned_name(f, node)
                if bound is not None and re.search(
                    rf"\b{re.escape(bound)}\s*\.\s*join\s*\(", f.text
                ):
                    continue
                what = (
                    f"thread bound to {bound!r} has no .join( path"
                    if bound is not None
                    else "anonymous thread can never be joined"
                )
                out.append(Diagnostic(
                    "thread-hygiene", f.display, node.lineno,
                    f"threading.Thread without daemon=: {what}; a "
                    "non-daemon thread here can wedge process exit",
                ))
            elif name in _EXECUTORS:
                if with_ids is None:
                    with_ids = _with_context_ids(f)
                if id(node) in with_ids:
                    continue
                bound = _assigned_name(f, node)
                if finally_src is None:
                    finally_src = _finally_sources(f)
                if bound is not None and re.search(
                    rf"\b{re.escape(bound)}\s*\.\s*shutdown\s*\(",
                    finally_src,
                ):
                    continue
                out.append(Diagnostic(
                    "thread-hygiene", f.display, node.lineno,
                    f"{name} is neither a `with` context nor shut down "
                    "in a finally — a wedged task leaks the pool (the "
                    "PR 7 gateway fan-out lesson, twice)",
                ))
    return out
