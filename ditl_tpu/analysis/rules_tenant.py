"""tenant-label-discipline: raw tenant identity never reaches telemetry.

Tenants are keyed on raw ``Authorization: Bearer`` credentials
(gateway/admission.py); everything observable — metric families, /usage
rollups, journal/ledger rows, incident manifests — must carry only the
credential-safe label (``tenant_label``'s sha digest or a
``sanitize_label``-reduced configured name). The runtime halves of that
invariant exist since ISSUE 4 ("raw API keys never leave this module");
this pass is the STATIC half (ISSUE 15 satellite): at every telemetry
sink call — ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` /
``<journal>.event(...)`` — no argument expression may mention a
raw-identity variable (``bearer``/``api_key``/``authorization`` spellings,
or a bare ``tenant``/``*_tenant`` name) unless that mention sits inside a
``tenant_label(...)`` or ``sanitize_label(...)`` wrapping call.

Lexical by design, like lock-discipline: the rule judges NAMES, so code
that launders a credential through an innocently-named variable escapes
it — the runtime guards still stand behind it. The payoff is the common
failure: someone threading ``tenant`` (which IS the raw bearer at the
gateway) straight into a metric family or a journal row.
"""

from __future__ import annotations

import ast

from ditl_tpu.analysis.core import (
    Diagnostic,
    Project,
    call_name,
    rule,
)


def _suspicious(identifier: str, settings) -> bool:
    low = identifier.lower()
    if any(marker in low for marker in settings.tenant_raw_markers):
        return True
    return low in settings.tenant_raw_names or low.endswith("_tenant")


def _terminal_names(node: ast.AST):
    """Every Name / Attribute-terminal identifier in a subtree, paired
    with its node (f-string values included — ast.walk descends into
    FormattedValue)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id, sub
        elif isinstance(sub, ast.Attribute):
            yield sub.attr, sub


@rule(
    "tenant-label-discipline",
    "raw bearer/tenant identifiers must pass through tenant_label()/"
    "sanitize_label() before reaching counter()/gauge()/histogram()/"
    ".event() telemetry sinks (the static half of the ISSUE 4 'raw API "
    "keys never leave' invariant)",
)
def check_tenant_label_discipline(project: Project) -> list[Diagnostic]:
    s = project.settings
    out: list[Diagnostic] = []
    for f in project.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in s.tenant_sink_calls:
                continue
            # Names inside a sanctioning wrapper call anywhere in the
            # argument subtree are laundered — collect them first so
            # `counter(f"x_{sanitize_label(tenant)}")` stays clean while
            # the unwrapped spelling fires.
            sanctioned: set[int] = set()
            roots = list(node.args) + [kw.value for kw in node.keywords]
            for root in roots:
                for sub in ast.walk(root):
                    if (isinstance(sub, ast.Call)
                            and call_name(sub) in s.tenant_label_funcs):
                        for inner in ast.walk(sub):
                            sanctioned.add(id(inner))
            for root in roots:
                for identifier, name_node in _terminal_names(root):
                    if id(name_node) in sanctioned:
                        continue
                    if not _suspicious(identifier, s):
                        continue
                    out.append(Diagnostic(
                        "tenant-label-discipline", f.display,
                        name_node.lineno,
                        f"raw tenant identity {identifier!r} reaches a "
                        f"{call_name(node)}() telemetry sink — wrap it in "
                        "tenant_label(...)/sanitize_label(...) (raw API "
                        "keys must never leave the admission layer)",
                    ))
    return out
