"""event-loop-hygiene: nothing that blocks may run on the event loop.

The gateway's evloop data plane (ISSUE 17) multiplexes every client
connection and every detached SSE stream onto ONE thread — a single
blocking call there is not one slow request, it is a full-gateway stall
(every open stream stops moving bytes at once; troubleshooting §35 is
the runtime signature, ``ditl_gateway_loop_tick_p95_s`` spiking). Mark a
function ``@event_loop`` (ditl_tpu/annotations.py) and every blocking
spelling inside it is a violation:

- ``sleep(...)`` in any spelling (``time.sleep``, a bare ``sleep``) —
  the loop sleeps only inside ``selector.select``;
- ``<x>.sendall(...)`` — blocks (or raises ``BlockingIOError`` mid-write,
  tearing the stream) regardless of socket mode; loop code uses ``send``
  with explicit partial-write buffering;
- ``<x>.join(...)`` — waiting for a thread/future on the loop deadlocks
  the moment that thread needs the loop to make progress;
- ``with self.<lock>:`` where the attribute looks lock-like (contains
  ``lock`` or ``cond``) and the line carries no ``# guarded-by:``
  witness — an uncontended lock is cheap, but a lock shared with worker
  threads is an unbounded wait; the witness comment is the claim that
  someone CHECKED the hold times on the other side. Cross-thread
  handoff in loop code uses ``collections.deque`` (atomic
  append/popleft) plus a wakeup byte instead.

Deliberately NOT flagged: ``.recv(`` / ``.send(`` / ``.accept(`` —
loop-owned sockets are non-blocking by construction
(``setblocking(False)`` at accept/detach), so these return immediately;
flagging them would force a pragma onto every legitimate readiness-driven
read. The flagged spellings block no matter what mode the fd is in.

Registered callbacks (ISSUE 18): the marker is not the only way onto the
loop. A callable handed to a registration-shaped call
(``.add_done_callback(cb)``, ``.call_soon(cb)``, ``.add_reader(fd, cb)``,
…) runs in loop context without any decorator — exactly where the
runtime watchdog keeps convicting stalls the static pass missed. The
rule resolves same-file targets (a module function by name, a
``self.<method>`` of the enclosing class, an inline ``lambda``) and
holds their bodies to the same blocking-spelling standard. Targets it
cannot see (imported callables, call results) stay silent — runtime
conviction, not this rule, is their guard. ``@event_loop``-marked
targets are skipped (already checked once); the pragma escape stays
reason-mandatory as everywhere else.
"""

from __future__ import annotations

import ast

from ditl_tpu.analysis.core import (
    Diagnostic,
    Project,
    SourceFile,
    call_name,
    rule,
)
from ditl_tpu.analysis.rules_locks import GUARDED_RE, _self_attr

_BLOCKING_METHODS = {"sendall", "join"}

# Registration-shaped method names whose callable arguments run in loop
# context (concurrent.futures / asyncio / selector-loop idioms).
# Deliberately NOT ``register``: selector.register takes opaque data, and
# atexit.register callbacks never touch the loop.
_REGISTRATION_METHODS = {
    "add_done_callback", "add_callback", "call_soon", "call_later",
    "call_at", "add_reader", "add_writer",
}


def _is_event_loop(fn: ast.AST, marker: str) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        name = dec.attr if isinstance(dec, ast.Attribute) else (
            dec.id if isinstance(dec, ast.Name) else ""
        )
        if name == marker:
            return True
    return False


def _lockish(attr: str) -> bool:
    low = attr.lower()
    return "lock" in low or "cond" in low


def _check_body(
    f: SourceFile, fn: ast.AST, qualname: str,
    kind: str = "@event_loop",
) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name == "sleep":
                out.append(Diagnostic(
                    "event-loop-hygiene", f.display, node.lineno,
                    f"sleep inside {kind} {qualname}: the loop may "
                    "only wait inside selector.select — a sleep here "
                    "stalls every open connection and stream",
                ))
            elif (
                name in _BLOCKING_METHODS
                and isinstance(node.func, ast.Attribute)
            ):
                hint = (
                    "use send with partial-write buffering"
                    if name == "sendall"
                    else "hand the wait to a worker, never the loop"
                )
                out.append(Diagnostic(
                    "event-loop-hygiene", f.display, node.lineno,
                    f".{name}() inside {kind} {qualname}: blocks "
                    f"the loop regardless of socket mode; {hint}",
                ))
        elif isinstance(node, ast.With):
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is None or not _lockish(attr):
                    continue
                line = f.lines[node.lineno - 1] \
                    if node.lineno <= len(f.lines) else ""
                if GUARDED_RE.search(line):
                    # A witness names the guarded state: someone checked
                    # the other side's hold times (lock-discipline's own
                    # grammar, reused as the sanction here).
                    continue
                out.append(Diagnostic(
                    "event-loop-hygiene", f.display, node.lineno,
                    f"with self.{attr} inside {kind} {qualname}: a "
                    "lock shared with workers is an unbounded wait on "
                    "the loop; prefer a deque handoff, or witness the "
                    "bounded hold with `# guarded-by: <state>`",
                ))
    return out


def _check_registered_callbacks(
    f: SourceFile, marker: str
) -> list[Diagnostic]:
    """ISSUE 18: hold callables *registered* as loop callbacks to the
    blocking-spelling standard, decorator or not. Resolution is same-file
    only — a module function by name, a ``self.<method>`` of the
    enclosing class, or an inline lambda; anything else is invisible to a
    single-file pass and left to the runtime watchdog."""
    module_fns = {
        n.name: n for n in f.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    class_methods: dict[str, dict[str, ast.AST]] = {}
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ClassDef):
            class_methods[node.name] = {
                item.name: item for item in node.body
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
            }

    out: list[Diagnostic] = []
    seen: set[int] = set()

    def visit(node: ast.AST, cls: str | None) -> None:
        if isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                visit(child, node.name)
            return
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REGISTRATION_METHODS
        ):
            reg = node.func.attr
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                if isinstance(arg, ast.Lambda):
                    out.extend(_check_body(
                        f, arg, f"<lambda> passed to .{reg}()",
                        kind="loop callback",
                    ))
                    continue
                target, qualname = None, ""
                if isinstance(arg, ast.Name):
                    target = module_fns.get(arg.id)
                    qualname = arg.id
                elif (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                    and cls is not None
                ):
                    target = class_methods.get(cls, {}).get(arg.attr)
                    qualname = f"{cls}.{arg.attr}"
                if target is None or id(target) in seen:
                    continue
                seen.add(id(target))
                if _is_event_loop(target, marker):
                    continue  # already held by the decorator pass
                out.extend(_check_body(
                    f, target, f"{qualname} (registered via .{reg}())",
                    kind="loop callback",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, cls)

    visit(f.tree, None)
    return out


@rule(
    "event-loop-hygiene",
    "functions marked @event_loop — and callables registered as loop "
    "callbacks — must not contain blocking spellings "
    "(sleep / .sendall / .join / un-witnessed lock waits)",
)
def check_event_loop_hygiene(project: Project) -> list[Diagnostic]:
    marker = project.settings.event_loop_decorator
    out: list[Diagnostic] = []
    for f in project.files:
        method_ids: set[int] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    method_ids.add(id(item))
                    if _is_event_loop(item, marker):
                        out.extend(_check_body(
                            f, item, f"{node.name}.{item.name}"
                        ))
        for node in ast.walk(f.tree):
            if _is_event_loop(node, marker) and id(node) not in method_ids:
                out.extend(_check_body(f, node, node.name))
        out.extend(_check_registered_callbacks(f, marker))
    return out
