"""Invariant lint plane (ISSUE 11): the repo's hard-won runtime rules as
machine-checked static passes.

Ten PRs of history keep re-finding the same invariant classes the hard way —
jax leaking into the provably-jax-free zones, stray device syncs on hot
paths, lock-guarded state touched outside its lock, name registries
hand-mirrored in three places drifting apart, chaos sites that silently
never fire, config knobs nobody documented. Each was pinned after the fact
by a one-off runtime test. This package makes them *compile-time*
properties of the tree instead: a self-contained, stdlib-only (ast +
module-graph) analyzer with a rule registry, file/line diagnostics, a
pragma escape hatch with a mandatory reason, and a CLI that exits non-zero
on any violation — the way production stacks wire sanitizers and custom
lints into CI rather than re-deriving discipline per change.

The analyzer must pass its own rules: nothing here imports jax (the
import-layering zone covers ``analysis/`` itself), and nothing here spawns
threads. Rule modules may lazily import other *jax-free* ditl_tpu modules
(e.g. ``telemetry.catalog``) when a rule checks against a registry that
already has one canonical home — re-declaring the registry here would be
exactly the mirror drift the rules exist to kill.

Usage::

    python -m ditl_tpu.analysis              # whole tree, exit 1 on violation
    python -m ditl_tpu.analysis --rule lock-discipline --json
    from ditl_tpu.analysis import run        # library entry (tests, bench)

Suppressing a finding (reason MANDATORY — a bare pragma is itself a
violation)::

    x = float(host_val)  # ditl: allow(blocking-transfer) -- host float, no sync
"""

from __future__ import annotations

from ditl_tpu.annotations import event_loop, hot_path
from ditl_tpu.analysis.core import (
    RULES,
    Diagnostic,
    Project,
    Settings,
    rule,
    run,
)

# Importing the rule modules registers their rules with the registry.
from ditl_tpu.analysis import (  # noqa: E402,F401  (registration side effect)
    rules_config,
    rules_evloop,
    rules_hotpath,
    rules_imports,
    rules_locks,
    rules_registry,
    rules_tenant,
    rules_threads,
)

__all__ = [
    "Diagnostic",
    "Project",
    "RULES",
    "Settings",
    "event_loop",
    "hot_path",
    "rule",
    "run",
]
