"""Analyzer framework: source loading, pragma grammar, rule registry,
runner. Stdlib-only (ast + os + re); nothing here imports jax — the
analyzer passes its own import-layering rule.

Pragma grammar (reason MANDATORY)::

    # ditl: allow(<rule>[, <rule>...]) -- <reason>

A pragma on the violating line suppresses that line; a pragma on its own
line suppresses the NEXT line (so long call expressions can carry the
pragma above them). A pragma with an empty reason, an unknown rule id, or
one that suppresses nothing is itself reported under the ``pragma`` rule —
the escape hatch is audited, not free.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

__all__ = [
    "Diagnostic",
    "Pragma",
    "Project",
    "RULES",
    "Rule",
    "Settings",
    "SourceFile",
    "rule",
    "run",
]

PRAGMA_RE = re.compile(
    r"#\s*ditl:\s*allow\(\s*([^)]*?)\s*\)\s*(?:--\s*(.*?))?\s*$"
)


@dataclass(frozen=True)
class Diagnostic:
    """One violation: rule id + file/line + human message. ``path`` is
    package-relative with the package name prefixed (clickable from the
    repo root)."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path,
            "line": self.line, "message": self.message,
        }


@dataclass
class Pragma:
    line: int
    rules: tuple[str, ...]
    reason: str
    own_line: bool  # comment-only line: also covers the next line
    used: bool = False

    def covers(self, rule_id: str, line: int) -> bool:
        if rule_id not in self.rules:
            return False
        return line == self.line or (self.own_line and line == self.line + 1)


class SourceFile:
    """One parsed module: AST + raw lines + pragmas + dotted module name."""

    def __init__(self, path: str, rel: str, module: str, display: str):
        self.path = path
        self.rel = rel  # package-dir-relative, forward slashes
        self.module = module  # dotted ("ditl_tpu.infer.continuous")
        self.display = display  # "ditl_tpu/infer/continuous.py"
        with open(path, encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        # Pragmas live in real COMMENT tokens only — a docstring or a
        # diagnostic message QUOTING the grammar must not register one.
        self.pragmas: list[Pragma] = []
        for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = PRAGMA_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            reason = (m.group(2) or "").strip()
            line, col = tok.start
            own = self.lines[line - 1][:col].strip() == ""
            self.pragmas.append(Pragma(line, rules, reason, own))

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.text, node) or ""


@dataclass(frozen=True)
class Settings:
    """What the rules check against. Defaults describe the real tree;
    fixture tests construct their own pointing at miniature packages
    (tests/fixtures/analysis/) so every rule is exercised against a known
    violation without planting one in the product code."""

    # -- import-layering ---------------------------------------------------
    # Sub-package prefixes (package-relative) that must stay jax-free on
    # import, transitively over module-level imports.
    jax_free_zones: tuple[str, ...] = (
        "telemetry", "gateway", "chaos", "client", "analysis",
    )
    forbidden_imports: tuple[str, ...] = ("jax", "jaxlib")
    # -- blocking-transfer -------------------------------------------------
    hot_path_decorator: str = "hot_path"
    # -- event-loop-hygiene (ISSUE 17) -------------------------------------
    event_loop_decorator: str = "event_loop"
    # -- registry-mirror ---------------------------------------------------
    # (file, variable): the canonical registry and its hand-written mirrors
    # (mirrors exist on purpose — the jax-free zones cannot import the
    # canonical module — so EQUALITY is the checked invariant).
    slo_canonical: tuple[str, str] = ("infer/continuous.py", "SLO_CLASSES")
    slo_mirrors: tuple[tuple[str, str], ...] = (
        ("gateway/admission.py", "SLO_CLASS_NAMES"),
        ("telemetry/serving.py", "SLO_CLASS_NAMES"),
    )
    chaos_registry: tuple[str, str] = ("chaos/plane.py", "SITES")
    chaos_consult_funcs: tuple[str, ...] = ("maybe_inject",)
    # -- config-drift ------------------------------------------------------
    config_module: str = "config.py"  # package-relative
    docs: tuple[str, ...] = ("docs/design.md",)  # repo-root-relative
    # -- metric-catalog ----------------------------------------------------
    metric_methods: tuple[str, ...] = ("counter", "gauge", "histogram")
    # -- tenant-label-discipline (ISSUE 15) --------------------------------
    # Telemetry sink call names judged, the identifier spellings treated
    # as raw tenant identity, and the laundering wrappers that sanction a
    # mention. Lexical on purpose (the lock-discipline stance).
    tenant_sink_calls: tuple[str, ...] = (
        "counter", "gauge", "histogram", "event",
    )
    tenant_raw_markers: tuple[str, ...] = (
        "bearer", "api_key", "apikey", "authorization",
    )
    tenant_raw_names: tuple[str, ...] = ("tenant", "raw_tenant")
    tenant_label_funcs: tuple[str, ...] = ("tenant_label", "sanitize_label")
    # Dotted module exporting normalize_family()/catalog_families(); ""
    # disables the rule (fixture projects without a catalog).
    catalog_module: str = "ditl_tpu.telemetry.catalog"


class Project:
    """All parsed sources under one package directory + the settings the
    rules read. Built once per run; rules are pure functions of it."""

    def __init__(self, package_dir: str, settings: Settings | None = None):
        self.package_dir = os.path.abspath(package_dir)
        self.root = os.path.dirname(self.package_dir)
        self.package = os.path.basename(self.package_dir)
        self.settings = settings or Settings()
        self.files: list[SourceFile] = []
        for dirpath, dirnames, filenames in os.walk(self.package_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, self.package_dir).replace(
                    os.sep, "/"
                )
                self.files.append(
                    SourceFile(
                        path, rel, self._module_name(rel),
                        f"{self.package}/{rel}",
                    )
                )
        self.by_rel = {f.rel: f for f in self.files}
        self.by_module = {f.module: f for f in self.files}

    def _module_name(self, rel: str) -> str:
        parts = rel[:-3].split("/")  # strip .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join([self.package, *parts]) if parts else self.package

    def doc_text(self) -> str:
        """Concatenated documentation sources (config-drift's 'mentioned
        in the docs' check). Missing files contribute nothing — the rule
        then reports every field, which is the right failure mode for a
        project that deleted its design doc."""
        chunks = []
        for rel in self.settings.docs:
            path = os.path.join(self.root, rel)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as fh:
                    chunks.append(fh.read())
        return "\n".join(chunks)


@dataclass(frozen=True)
class Rule:
    id: str
    doc: str
    fn: object = field(compare=False)


RULES: dict[str, Rule] = {}

# The pragma auditor is not a registered pass (it cannot be pragma'd away)
# but its id participates in diagnostics and --rule filtering.
PRAGMA_RULE = "pragma"


def rule(rule_id: str, doc: str):
    """Register ``fn(project) -> list[Diagnostic]`` under ``rule_id``."""

    def register(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, doc, fn)
        return fn

    return register


def _audit_pragmas(project: Project, known: set[str]) -> list[Diagnostic]:
    out = []
    for f in project.files:
        for p in f.pragmas:
            if not p.rules:
                out.append(Diagnostic(
                    PRAGMA_RULE, f.display, p.line,
                    "pragma names no rule: use "
                    "`# ditl: allow(<rule>) -- <reason>`",
                ))
                continue
            for rid in p.rules:
                if rid not in known:
                    out.append(Diagnostic(
                        PRAGMA_RULE, f.display, p.line,
                        f"pragma names unknown rule {rid!r} "
                        f"(known: {', '.join(sorted(known))})",
                    ))
            if not p.reason:
                out.append(Diagnostic(
                    PRAGMA_RULE, f.display, p.line,
                    "pragma without a reason: every suppression must say "
                    "why (`# ditl: allow(rule) -- <reason>`)",
                ))
    return out


def run(
    package_dir: str,
    rules: list[str] | None = None,
    settings: Settings | None = None,
) -> list[Diagnostic]:
    """Run the selected rules (default: all) over ``package_dir``.
    Returns pragma-filtered diagnostics sorted by (path, line, rule).
    Unknown rule ids raise ValueError (exit 2 at the CLI)."""
    project = Project(package_dir, settings)
    # dict.fromkeys: a repeated --rule flag must not run the rule twice
    # (doubled diagnostics and a doubled violation count).
    selected = sorted(RULES) if rules is None else list(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in RULES and r != PRAGMA_RULE]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; known: {sorted(RULES)}"
        )
    diags: list[Diagnostic] = []
    for rid in selected:
        if rid == PRAGMA_RULE:
            continue
        diags.extend(RULES[rid].fn(project))
    # Pragma suppression: a reasoned pragma covering (rule, line) eats the
    # diagnostic. Reason-less pragmas still suppress — the missing reason
    # is reported separately below, so the tree is never "clean with an
    # unexplained hole" silently.
    kept: list[Diagnostic] = []
    for d in diags:
        f = _file_for(project, d.path)
        covered = None
        if f is not None:
            for p in f.pragmas:
                if p.covers(d.rule, d.line):
                    covered = p
                    break
        if covered is None:
            kept.append(d)
        else:
            covered.used = True
    kept.extend(_audit_pragmas(project, set(RULES) | {PRAGMA_RULE}))
    # Unused-pragma audit: a pragma that suppressed nothing is stale — it
    # documents an exception that no longer exists, and its line coverage
    # would silently eat the NEXT violation introduced there. Only judged
    # when every rule it names actually ran this invocation (under
    # --rule filtering a pragma for an unselected rule is merely dormant).
    ran = set(selected)
    for f in project.files:
        for p in f.pragmas:
            if p.used or not p.reason or not p.rules:
                continue  # reasonless/empty pragmas are already reported
            if all(rid in ran for rid in p.rules):
                kept.append(Diagnostic(
                    PRAGMA_RULE, f.display, p.line,
                    f"pragma for {', '.join(p.rules)} suppresses nothing "
                    "— stale suppressions hide the next real violation "
                    "on this line; delete it",
                ))
    return sorted(kept, key=lambda d: (d.path, d.line, d.rule))


def _file_for(project: Project, display: str) -> SourceFile | None:
    for f in project.files:
        if f.display == display:
            return f
    return None


# -- shared AST helpers (used by several rule modules) ----------------------


def call_name(node: ast.Call) -> str:
    """Terminal name of the called function: ``jax.device_get(...)`` and
    ``device_get(...)`` both resolve to ``device_get``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def dotted(node: ast.AST) -> str:
    """Best-effort dotted spelling of a Name/Attribute chain ('' when the
    chain bottoms out in something dynamic)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def module_literal(
    f: SourceFile, name: str
) -> tuple[tuple, int] | None:
    """Module-level assignment ``name = <literal>`` as (ordered value
    tuple, lineno). Dicts contribute their keys (declaration order IS the
    registry order for rank registries); sets are sorted for a stable
    comparison. None when absent or not a literal."""
    for node in f.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == name):
            continue
        # frozenset({...}) / tuple([...]) wrappers: unwrap one call level.
        if isinstance(value, ast.Call) and len(value.args) == 1:
            value = value.args[0]
        try:
            lit = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None
        if isinstance(lit, dict):
            return tuple(lit.keys()), node.lineno
        if isinstance(lit, (set, frozenset)):
            return tuple(sorted(lit)), node.lineno
        if isinstance(lit, (list, tuple)):
            return tuple(lit), node.lineno
        return (lit,), node.lineno
    return None
