from ditl_tpu.models import llama  # noqa: F401
from ditl_tpu.models.presets import PRESETS, get_preset  # noqa: F401
