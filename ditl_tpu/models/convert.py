"""HuggingFace checkpoint import: torch Llama/Mixtral weights -> param pytree.

The reference never loads weights at all — its Llama-3.1-70B lives behind an
HTTP API (ref ``src/distributed_inference.py:34-41``, ``MODEL_NAME`` in
``config.py``). For this framework to fine-tune/serve those same models
locally on TPU, real checkpoints must come in from the HF ecosystem. This
module maps a ``transformers`` state dict onto the stacked-layer param tree
(models/llama.py) with pure numpy host-side work:

- torch ``Linear.weight`` is (out, in) — transposed here to the (in, out)
  einsum layout the model uses;
- per-layer tensors are stacked along the leading ``layers`` axis (the
  ``lax.scan`` layout, one HLO per layer);
- nothing touches a device: outputs are numpy, so the caller can shard them
  straight to the mesh with ``jax.device_put`` / ``make_array_from_callback``
  without first materializing the whole model on one chip.

RoPE/RMSNorm/SwiGLU conventions match HF's Llama exactly (same rotate-half
frequency layout, same eps placement); verified by the logits-parity test
against a randomly initialized ``LlamaForCausalLM`` (tests/test_convert.py).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from ditl_tpu.config import ModelConfig

__all__ = [
    "config_from_hf",
    "params_from_state_dict",
    "state_dict_from_params",
    "load_hf_model",
    "export_hf_model",
]


def config_from_hf(hf_config: Any, **overrides) -> ModelConfig:
    """Derive a ModelConfig from a ``transformers`` Llama/Mixtral config."""
    num_heads = hf_config.num_attention_heads
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // num_heads
    )
    kwargs: dict[str, Any] = dict(
        name=getattr(hf_config, "name_or_path", "") or hf_config.model_type,
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=num_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", num_heads),
        head_dim=head_dim,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_norm_eps=hf_config.rms_norm_eps,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        # Qwen2-family: q/k/v bias. HF Llama configs carry an explicit
        # attention_bias flag; Qwen2Config implies it by architecture.
        attention_bias=bool(
            getattr(hf_config, "attention_bias", False)
            or getattr(hf_config, "model_type", "") == "qwen2"
        ),
    )
    if getattr(hf_config, "num_local_experts", 0):  # Mixtral
        kwargs["num_experts"] = hf_config.num_local_experts
        kwargs["num_experts_per_tok"] = hf_config.num_experts_per_tok
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling and scaling.get("rope_type", scaling.get("type")) == "llama3":
        kwargs["rope_scaling_factor"] = scaling["factor"]
        kwargs["rope_scaling_low_freq_factor"] = scaling["low_freq_factor"]
        kwargs["rope_scaling_high_freq_factor"] = scaling["high_freq_factor"]
        kwargs["rope_scaling_original_max_len"] = scaling[
            "original_max_position_embeddings"
        ]
    elif scaling:
        raise ValueError(
            f"unsupported rope_scaling type {scaling!r} (only 'llama3' NTK "
            "scaling is implemented)"
        )
    kwargs.update(overrides)
    return ModelConfig(**kwargs)


def _np(t) -> np.ndarray:
    """torch tensor (any dtype/device) -> float32 numpy without torch deps
    leaking into the signature."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def _stack(sd: Mapping[str, Any], template: str, n_layers: int, transpose: bool) -> np.ndarray:
    mats = []
    for i in range(n_layers):
        w = _np(sd[template.format(i=i)])
        mats.append(w.T if transpose else w)
    return np.stack(mats, axis=0)


def params_from_state_dict(
    sd: Mapping[str, Any], cfg: ModelConfig, dtype: str | None = None
) -> dict[str, Any]:
    """HF Llama/Mixtral state dict -> this framework's param pytree (numpy).

    ``dtype`` defaults to ``cfg.param_dtype``. Keys follow HF's
    ``model.layers.{i}.*`` naming; both dense (Llama) and sparse (Mixtral)
    MLPs are handled according to ``cfg.num_experts``.
    """
    pd = np.dtype(dtype or cfg.param_dtype)
    L = cfg.num_layers

    def cast(x: np.ndarray) -> np.ndarray:
        return x.astype(pd)

    params: dict[str, Any] = {
        "embed": {"embedding": cast(_np(sd["model.embed_tokens.weight"]))},
        "layers": {
            "attn_norm": {
                "scale": cast(
                    _stack(sd, "model.layers.{i}.input_layernorm.weight", L, False)
                )
            },
            "attn": (
                {
                    "w_qkv": cast(np.concatenate([
                        _stack(sd, "model.layers.{i}.self_attn.q_proj.weight", L, True),
                        _stack(sd, "model.layers.{i}.self_attn.k_proj.weight", L, True),
                        _stack(sd, "model.layers.{i}.self_attn.v_proj.weight", L, True),
                    ], axis=-1)),
                    "wo": cast(_stack(sd, "model.layers.{i}.self_attn.o_proj.weight", L, True)),
                }
                if cfg.fused_qkv else
                {
                    "wq": cast(_stack(sd, "model.layers.{i}.self_attn.q_proj.weight", L, True)),
                    "wk": cast(_stack(sd, "model.layers.{i}.self_attn.k_proj.weight", L, True)),
                    "wv": cast(_stack(sd, "model.layers.{i}.self_attn.v_proj.weight", L, True)),
                    "wo": cast(_stack(sd, "model.layers.{i}.self_attn.o_proj.weight", L, True)),
                }
            ),
            "mlp_norm": {
                "scale": cast(
                    _stack(
                        sd, "model.layers.{i}.post_attention_layernorm.weight", L, False
                    )
                )
            },
        },
        "final_norm": {"scale": cast(_np(sd["model.norm.weight"]))},
    }
    if cfg.attention_bias:  # Qwen2-family q/k/v bias (1-D: no transpose)
        params["layers"]["attn"].update({
            "bq": cast(_stack(sd, "model.layers.{i}.self_attn.q_proj.bias", L, False)),
            "bk": cast(_stack(sd, "model.layers.{i}.self_attn.k_proj.bias", L, False)),
            "bv": cast(_stack(sd, "model.layers.{i}.self_attn.v_proj.bias", L, False)),
        })
    if cfg.num_experts > 0:  # Mixtral-style sparse MLP
        e = cfg.num_experts
        router = _stack(sd, "model.layers.{i}.block_sparse_moe.gate.weight", L, True)

        def experts(w_name: str, transpose: bool) -> np.ndarray:
            return np.stack(
                [
                    np.stack(
                        [
                            (lambda w: w.T if transpose else w)(
                                _np(
                                    sd[
                                        f"model.layers.{i}.block_sparse_moe."
                                        f"experts.{j}.{w_name}.weight"
                                    ]
                                )
                            )
                            for j in range(e)
                        ],
                        axis=0,
                    )
                    for i in range(L)
                ],
                axis=0,
            )  # (L, E, ..., ...)

        params["layers"]["moe"] = {
            "router": cast(router),
            "w_gate": cast(experts("w1", True)),
            "w_up": cast(experts("w3", True)),
            "w_down": cast(experts("w2", True)),
        }
    elif cfg.fused_gate_up:
        params["layers"]["mlp"] = {
            "w_gu": cast(np.concatenate([
                _stack(sd, "model.layers.{i}.mlp.gate_proj.weight", L, True),
                _stack(sd, "model.layers.{i}.mlp.up_proj.weight", L, True),
            ], axis=-1)),
            "w_down": cast(_stack(sd, "model.layers.{i}.mlp.down_proj.weight", L, True)),
        }
    else:
        params["layers"]["mlp"] = {
            "w_gate": cast(_stack(sd, "model.layers.{i}.mlp.gate_proj.weight", L, True)),
            "w_up": cast(_stack(sd, "model.layers.{i}.mlp.up_proj.weight", L, True)),
            "w_down": cast(_stack(sd, "model.layers.{i}.mlp.down_proj.weight", L, True)),
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": cast(_np(sd["lm_head.weight"]).T)}
    return params


def state_dict_from_params(params: Mapping[str, Any], cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Inverse of ``params_from_state_dict``: param pytree -> HF state dict
    (numpy, f32) — so a TPU fine-tune can be served by any HF-stack consumer.
    LoRA adapters, if present, must be merged into the base weights first
    (models/lora.py ``merge_lora``); they have no HF-side representation here."""

    def host(x) -> np.ndarray:
        return np.asarray(x, np.float32)

    L = cfg.num_layers
    layers = params["layers"]
    if "lora" in layers:
        raise ValueError(
            "param tree still carries LoRA adapters — exporting would silently "
            "drop the fine-tune (base weights are frozen under LoRA). Call "
            "models.lora.merge_lora(params, cfg) first."
        )
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host(params["embed"]["embedding"]),
        "model.norm.weight": host(params["final_norm"]["scale"]),
    }
    for i in range(L):
        p = f"model.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = host(layers["attn_norm"]["scale"][i])
        sd[f"{p}.post_attention_layernorm.weight"] = host(layers["mlp_norm"]["scale"][i])
        if "w_qkv" in layers["attn"]:
            nq = cfg.num_heads * cfg.head_dim
            nk = cfg.num_kv_heads * cfg.head_dim
            w = layers["attn"]["w_qkv"][i]
            sd[f"{p}.self_attn.q_proj.weight"] = host(w[:, :nq]).T
            sd[f"{p}.self_attn.k_proj.weight"] = host(w[:, nq:nq + nk]).T
            sd[f"{p}.self_attn.v_proj.weight"] = host(w[:, nq + nk:]).T
            sd[f"{p}.self_attn.o_proj.weight"] = host(layers["attn"]["wo"][i]).T
        else:
            for ours, theirs in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "o_proj")):
                sd[f"{p}.self_attn.{theirs}.weight"] = host(layers["attn"][ours][i]).T
        if cfg.attention_bias:
            for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
                sd[f"{p}.self_attn.{theirs}.bias"] = host(layers["attn"][ours][i])
        if cfg.num_experts > 0:
            moe = layers["moe"]
            sd[f"{p}.block_sparse_moe.gate.weight"] = host(moe["router"][i]).T
            for j in range(cfg.num_experts):
                q = f"{p}.block_sparse_moe.experts.{j}"
                sd[f"{q}.w1.weight"] = host(moe["w_gate"][i, j]).T
                sd[f"{q}.w3.weight"] = host(moe["w_up"][i, j]).T
                sd[f"{q}.w2.weight"] = host(moe["w_down"][i, j]).T
        elif "w_gu" in layers["mlp"]:
            mlp = layers["mlp"]
            f = cfg.intermediate_size
            sd[f"{p}.mlp.gate_proj.weight"] = host(mlp["w_gu"][i, :, :f]).T
            sd[f"{p}.mlp.up_proj.weight"] = host(mlp["w_gu"][i, :, f:]).T
            sd[f"{p}.mlp.down_proj.weight"] = host(mlp["w_down"][i]).T
        else:
            mlp = layers["mlp"]
            sd[f"{p}.mlp.gate_proj.weight"] = host(mlp["w_gate"][i]).T
            sd[f"{p}.mlp.up_proj.weight"] = host(mlp["w_up"][i]).T
            sd[f"{p}.mlp.down_proj.weight"] = host(mlp["w_down"][i]).T
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = host(params["lm_head"]["kernel"]).T
    return sd


def export_hf_model(params: Mapping[str, Any], cfg: ModelConfig, path: str) -> None:
    """Write a ``transformers``-loadable checkpoint directory from a param
    pytree (the serve-anywhere exit path the reference's API-only design never
    needed — its model lived behind someone else's server)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM, MixtralConfig, MixtralForCausalLM

    common = dict(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim,
        max_position_embeddings=cfg.max_seq_len,
        rms_norm_eps=cfg.rms_norm_eps,
        rope_theta=cfg.rope_theta,
        tie_word_embeddings=cfg.tie_embeddings,
    )
    if cfg.rope_scaling_factor > 0:
        # Round-trip the Llama-3.1 NTK scaling — omitting it would make the
        # exported model compute different (unscaled) RoPE than this one.
        common["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": cfg.rope_scaling_factor,
            "low_freq_factor": cfg.rope_scaling_low_freq_factor,
            "high_freq_factor": cfg.rope_scaling_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_scaling_original_max_len,
        }
    if cfg.num_experts > 0:
        hf_cfg = MixtralConfig(
            num_local_experts=cfg.num_experts,
            num_experts_per_tok=cfg.num_experts_per_tok,
            **common,
        )
        model = MixtralForCausalLM(hf_cfg)
    elif cfg.attention_bias:
        # Qwen2-family (q/k/v bias): export as a native Qwen2 checkpoint.
        from transformers import Qwen2Config, Qwen2ForCausalLM

        common.pop("head_dim", None)  # Qwen2Config derives it
        if cfg.head_dim * cfg.num_heads != cfg.hidden_size:
            raise ValueError(
                "Qwen2 export needs head_dim * num_heads == hidden_size "
                f"({cfg.head_dim} * {cfg.num_heads} != {cfg.hidden_size})"
            )
        hf_cfg = Qwen2Config(**common)
        model = Qwen2ForCausalLM(hf_cfg)
    else:
        hf_cfg = LlamaConfig(attention_bias=False, mlp_bias=False, **common)
        model = LlamaForCausalLM(hf_cfg)
    sd = {k: torch.from_numpy(v) for k, v in state_dict_from_params(params, cfg).items()}
    missing, unexpected = model.load_state_dict(sd, strict=False)
    # Tied-embedding models have no lm_head entry; anything else missing is a bug.
    real_missing = [m for m in missing if not (cfg.tie_embeddings and "lm_head" in m)]
    if real_missing or unexpected:
        raise ValueError(
            f"state dict mismatch exporting to HF: missing={real_missing} "
            f"unexpected={unexpected}"
        )
    model.save_pretrained(path)


def main(argv: list[str] | None = None) -> int:
    """CLI: convert an Orbax training checkpoint to a HF checkpoint dir.

        python -m ditl_tpu.models.convert \\
            --checkpoint-dir /mnt/ckpt --preset llama3-8b --out /mnt/hf_export

    LoRA runs are merged automatically (models/lora.py) before export.
    """
    import argparse

    import jax

    from ditl_tpu.models import llama
    from ditl_tpu.models.presets import get_preset
    from ditl_tpu.train.checkpoint import CheckpointManager
    from ditl_tpu.utils.logging import get_logger, setup_logging

    setup_logging()
    logger = get_logger(__name__)
    parser = argparse.ArgumentParser(prog="ditl_tpu.models.convert")
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--preset", required=True)
    parser.add_argument("--out", required=True)
    parser.add_argument("--lora-rank", type=int, default=0,
                        help="set if the checkpoint was a LoRA fine-tune")
    parser.add_argument("--lora-alpha", type=float, default=16.0,
                        help="must match the training run's model.lora_alpha "
                        "(the merge scale is alpha/rank)")
    args = parser.parse_args(argv)

    cfg = get_preset(args.preset, lora_rank=args.lora_rank,
                     lora_alpha=args.lora_alpha)
    abstract = jax.eval_shape(lambda: llama.init_params(jax.random.key(0), cfg))
    mgr = CheckpointManager(args.checkpoint_dir)
    params = mgr.restore_latest_params(abstract)
    mgr.close()
    if params is None:
        raise SystemExit(f"no checkpoint found in {args.checkpoint_dir}")
    if cfg.lora_rank > 0:
        from ditl_tpu.models.lora import merge_lora

        logger.info("merging LoRA adapters (rank %d) into base weights", cfg.lora_rank)
        params = merge_lora(params, cfg)
        import dataclasses

        cfg = dataclasses.replace(cfg, lora_rank=0)
    export_hf_model(params, cfg, args.out)
    logger.info("exported HF checkpoint to %s", args.out)
    return 0


def load_hf_model(model_or_path: Any, **config_overrides):
    """Convenience: a ``transformers`` model instance *or* a local checkpoint
    path -> ``(params, ModelConfig)``. Network access is never attempted for
    instances; for paths, ``local_files_only=True`` keeps it hermetic."""
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        # torch_dtype="auto" keeps the checkpoint's storage dtype (bf16 for
        # modern Llama releases) — loading a 70B as f32 would double host RAM
        # before conversion even starts. _np upcasts per-tensor only.
        model = AutoModelForCausalLM.from_pretrained(
            model_or_path, local_files_only=True, torch_dtype="auto"
        )
    else:
        model = model_or_path
    cfg = config_from_hf(model.config, **config_overrides)
    params = params_from_state_dict(model.state_dict(), cfg)
    return params, cfg


if __name__ == "__main__":
    import sys

    sys.exit(main())
