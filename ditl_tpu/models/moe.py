"""Mixtral-style sparse Mixture-of-Experts block (expert-parallel, L1).

Absent from the reference (SURVEY.md §2: 'EP: absent'); required by the
BASELINE.json config 'Mixtral-8x7B MoE, expert-sharded fine-tune on v5p-64'.

GShard/Switch-style capacity-factor dispatch, chosen over gather/scatter
routing because every shape is static and every step is an einsum — exactly
what XLA/MXU want, and the expert dim shards cleanly over the ``expert`` mesh
axis (dispatch/combine einsums lower to all-to-alls on ICI):

1. router logits -> softmax gates (float32; routing is precision-sensitive),
2. top-k experts per token, renormalized,
3. each token claims a capacity slot per chosen expert (cumsum trick); tokens
   beyond ``capacity = ceil(k*T/E * capacity_factor)`` are dropped (residual
   path still carries them),
4. dispatch einsum (T,E,C) x (T,D) -> (E,C,D); per-expert SwiGLU; combine back.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ditl_tpu.config import ModelConfig

CAPACITY_FACTOR = 1.25

__all__ = ["init_moe_params", "moe_logical_axes", "moe_block", "load_balancing_loss"]


def init_moe_params(rng: jax.Array, cfg: ModelConfig) -> dict[str, Any]:
    pd = jnp.dtype(cfg.param_dtype)
    d, f, L, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers, cfg.num_experts
    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape) * (1.0 / math.sqrt(fan_in))).astype(pd)

    return {
        "router": dense(k1, (L, d, E), d),
        "w_gate": dense(k2, (L, E, d, f), d),
        "w_up": dense(k3, (L, E, d, f), d),
        "w_down": dense(k4, (L, E, f, d), f),
    }


def moe_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    return {
        "router": ("layers", "embed", None),
        "w_gate": ("layers", "expert", "embed", "mlp"),
        "w_up": ("layers", "expert", "embed", "mlp"),
        "w_down": ("layers", "expert", "mlp", "embed"),
    }


def load_balancing_loss(gates: jax.Array, dispatch_mask: jax.Array) -> jax.Array:
    """Switch-Transformer aux loss: E * sum_e(fraction_routed_e * mean_gate_e)."""
    e = gates.shape[-1]
    tokens_per_expert = dispatch_mask.sum(axis=(0,)).sum(axis=-1)  # (E,)
    f = tokens_per_expert / jnp.maximum(dispatch_mask.sum(), 1.0)
    p = gates.mean(axis=0)
    return e * jnp.sum(f * p)


def moe_block(
    moe: dict[str, Any],
    h: jax.Array,
    cfg: ModelConfig,
    *,
    mesh=None,
    rules=None,
) -> tuple[jax.Array, jax.Array]:
    """(B, S, D) -> ((B, S, D), aux_loss) through top-k routed experts. The
    scalar aux loss is the Switch load-balancing term, weighted into the total
    loss by ``ModelConfig.router_aux_coef`` (train/step.py)."""
    b, s, d = h.shape
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    cd = h.dtype
    t = b * s
    x = h.reshape(t, d)

    gates = jax.nn.softmax(
        jnp.einsum("td,de->te", x.astype(jnp.float32), moe["router"].astype(jnp.float32)),
        axis=-1,
    )  # (T, E) f32
    top_w, top_idx = jax.lax.top_k(gates, k)  # (T, k)
    top_w = top_w / jnp.maximum(top_w.sum(axis=-1, keepdims=True), 1e-9)

    capacity = max(1, int(math.ceil(k * t / e * CAPACITY_FACTOR)))

    # Flatten (T, k) token-major so slot priority follows token order.
    flat_idx = top_idx.reshape(t * k)
    flat_w = top_w.reshape(t * k)
    onehot = jax.nn.one_hot(flat_idx, e, dtype=jnp.float32)  # (TK, E)
    pos_in_expert = jnp.einsum(
        "xe,xe->x", jnp.cumsum(onehot, axis=0) - 1.0, onehot
    )  # (TK,)
    keep = pos_in_expert < capacity
    slot_onehot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = onehot[:, :, None] * slot_onehot[:, None, :] * keep[:, None, None]
    # (TK, E, C)

    token_x = x[jnp.arange(t * k) // k]  # (TK, D)
    expert_in = jnp.einsum("xec,xd->ecd", dispatch, token_x.astype(jnp.float32)).astype(cd)

    from ditl_tpu.ops.quant import weight_einsum

    def ffn(w_gate, w_up, w_down, xe):
        gate = weight_einsum("ecd,edf->ecf", xe, w_gate, compute_dtype=cd)
        up = weight_einsum("ecd,edf->ecf", xe, w_up, compute_dtype=cd)
        return weight_einsum(
            "ecf,efd->ecd", jax.nn.silu(gate) * up, w_down, compute_dtype=cd
        )

    expert_out = ffn(moe["w_gate"], moe["w_up"], moe["w_down"], expert_in)  # (E, C, D)

    combined = jnp.einsum(
        "xec,ecd->xd", dispatch, expert_out.astype(jnp.float32)
    ) * flat_w[:, None]  # (TK, D)
    out = combined.reshape(t, k, d).sum(axis=1).astype(cd)
    aux = load_balancing_loss(gates, dispatch)
    return out.reshape(b, s, d), aux
