"""Named model presets for the BASELINE.json target configs.

Preset definitions are part of checkpoint provenance: serving or exporting a
checkpoint under a preset whose architecture/RoPE fields changed since
training silently changes the math (RoPE scaling and context length are not
stored in the param tree, so restore cannot detect it). Treat existing preset
names as frozen — new variants get NEW names (e.g. llama31-8b vs llama3-8b).
"""

from __future__ import annotations

from dataclasses import replace

from ditl_tpu.config import ModelConfig

PRESETS: dict[str, ModelConfig] = {
    # Debug/test model: small but architecturally identical to Llama-3.1.
    "tiny-llama": ModelConfig(),
    "tiny-moe": ModelConfig(
        name="tiny-moe", num_experts=8, num_experts_per_tok=2, intermediate_size=344
    ),
    "llama3-8b": ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=8192,
        rope_theta=500000.0,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=8192,
        rope_theta=500000.0,
    ),
    # Llama-3.1: long context via NTK rope scaling (separate names so
    # checkpoints trained under the 3.0-style presets keep their RoPE).
    "llama31-8b": ModelConfig(
        name="llama31-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=131072,
        rope_theta=500000.0,
        rope_scaling_factor=8.0,
    ),
    "llama31-70b": ModelConfig(
        name="llama31-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=131072,
        rope_theta=500000.0,
        rope_scaling_factor=8.0,
    ),
    # Qwen2/2.5-family (q/k/v attention bias, rope 1e6; the 7B unties
    # embeddings, the 0.5B ties them).
    "qwen2-7b": ModelConfig(
        name="qwen2-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
    ),
    "qwen2-0.5b": ModelConfig(
        name="qwen2-0.5b",
        vocab_size=151936,
        hidden_size=896,
        intermediate_size=4864,
        num_layers=24,
        num_heads=14,
        num_kv_heads=2,
        head_dim=64,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
        tie_embeddings=True,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=32768,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
    ),
}


def get_preset(name: str, **overrides) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; available: {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg
