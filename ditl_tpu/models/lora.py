"""LoRA adapters (L1).

Absent from the reference; required by the BASELINE.json config
'Llama-3.1-70B LoRA fine-tune + LiteLLM eval loop on v5p-32'.

Classic LoRA (Hu et al.): frozen base weight W plus trainable low-rank update
``(alpha/r) * A @ B`` on the attention q/v projections. ``A`` is initialized
gaussian, ``B`` zero, so the adapted model starts exactly equal to the base.
The train step freezes non-LoRA params via an optax mask (train/step.py), so
optimizer state is allocated only for the adapters — the whole point of LoRA
memory-wise.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ditl_tpu.config import ModelConfig

# Base-projection names that receive adapters (classic attention-only LoRA).
LORA_TARGETS = ("wq", "wv")

__all__ = [
    "LORA_TARGETS",
    "init_lora_params",
    "lora_logical_axes",
    "lora_delta",
    "merge_lora",
    "stack_adapters",
    "zeros_adapter",
]


def _target_out_dim(name: str, cfg: ModelConfig) -> int:
    return {
        "wq": cfg.num_heads * cfg.head_dim,
        "wk": cfg.num_kv_heads * cfg.head_dim,
        "wv": cfg.num_kv_heads * cfg.head_dim,
        "wo": cfg.hidden_size,
    }[name]


def init_lora_params(rng: jax.Array, cfg: ModelConfig) -> dict[str, Any]:
    pd = jnp.dtype(cfg.param_dtype)
    d, r, L = cfg.hidden_size, cfg.lora_rank, cfg.num_layers
    out: dict[str, Any] = {}
    for i, name in enumerate(LORA_TARGETS):
        key = jax.random.fold_in(rng, i)
        out[name] = {
            "a": (jax.random.normal(key, (L, d, r)) * (1.0 / math.sqrt(d))).astype(pd),
            "b": jnp.zeros((L, r, _target_out_dim(name, cfg)), pd),
        }
    return out


def lora_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    out_axis = {"wq": "heads", "wk": "kv_heads", "wv": "kv_heads", "wo": "embed"}
    return {
        name: {
            "a": ("layers", "embed", "lora_rank"),
            "b": ("layers", "lora_rank", out_axis[name]),
        }
        for name in LORA_TARGETS
    }


def lora_delta(
    p: dict[str, Any],
    h: jax.Array,
    cfg: ModelConfig,
    adapter_ids: jax.Array | None = None,
) -> jax.Array:
    """(alpha/r) * (h @ A) @ B, computed in the activation dtype.

    With a multi-adapter tree (``stack_adapters``: per-layer slices carry a
    leading adapter axis (K, d, r)), ``adapter_ids`` (B,) selects each row's
    adapter — a (B, d, r) gather per layer, tiny next to the base matmuls.
    Serving: index 0 is conventionally ``zeros_adapter`` (= the base model).
    """
    cd = h.dtype
    scale = cfg.lora_alpha / cfg.lora_rank
    if p["a"].ndim == 3:  # (K, d, r): multi-adapter serving tree
        if adapter_ids is None:
            raise ValueError("multi-adapter LoRA tree needs adapter_ids")
        a_sel = p["a"][adapter_ids].astype(cd)  # (B, d, r)
        b_sel = p["b"][adapter_ids].astype(cd)  # (B, r, f)
        low = jnp.einsum("bsd,bdr->bsr", h, a_sel, preferred_element_type=cd)
        return scale * jnp.einsum(
            "bsr,brf->bsf", low, b_sel, preferred_element_type=cd
        )
    low = jnp.einsum("bsd,dr->bsr", h, p["a"].astype(cd), preferred_element_type=cd)
    return scale * jnp.einsum(
        "bsr,rf->bsf", low, p["b"].astype(cd), preferred_element_type=cd
    )


def stack_adapters(adapters: list[dict[str, Any]]) -> dict[str, Any]:
    """Stack K adapter trees for multi-LoRA serving: leaves go from
    (L, d, r) to (L, K, d, r) — the adapter axis sits AFTER the layer axis
    so the model's layer scan still slices axis 0 and each layer body sees a
    (K, d, r) slice. Put ``zeros_adapter`` first so id 0 serves the base
    model."""
    if not adapters:
        raise ValueError("need at least one adapter")
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *adapters)


def zeros_adapter(cfg: ModelConfig) -> dict[str, Any]:
    """An all-zeros adapter (delta is exactly 0: the base model)."""
    return jax.tree.map(jnp.zeros_like, init_lora_params(jax.random.key(0), cfg))


def merge_lora(params: dict[str, Any], cfg: ModelConfig) -> dict[str, Any]:
    """Fold adapters into the base weights: W' = W + (alpha/r)·A@B per layer.

    Returns a new param tree with no ``lora`` subtree — loadable by a
    ``lora_rank=0`` config and exportable to HF (models/convert.py). The
    merged model computes exactly what the adapted model computed (same
    identity ``h@W + Δ(h) = h@(W + (alpha/r)A@B)``)."""
    lora = params["layers"].get("lora")
    if lora is None:
        return params
    scale = cfg.lora_alpha / cfg.lora_rank
    new_layers = {k: v for k, v in params["layers"].items() if k != "lora"}
    attn = dict(new_layers["attn"])
    for name, p in lora.items():
        delta = scale * jnp.einsum(
            "ldr,lrf->ldf",
            p["a"].astype(jnp.float32),
            p["b"].astype(jnp.float32),
        )
        attn[name] = (attn[name].astype(jnp.float32) + delta).astype(attn[name].dtype)
    new_layers["attn"] = attn
    return {**params, "layers": new_layers}
