"""Llama-3.1-family decoder-only transformer (L1), TPU-first.

The reference never instantiates a model — its 70B Llama lives behind an HTTP
API (ref ``src/distributed_inference.py:34-41``) and the on-device compute is a
char-ordinal mean (ref ``src/utils.py:25-28``). This module is the real local
model the BASELINE.json north star calls for, designed for XLA/TPU:

- **Pure functional**: parameters are a pytree of arrays; ``init`` / ``forward``
  are plain functions, trivially composable with jit/grad/shard.
- **Scanned layers**: all decoder layers are stacked along a leading ``layers``
  dim and traversed with ``lax.scan`` — one layer's HLO compiled once instead
  of L times (compile-time and code-size win XLA can't get from unrolled
  Python loops).
- **Rematerialization**: ``jax.checkpoint`` around the scanned layer trades
  FLOPs for HBM (``ModelConfig.remat``).
- **bf16 compute / f32 masters**: matmuls run in ``cfg.dtype`` on the MXU with
  float32 accumulation; norms/softmax/logits in float32.
- **Logical sharding**: ``param_logical_axes`` mirrors the param tree with
  logical axis names; parallel/sharding.py maps them to the mesh (DP / FSDP /
  TP / SP / EP without touching this file).
- GQA (``num_kv_heads < num_heads``), RoPE (``rope_theta``), RMSNorm, SwiGLU —
  the Llama-3.1 architecture; Mixtral-style MoE via ``num_experts > 0``
  (models/moe.py); LoRA adapters via ``lora_rank > 0`` (models/lora.py).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ditl_tpu.config import ModelConfig
from ditl_tpu.ops.attention import dot_product_attention

Params = dict[str, Any]

__all__ = ["init_params", "param_logical_axes", "forward", "num_params"]


def _dtype(name: str):
    return jnp.dtype(name)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize the full parameter pytree (layers stacked on axis 0)."""
    pd = _dtype(cfg.param_dtype)
    d, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, f, L = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size, cfg.num_layers
    if nh % nkv:
        raise ValueError(f"num_heads {nh} must be divisible by num_kv_heads {nkv}")

    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape) * (1.0 / math.sqrt(fan_in))).astype(pd)

    params: Params = {
        "embed": {
            "embedding": (jax.random.normal(next(keys), (cfg.vocab_size, d)) * 0.02).astype(pd)
        },
        "layers": {
            "attn_norm": {"scale": jnp.ones((L, d), pd)},
            "attn": (
                {
                    "w_qkv": dense(
                        next(keys), (L, d, (nh + 2 * nkv) * hd), d
                    ),
                    "wo": dense(next(keys), (L, nh * hd, d), nh * hd),
                }
                if cfg.fused_qkv else
                {
                    "wq": dense(next(keys), (L, d, nh * hd), d),
                    "wk": dense(next(keys), (L, d, nkv * hd), d),
                    "wv": dense(next(keys), (L, d, nkv * hd), d),
                    "wo": dense(next(keys), (L, nh * hd, d), nh * hd),
                }
            ),
            "mlp_norm": {"scale": jnp.ones((L, d), pd)},
        },
        "final_norm": {"scale": jnp.ones((d,), pd)},
    }
    if cfg.attention_bias:  # Qwen2-family: bias on q/k/v only
        params["layers"]["attn"].update({
            "bq": jnp.zeros((L, nh * hd), pd),
            "bk": jnp.zeros((L, nkv * hd), pd),
            "bv": jnp.zeros((L, nkv * hd), pd),
        })
    if cfg.num_experts > 0:
        from ditl_tpu.models.moe import init_moe_params

        params["layers"]["moe"] = init_moe_params(next(keys), cfg)
    elif cfg.fused_gate_up:
        params["layers"]["mlp"] = {
            "w_gu": dense(next(keys), (L, d, 2 * f), d),
            "w_down": dense(next(keys), (L, f, d), f),
        }
    else:
        params["layers"]["mlp"] = {
            "w_gate": dense(next(keys), (L, d, f), d),
            "w_up": dense(next(keys), (L, d, f), d),
            "w_down": dense(next(keys), (L, f, d), f),
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": dense(next(keys), (d, cfg.vocab_size), d)}
    if cfg.lora_rank > 0:
        if cfg.fused_qkv:
            raise ValueError(
                "fused_qkv does not compose with LoRA adapters (deltas "
                "target the per-projection names wq/wk/wv)"
            )
        from ditl_tpu.models.lora import init_lora_params

        params["layers"]["lora"] = init_lora_params(next(keys), cfg)
    return params


def param_logical_axes(cfg: ModelConfig) -> Params:
    """Same structure as ``init_params``, leaves are logical-axis tuples."""
    axes: Params = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": {
            "attn_norm": {"scale": ("layers", "norm")},
            "attn": {
                **({"w_qkv": ("layers", "embed", "heads")}
                   if cfg.fused_qkv else
                   {"wq": ("layers", "embed", "heads"),
                    "wk": ("layers", "embed", "kv_heads"),
                    "wv": ("layers", "embed", "kv_heads")}),
                "wo": ("layers", "heads", "embed"),
                **({"bq": ("layers", "heads"),
                    "bk": ("layers", "kv_heads"),
                    "bv": ("layers", "kv_heads")}
                   if cfg.attention_bias else {}),
            },
            "mlp_norm": {"scale": ("layers", "norm")},
        },
        "final_norm": {"scale": ("norm",)},
    }
    if cfg.num_experts > 0:
        from ditl_tpu.models.moe import moe_logical_axes

        axes["layers"]["moe"] = moe_logical_axes(cfg)
    elif cfg.fused_gate_up:
        axes["layers"]["mlp"] = {
            "w_gu": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        }
    else:
        axes["layers"]["mlp"] = {
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        }
    if not cfg.tie_embeddings:
        axes["lm_head"] = {"kernel": ("embed", "vocab")}
    if cfg.lora_rank > 0:
        from ditl_tpu.models.lora import lora_logical_axes

        axes["layers"]["lora"] = lora_logical_axes(cfg)
    return axes


def num_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def head_weights(params: Params, cfg: ModelConfig) -> jax.Array:
    """The (D, V) lm-head matrix (transposed embedding when tied)."""
    if cfg.tie_embeddings:
        return params["embed"]["embedding"].T
    return params["lm_head"]["kernel"]


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """RMSNorm in float32 (norm statistics are precision-sensitive)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(
    head_dim: int, theta: float | None = None, cfg: ModelConfig | None = None
) -> jax.Array:
    """Inverse RoPE frequencies; applies Llama-3.1 NTK scaling when
    ``cfg.rope_scaling_factor > 0`` (same piecewise-by-wavelength rule as
    HF's "llama3" rope_scaling: long wavelengths divided by ``factor``,
    short ones untouched, a smooth interpolation between). With ``cfg``
    given, theta comes from the config — one source of truth for both the
    base frequencies and the scaling wavelength bands."""
    if cfg is not None:
        theta = cfg.rope_theta
    if theta is None:
        raise ValueError("rope_frequencies needs theta or cfg")
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if cfg is None or cfg.rope_scaling_factor <= 0:
        return inv_freq
    factor = cfg.rope_scaling_factor
    low_f, high_f = cfg.rope_scaling_low_freq_factor, cfg.rope_scaling_high_freq_factor
    old_len = cfg.rope_scaling_original_max_len
    wavelen = 2.0 * math.pi / inv_freq
    scaled = jnp.where(wavelen > old_len / low_f, inv_freq / factor, inv_freq)
    smooth = (old_len / wavelen - low_f) / (high_f - low_f)
    smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
    medium = (wavelen >= old_len / high_f) & (wavelen <= old_len / low_f)
    return jnp.where(medium, smoothed, scaled)


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float | None = None,
    cfg: ModelConfig | None = None,
) -> jax.Array:
    """Rotary position embedding. x: (B, S, H, D); positions: (B, S).
    Pass ``cfg`` (theta + scaling from config) or a bare ``theta``."""
    freqs = rope_frequencies(x.shape[-1], theta, cfg)  # (D/2,)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _constrain(x: jax.Array, logical_axes, mesh, rules):
    if mesh is None:
        return x
    from jax.sharding import NamedSharding

    from ditl_tpu.parallel.sharding import logical_to_spec

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_spec(logical_axes, rules))
    )


def _apply_remat(layer_fn, cfg: ModelConfig):
    """Wrap a layer body with the configured rematerialization policy."""
    if cfg.remat == "full":
        return jax.checkpoint(layer_fn)
    if cfg.remat == "dots":
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        )
    if cfg.remat == "dots_inputs":
        # "dots" plus the two norm outputs (attn_in/mlp_in): the backward's
        # weight-gradient GEMMs read stored operands instead of a recompute
        # chain. Deliberately does NOT save the flash attention output —
        # measured on v5e (r5): adding attn_out REGRESSED the step by
        # ~45 ms (the recompute overlaps fine; the extra resident buffers
        # push XLA into worse layouts), while attn_in+mlp_in combined with
        # fused_gate_up is -20 ms. ~64MB/layer extra HBM over "dots".
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
                jax.checkpoint_policies.save_only_these_names(
                    "attn_in", "mlp_in"
                ),
            ),
        )
    if cfg.remat == "attn":
        # Save only the per-layer attention outputs; recompute the rest.
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
    if cfg.remat != "none":
        raise ValueError(
            f"unknown remat policy {cfg.remat!r} "
            "(none|full|dots|dots_inputs|attn)"
        )
    return layer_fn


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _decoder_layer(
    layer_params: Params,
    x: jax.Array,
    *,
    cfg: ModelConfig,
    positions: jax.Array,
    segment_ids: jax.Array | None,
    mesh,
    rules,
    layer_cache: dict | None = None,
    cache_index: jax.Array | None = None,
    attn_mask: jax.Array | None = None,
    adapter_ids: jax.Array | None = None,
    paged: dict | None = None,
    prefill_causal: bool = False,
) -> tuple[jax.Array, jax.Array] | tuple[jax.Array, jax.Array, dict]:
    """One decoder block. With ``layer_cache`` (this layer's slice of the KV
    cache pytree, values shaped (B, Smax, K, D) — plus scales when int8,
    infer/cache.py), the chunk's keys/values are written at slot
    ``cache_index`` and attention runs against the whole cache under
    ``attn_mask`` — the KV-cache prefill/decode path (infer/engine.py).

    When ``layer_cache`` holds page pools + tail buffers (``{"kp", "vp",
    "tk", "tv"}``; pools (n_pages, K, page_size, D) — kv-heads before page
    slots, the Mosaic trailing-dim layout of ops/paged_attention.py; tails
    (B, K, T, D)), ``paged`` carries the tick metadata — ``table``
    (B, maxp), ``starts``/``lengths`` (B,) and the scan column ``t`` — and
    this is the single-token paged decode step: the token's K/V land in
    the tail buffer (returned as this layer's new_kv; the pools are NOT
    re-emitted) and attention runs through the page table plus the tail."""
    b, s, d = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    cd = _dtype(cfg.dtype)
    attn = layer_params["attn"]
    lora = layer_params.get("lora")

    from ditl_tpu.ops.quant import is_quantized_leaf, weight_einsum

    def base_proj(t, w):
        """The attention projections' base matmul — the proj_bwd_impl seam.
        The Pallas variant (ops/projection.py) keeps the forward
        bit-identical and swaps only the backward's spelling."""
        if cfg.proj_bwd_impl == "pallas":
            if is_quantized_leaf(w):
                # Reject-don't-drop (same failure mode as mlp_custom_vjp):
                # quantized serving never differentiates — leave it off.
                raise ValueError(
                    "proj_bwd_impl='pallas' needs plain float weights "
                    "(quantized serving never differentiates — leave it off)"
                )
            from ditl_tpu.ops.projection import projection

            return projection(
                t, w.astype(cd), bwd_impl="pallas",
                blocks=(cfg.proj_bwd_block_n, cfg.proj_bwd_block_d),
                mesh=mesh, rules=rules,
            )
        return weight_einsum("bsd,df->bsf", t, w, compute_dtype=cd)

    def proj(h, w, name):
        out = base_proj(h, w)
        if lora is not None and name in lora:
            from ditl_tpu.models.lora import lora_delta

            out = out + lora_delta(lora[name], h, cfg, adapter_ids=adapter_ids)
        return out

    # Attention block
    h = rms_norm(x, layer_params["attn_norm"]["scale"], cfg.rms_norm_eps)
    # Named for remat="dots_inputs": h is the qkv projections' WGRAD
    # operand — saving it keeps the backward's weight-gradient GEMMs fed
    # from a stored buffer instead of a recompute chain (r5 ablation:
    # in-step wgrads ran at ~2x their isolated cost under remat="dots").
    h = checkpoint_name(h, "attn_in")

    def _bias(t, name):
        # Qwen2-family q/k/v bias (o stays bias-free).
        return t + attn[name].astype(t.dtype) if name in attn else t

    if "w_qkv" in attn:
        # fused_qkv: one (D, (nh+2*nkv)*hd) GEMM replaces the q/k/v trio —
        # and one dgrad/wgrad pair replaces three each in the backward.
        if lora is not None:
            # init_params guards config-time; this closes the runtime hole
            # (adapters attached post-init by the serving path or a loaded
            # tree) — silently dropping deltas would serve base outputs.
            raise ValueError(
                "fused_qkv does not compose with LoRA adapters (deltas "
                "target the per-projection names wq/wk/wv)"
            )
        qkv = base_proj(h, attn["w_qkv"])
        q, k, v = jnp.split(
            qkv, (nh * hd, (nh + nkv) * hd), axis=-1
        )
        q = _bias(q, "bq").reshape(b, s, nh, hd)
        k = _bias(k, "bk").reshape(b, s, nkv, hd)
        v = _bias(v, "bv").reshape(b, s, nkv, hd)
    else:
        q = _bias(proj(h, attn["wq"], "wq"), "bq").reshape(b, s, nh, hd)
        k = _bias(proj(h, attn["wk"], "wk"), "bk").reshape(b, s, nkv, hd)
        v = _bias(proj(h, attn["wv"], "wv"), "bv").reshape(b, s, nkv, hd)
    q = apply_rope(q, positions, cfg=cfg)
    k = apply_rope(k, positions, cfg=cfg)
    q = _constrain(q, ("batch", "seq", "act_heads", "head_dim"), mesh, rules)
    k = _constrain(k, ("batch", "seq", "act_kv_heads", "head_dim"), mesh, rules)
    new_kv = None
    if layer_cache is not None and "kp" in layer_cache:
        from ditl_tpu.ops.paged_attention import paged_attention

        # Deferred flush: the chunk's K/V go into the tick's small TAIL
        # buffer (per-token writes into the big page pool inside the decode
        # scan cost ~7 ms/step on v5e); the kernel reads pages + tail, and
        # the engine flushes the tail into pages once per tick.
        tdt = layer_cache["tk"].dtype
        k_tok = jnp.swapaxes(k, 1, 2).astype(tdt)  # (B, K, S, D)
        v_tok = jnp.swapaxes(v, 1, 2).astype(tdt)
        if s == 1:
            # Plain decode tick: every live slot writes tail column
            # ``paged["t"]`` (the scan step — slots advance in lock-step
            # within a tick, each at its own global position).
            tk = jax.lax.dynamic_update_slice(
                layer_cache["tk"], k_tok, (0, 0, paged["t"], 0)
            )
            tv = jax.lax.dynamic_update_slice(
                layer_cache["tv"], v_tok, (0, 0, paged["t"], 0)
            )
        else:
            # Speculative verify: K+1 tokens land at per-row tail offsets
            # ``paged["off"]`` (= pos - starts; slots advance by their own
            # acceptance, so depths diverge within the tick).
            from ditl_tpu.infer.cache import scatter_tail

            tk = scatter_tail(layer_cache["tk"], k_tok, paged["off"])
            tv = scatter_tail(layer_cache["tv"], v_tok, paged["off"])
        new_kv = {"tk": tk, "tv": tv}
        attn_out = paged_attention(
            q[:, 0] if s == 1 else q,
            layer_cache["kp"], layer_cache["vp"], paged["table"],
            paged["lengths"], tail_k=tk, tail_v=tv, starts=paged["starts"],
            k_scale=layer_cache.get("ks"), v_scale=layer_cache.get("vs"),
            mesh=mesh, rules=rules,
        )
        if s == 1:
            attn_out = attn_out[:, None]
    elif layer_cache is not None and prefill_causal:
        from ditl_tpu.infer.cache import write_kv

        # Full prefill from an EMPTY cache (offset 0): every query attends
        # only chunk positions — pure causal self-attention, so the Pallas
        # flash kernel applies (the O(S²) score tensor never hits HBM;
        # 3.4× faster at 8k context than the masked cache read, BASELINE).
        # Validity (right-padding) rides segment_ids; the cache write is
        # unchanged.
        new_kv = write_kv(layer_cache, k, v, cache_index)
        attn_out = dot_product_attention(
            q, k, v, causal=True, segment_ids=segment_ids,
            impl=cfg.attention_impl, mesh=mesh, rules=rules,
            block_sizes=(cfg.flash_block_q, cfg.flash_block_kv,
                         cfg.flash_block_q_bwd, cfg.flash_block_kv_bwd),
        )
    elif layer_cache is not None:
        from ditl_tpu.infer.cache import read_kv, write_kv

        new_kv = write_kv(layer_cache, k, v, cache_index)
        if "k_scale" in new_kv:
            # int8 cache: hand the raw int8 values + scales to attention so
            # the dequant fuses into the dots (HBM reads stay int8-sized).
            attn_out = dot_product_attention(
                q, new_kv["k"], new_kv["v"], causal=False, mask=attn_mask,
                impl=cfg.attention_impl, mesh=mesh, rules=rules,
                k_scale=new_kv["k_scale"], v_scale=new_kv["v_scale"],
            )
        else:
            k_full, v_full = read_kv(new_kv, cd)
            attn_out = dot_product_attention(
                q, k_full, v_full, causal=False, mask=attn_mask,
                impl=cfg.attention_impl, mesh=mesh, rules=rules,
            )
    else:
        attn_out = dot_product_attention(
            q, k, v, causal=True, segment_ids=segment_ids, impl=cfg.attention_impl,
            mesh=mesh, rules=rules,
            block_sizes=(cfg.flash_block_q, cfg.flash_block_kv,
                         cfg.flash_block_q_bwd, cfg.flash_block_kv_bwd),
        )
    attn_out = attn_out.reshape(b, s, nh * hd)
    # Named for the remat="attn" policy: saving this one activation means the
    # backward pass never re-runs the attention kernel itself (its recompute
    # is the expensive part of full remat), while everything else (norms,
    # projections, SwiGLU) is still rematerialized.
    attn_out = checkpoint_name(attn_out, "attn_out")
    x = x + proj(attn_out, attn["wo"], "wo")
    x = _constrain(x, ("batch", "seq", "act_embed"), mesh, rules)

    # MLP / MoE block
    h = rms_norm(x, layer_params["mlp_norm"]["scale"], cfg.rms_norm_eps)
    h = checkpoint_name(h, "mlp_in")  # gate/up wgrad operand (see attn_in)
    aux = jnp.zeros((), jnp.float32)
    if "moe" in layer_params:
        from ditl_tpu.models.moe import moe_block

        mlp_out, aux = moe_block(layer_params["moe"], h, cfg, mesh=mesh, rules=rules)
    else:
        mlp = layer_params["mlp"]
        use_custom_vjp = cfg.mlp_custom_vjp or cfg.mlp_bwd_impl == "pallas"
        if use_custom_vjp and "w_gu" not in mlp:
            # Reject-don't-drop: silently falling back to autodiff would
            # make an A/B of the flag measure byte-identical programs.
            raise ValueError(
                "mlp_custom_vjp/mlp_bwd_impl='pallas' require "
                "fused_gate_up=True (the hand-written backward targets the "
                "fused w_gu layout)"
            )
        if "w_gu" in mlp and use_custom_vjp:
            if is_quantized_leaf(mlp["w_gu"]) or is_quantized_leaf(mlp["w_down"]):
                raise ValueError(
                    "mlp_custom_vjp/mlp_bwd_impl need plain float weights "
                    "(quantized serving never differentiates — leave it off)"
                )
            from ditl_tpu.ops.mlp import mlp_block

            mlp_out = mlp_block(
                lambda t: _constrain(t, ("batch", "seq", "act_mlp"),
                                     mesh, rules),
                h, mlp["w_gu"].astype(cd), mlp["w_down"].astype(cd),
                bwd_impl=cfg.mlp_bwd_impl,
                bwd_blocks=(cfg.mlp_bwd_block_n, cfg.mlp_bwd_block_f,
                            cfg.mlp_bwd_block_d),
                mesh=mesh, rules=rules,
            )
        else:
            if "w_gu" in mlp:
                # fused_gate_up: one (D, 2F) GEMM replaces the gate/up
                # pair — and one dgrad/wgrad pair replaces two in the
                # backward.
                gu = weight_einsum(
                    "bsd,df->bsf", h, mlp["w_gu"], compute_dtype=cd
                )
                gate, up = jnp.split(gu, 2, axis=-1)
            else:
                gate = weight_einsum(
                    "bsd,df->bsf", h, mlp["w_gate"], compute_dtype=cd
                )
                up = weight_einsum(
                    "bsd,df->bsf", h, mlp["w_up"], compute_dtype=cd
                )
            inner = jax.nn.silu(gate) * up
            inner = _constrain(inner, ("batch", "seq", "act_mlp"), mesh, rules)
            # Named so remat policies CAN save it (w_down's wgrad
            # operand); no shipped policy does — measured
            # neutral-to-negative on v5e.
            inner = checkpoint_name(inner, "mlp_inner")
            mlp_out = weight_einsum(
                "bsf,fd->bsd", inner, mlp["w_down"], compute_dtype=cd
            )
    x = x + mlp_out
    x = _constrain(x, ("batch", "seq", "act_embed"), mesh, rules)
    if new_kv is not None:
        return x, aux, new_kv
    return x, aux


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: ModelConfig,
    *,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
    mesh=None,
    rules=None,
    with_aux: bool = False,
    cache: dict[str, jax.Array] | None = None,
    cache_index: jax.Array | None = None,
    attn_mask: jax.Array | None = None,
    return_hidden: bool = False,
    adapter_ids: jax.Array | None = None,
    paged: dict | None = None,
    prefill_causal: bool = False,
) -> Any:
    """Token ids (B, S) -> logits (B, S, V) in float32.

    ``return_hidden=True`` skips the lm-head projection and returns the
    final-normed hidden states (B, S, D) instead of logits — the fused
    blockwise cross-entropy (ops/fused_ce.py) applies the head itself so the
    full logits tensor is never materialized.

    ``with_aux=True`` additionally returns the summed per-layer auxiliary loss
    (MoE router load balancing; zero for dense models).

    ``cache`` (``{"k": (L,B,Smax,K,D), "v": ...}``, see infer/cache.py) turns
    this into the incremental-decode forward: the chunk's K/V are written into
    the cache at ``cache_index`` and attention uses ``attn_mask`` (B, S, Smax)
    instead of the causal mask. Returns ``(logits, new_cache)`` (plus aux when
    requested). No remat in this mode — there is no backward pass.

    ``prefill_causal=True`` (with ``cache``): the chunk prefills an EMPTY
    cache from offset 0, so attention is pure causal self-attention over
    the chunk (validity via ``segment_ids``) and routes through the flash
    kernel instead of a masked full-cache read — the long-prompt serving
    prefill path."""
    cd = _dtype(cfg.dtype)
    b, s = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    # Embedding lookup. The stored table is (vocab->tensor, embed->fsdp)
    # sharded; gathering straight from it leaves the output embed-sharded in a
    # permuted device order that GSPMD cannot reshard to the batch-sharded
    # activation layout without an "involuntary full rematerialization"
    # (replicate-then-repartition) — in both the forward gather and the
    # backward scatter-add. Constraining the table to vocab-sharded /
    # embed-replicated for the lookup makes XLA use its sharded-vocab gather
    # (mask out-of-shard ids + psum over the tensor axis), whose output is
    # already batch-sharded; the embed-axis all-gather this implies is the
    # same per-use weight all-gather FSDP performs everywhere else.
    table = _constrain(params["embed"]["embedding"].astype(cd), ("vocab", None), mesh, rules)
    x = table[input_ids]
    x = _constrain(x, ("batch", "seq", "act_embed"), mesh, rules)

    if cache is not None:
        def cached_layer_fn(carry, xs):
            layer_params, layer_cache = xs
            y, aux, new_kv = _decoder_layer(
                layer_params,
                carry,
                cfg=cfg,
                positions=positions,
                segment_ids=segment_ids,
                mesh=mesh,
                rules=rules,
                layer_cache=layer_cache,
                cache_index=cache_index,
                attn_mask=attn_mask,
                adapter_ids=adapter_ids,
                paged=paged,
                prefill_causal=prefill_causal,
            )
            return y, (aux, new_kv)

        x, (layer_aux, new_cache) = jax.lax.scan(
            cached_layer_fn, x, (params["layers"], cache)
        )
    elif mesh is not None and mesh.shape.get("stage", 1) > 1:
        # Pipeline parallelism: layers are stage-sharded; microbatches flow
        # through the stages via ppermute (parallel/pipeline.py). Layer bodies
        # run inside shard_map, so no GSPMD constraints (mesh=None).
        from ditl_tpu.parallel.pipeline import pipeline_apply

        def pipe_layer(h, layer_params, ex):
            pos, seg = ex
            return _decoder_layer(
                layer_params, h, cfg=cfg, positions=pos, segment_ids=seg,
                mesh=None, rules=None,
            )

        pipe_layer = _apply_remat(pipe_layer, cfg)
        x, layer_aux = pipeline_apply(
            pipe_layer,
            params["layers"],
            x,
            (positions, segment_ids),
            mesh=mesh,
            rules=rules,
            n_microbatches=cfg.pipeline_microbatches or None,
        )
        new_cache = None
    else:
        def layer_fn(carry, layer_params):
            return _decoder_layer(
                layer_params,
                carry,
                cfg=cfg,
                positions=positions,
                segment_ids=segment_ids,
                mesh=mesh,
                rules=rules,
                adapter_ids=adapter_ids,
            )

        layer_fn = _apply_remat(layer_fn, cfg)
        x, layer_aux = jax.lax.scan(
            layer_fn, x, params["layers"], unroll=cfg.scan_unroll
        )
        new_cache = None

    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_norm_eps)
    if return_hidden:
        out = (x,)
        if with_aux:
            out = out + (jnp.sum(layer_aux),)
        if cache is not None:
            out = out + (new_cache,)
        return out if len(out) > 1 else x
    from ditl_tpu.ops.quant import weight_einsum

    logits = weight_einsum(
        "bsd,dv->bsv", x, head_weights(params, cfg),
        compute_dtype=cd, preferred=jnp.float32,
    )
    logits = _constrain(logits, ("batch", "seq", "act_vocab"), mesh, rules)
    out = (logits,)
    if with_aux:
        out = out + (jnp.sum(layer_aux),)
    if cache is not None:
        out = out + (new_cache,)
    return out if len(out) > 1 else logits
