"""ditl_tpu — a TPU-native distributed fine-tuning / inference framework.

A brand-new JAX / XLA / pjit / Pallas framework with the capabilities of the
reference repo ``naman1618/Distributed-Inference-with-PyTorch-and-LiteLLM``
(see SURVEY.md), redesigned TPU-first:

- ``ditl_tpu.config``   — typed config system (replaces the reference's
  git-ignored ``config.py`` dict, ref ``src/distributed_inference.py:12``).
- ``ditl_tpu.runtime``  — multi-host bring-up over ICI/DCN via
  ``jax.distributed`` + device mesh construction (replaces
  ``dist.init_process_group('nccl')``, ref ``src/distributed_inference.py:14-21``).
- ``ditl_tpu.data``     — rank/world-size-aware sharding with epoch-seeded
  shuffling (``DistributedSampler`` semantics, ref
  ``src/distributed_inference.py:58-59,63``) and global device arrays.
- ``ditl_tpu.models``   — Llama-style transformer, Mixtral-style MoE, LoRA.
- ``ditl_tpu.ops``      — jit/Pallas compute: fused attention kernels, ring
  attention, and the capability-parity text-encode op (ref ``src/utils.py:25-28``).
- ``ditl_tpu.parallel`` — mesh axes + GSPMD sharding rules (DP/FSDP/TP/SP/EP).
- ``ditl_tpu.train``    — train state, pjit train step, Orbax checkpointing,
  metrics (tokens/sec/chip, step-time p50).
- ``ditl_tpu.client``   — OpenAI-compatible remote-LLM client with retry/backoff
  (replaces the LiteLLM path, ref ``src/distributed_inference.py:34-41``).
- ``ditl_tpu.launch``   — single launcher for all hosts (replaces
  ``scripts/run_node0.sh``/``run_node1.sh``).
"""

__version__ = "0.1.0"

from ditl_tpu.config import (  # noqa: F401
    APIConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    RuntimeConfig,
    TrainConfig,
)
