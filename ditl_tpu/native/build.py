"""Shared build-on-first-use scaffold for the C++ runtime components.

One place for the g++ invocation, staleness check, atomic replace, and
double-checked-locking loader that native/dataprep.py and native/fsm.py both
use — a fix to the build logic lands once, not per component. No
pip/pybind11 involved (plain ``ctypes`` per the zero-new-dependency rule);
every caller keeps a pure-Python/numpy fallback so a machine without a
toolchain still runs."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["NativeLib", "BUILD_DIR"]

BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")


class NativeLib:
    """Lazily builds ``csrc/<name>.cpp`` into ``_build/lib<name>.so`` and
    loads it, registering ctypes signatures via ``register``. ``get()``
    returns the CDLL or None (build/toolchain failure — caller falls back);
    the outcome is cached either way."""

    def __init__(self, name: str, register: Callable[[ctypes.CDLL], None]):
        self.name = name
        self.src = os.path.join(
            os.path.dirname(__file__), "..", "..", "csrc", f"{name}.cpp"
        )
        self.so = os.path.join(BUILD_DIR, f"lib{name}.so")
        self._register = register
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._tried = False

    def _build_and_load(self) -> ctypes.CDLL | None:
        src = os.path.abspath(self.src)
        if not os.path.exists(src):
            logger.warning("native %s source missing at %s", self.name, src)
            return None
        os.makedirs(BUILD_DIR, exist_ok=True)
        if not os.path.exists(self.so) or os.path.getmtime(self.so) < os.path.getmtime(src):
            tmp = self.so + f".tmp.{os.getpid()}"
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, self.so)  # atomic: concurrent builders don't corrupt
                logger.info("built native %s: %s", self.name, self.so)
            except (subprocess.SubprocessError, OSError) as e:
                logger.warning(
                    "native %s build failed (%s); using Python path", self.name, e
                )
                return None
        try:
            lib = ctypes.CDLL(self.so)
        except OSError as e:
            logger.warning(
                "native %s load failed (%s); using Python path", self.name, e
            )
            return None
        self._register(lib)
        return lib

    def get(self) -> ctypes.CDLL | None:
        if self._lib is None and not self._tried:
            with self._lock:
                if self._lib is None and not self._tried:
                    self._lib = self._build_and_load()
                    self._tried = True
        return self._lib

    def available(self) -> bool:
        return self.get() is not None
