"""ctypes bridge to the C++ data-prep library (csrc/dataprep.cpp).

Build-on-first-use via native/build.NativeLib (no pip/pybind11 involved —
plain ``ctypes`` per the zero-new-dependency rule). Every entry point has a
pure-Python/numpy fallback, so a machine without a toolchain still runs —
just slower on the host data path.

Used by data/loader.py for the byte-tokenizer hot path: packing a shard's
documents into fixed (rows, seq_len) training batches. HF tokenizers bring
their own native code and bypass this.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ditl_tpu.native.build import NativeLib

__all__ = ["available", "pack_stream", "segments_positions", "tokenize_padded"]

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _register(lib: ctypes.CDLL) -> None:
    lib.dp_stream_size.restype = ctypes.c_int64
    lib.dp_stream_size.argtypes = [_i64p, ctypes.c_int64]
    lib.dp_pack_stream.restype = ctypes.c_int64
    lib.dp_pack_stream.argtypes = [
        _u8p, _i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, _i32p, ctypes.c_int64,
    ]
    lib.dp_segments_positions.restype = None
    lib.dp_segments_positions.argtypes = [
        _i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, _i32p, _i32p,
    ]
    lib.dp_tokenize_padded.restype = ctypes.c_int64
    lib.dp_tokenize_padded.argtypes = [
        _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, _i32p, _f32p,
    ]


_LIB = NativeLib("dataprep", _register)


def _get() -> ctypes.CDLL | None:
    return _LIB.get()


def available() -> bool:
    return _LIB.available()


def _concat_docs(texts: list[str]) -> tuple[np.ndarray, np.ndarray]:
    blobs = [t.encode("utf-8") for t in texts]
    offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return np.frombuffer(b"".join(blobs), dtype=np.uint8), offsets


def pack_stream(
    texts: list[str], *, bos: int, eos: int, byte_offset: int
) -> np.ndarray:
    """[bos] + utf8-bytes+offset + [eos] per doc, concatenated. int32."""
    lib = _get()
    if lib is None:  # Python fallback, identical semantics
        out: list[int] = []
        for t in texts:
            out.append(bos)
            out.extend(b + byte_offset for b in t.encode("utf-8"))
            out.append(eos)
        return np.asarray(out, dtype=np.int32)
    data, offsets = _concat_docs(texts)
    if len(data) == 0:
        data = np.zeros(1, dtype=np.uint8)  # ctypes needs a real pointer
    out = np.empty(int(lib.dp_stream_size(offsets, len(texts))), dtype=np.int32)
    n = lib.dp_pack_stream(
        data, offsets, len(texts), bos, eos, byte_offset, out, out.size
    )
    assert n == out.size, f"native pack wrote {n}, expected {out.size}"
    return out


def segments_positions(
    rows: np.ndarray, *, bos: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-row packed-document segment ids and restarting positions."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    lib = _get()
    if lib is None:  # numpy fallback (same as the original loader code)
        is_bos = rows == bos
        segments = np.cumsum(is_bos, axis=1).astype(np.int32) + 1
        col = np.broadcast_to(np.arange(rows.shape[1]), rows.shape)
        last_bos = np.maximum.accumulate(np.where(is_bos, col, 0), axis=1)
        return segments, (col - last_bos).astype(np.int32)
    segments = np.empty_like(rows)
    positions = np.empty_like(rows)
    lib.dp_segments_positions(
        rows, rows.shape[0], rows.shape[1], bos, segments, positions
    )
    return segments, positions


def tokenize_padded(
    text: str, seq_len: int, *, bos: int, eos: int, pad: int, byte_offset: int
) -> tuple[np.ndarray, np.ndarray]:
    """One padded row + loss mask (the non-packed path)."""
    if seq_len < 2:
        raise ValueError(f"seq_len must be >= 2 (bos+eos), got {seq_len}")
    lib = _get()
    data = np.frombuffer(text.encode("utf-8"), dtype=np.uint8)
    if lib is None:
        ids = [bos] + [int(b) + byte_offset for b in data[: seq_len - 2]] + [eos]
        row = np.full(seq_len, pad, dtype=np.int32)
        row[: len(ids)] = ids
        mask = np.zeros(seq_len, dtype=np.float32)
        mask[: len(ids)] = 1.0
        return row, mask
    if len(data) == 0:
        data = np.zeros(1, dtype=np.uint8)
        n_bytes = 0
    else:
        n_bytes = len(data)
    row = np.empty(seq_len, dtype=np.int32)
    mask = np.empty(seq_len, dtype=np.float32)
    lib.dp_tokenize_padded(
        np.ascontiguousarray(data), n_bytes, seq_len, bos, eos, pad,
        byte_offset, row, mask,
    )
    return row, mask
