"""Native (C++) host-side components, loaded via ctypes with pure-Python
fallbacks. See csrc/ for sources and native/dataprep.py for the build/load
logic."""
