"""ctypes bridge to the C++ grammar-table builder (csrc/fsm.cpp).

Build-on-first-use via native/build.NativeLib; falls back to the numpy walk
in infer/grammar.py when no toolchain is available. The walk is
O(states x vocab x token_len); on a 32k-vocab tokenizer the C++ path keeps
grammar registration interactive (tens of ms instead of seconds).
"""

from __future__ import annotations

import ctypes

import numpy as np

from ditl_tpu.native.build import NativeLib

__all__ = ["available", "token_table_native"]

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _register(lib: ctypes.CDLL) -> None:
    lib.fsm_token_table.restype = None
    lib.fsm_token_table.argtypes = [
        _i32p, ctypes.c_int64, _u8p, _i64p, ctypes.c_int64, _i32p,
    ]


_LIB = NativeLib("fsm", _register)


def available() -> bool:
    return _LIB.available()


def token_table_native(
    byte_next: np.ndarray, toks: list[bytes]
) -> np.ndarray | None:
    """(S, 256) byte DFA + per-token byte strings -> (S, V) token table,
    or None when the native library is unavailable (caller falls back to
    the vectorized numpy walk). Zero-byte tokens come back -1 (disallowed)."""
    lib = _LIB.get()
    if lib is None:
        return None
    byte_next = np.ascontiguousarray(byte_next, np.int32)
    n_states = byte_next.shape[0]
    offsets = np.zeros(len(toks) + 1, np.int64)
    np.cumsum([len(t) for t in toks], out=offsets[1:])
    blob = np.frombuffer(b"".join(toks), np.uint8)
    if blob.size == 0:
        blob = np.zeros(1, np.uint8)  # ctypes needs a real pointer
    out = np.empty((n_states, len(toks)), np.int32)
    lib.fsm_token_table(
        byte_next, n_states, np.ascontiguousarray(blob), offsets, len(toks), out
    )
    return out
