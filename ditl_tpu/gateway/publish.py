"""Fleet-wide adapter publication (ISSUE 16 tentpole, gateway half).

The replica half (infer/adapters.py) can hot-swap ONE engine's adapter
row; this module is the coordinator that makes a trainer's adapter-only
checkpoint reach EVERY replica of a live fleet without a restart:

- **Verify at the edge first.** The checkpoint dir's manifest/crc is
  checked at the gateway before any replica is touched (the PR 5
  torn-save rule, via utils/adapterfmt — stdlib-only, so the gateway
  package stays provably jax-free). A torn or corrupt checkpoint is
  refused in one place with one reason; replicas re-verify the exact
  bytes themselves on their own load path anyway (defense in depth —
  the gateway and a replica reading different bytes is precisely the
  failure the double check catches).
- **Per-replica walk, crash-equivalent aborts.** Replicas are walked in
  a deterministic order; each hop POSTs the replica's own
  /v1/adapters/{publish,load,evict} endpoint, which does
  verify -> load-to-spare-row -> flip-name-pointer -> drain-old-row
  locally. The ``adapter.publish`` chaos site is consulted BEFORE each
  hop: an injected fault aborts the walk exactly where a SIGKILL of the
  coordinating gateway would — every replica already flipped serves the
  NEW adapter, every replica not yet reached keeps serving the OLD one,
  and no replica anywhere serves a torn one (the row flip is atomic
  under each registry's lock). Re-running the publication converges the
  stragglers; a rolling restart with baked weights stays the full-weights
  fallback.
- **Every outcome journaled.** ``adapter.publish.start`` -> one
  ``.hop``/``.hop_failed``/``.hop_lost`` per replica ->
  ``adapter.publish.done`` with the per-replica outcome map, in the
  gateway's own journal — `merge_journals` over the fleet's journal dirs
  reads as one causally-ordered chain next to each replica's own
  ``adapter.loaded``/``adapter.published`` events.

jax-free like the rest of gateway/ (the import-layering analysis rule).
"""

from __future__ import annotations

import json
import threading

from ditl_tpu.chaos.plane import InjectedFault, maybe_inject
from ditl_tpu.utils import adapterfmt
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["AdapterPublisher"]

PREFIX = "ditl_adapter"
_OPS = ("load", "evict", "publish")


class AdapterPublisher:
    """Coordinates one adapter lifecycle operation across a Fleet.

    ``fleet`` is a gateway Fleet (pooled per-replica HTTP + liveness
    views); ``registry`` a telemetry MetricsRegistry for the
    ``ditl_adapter_publish*`` families; ``journal`` an EventJournal."""

    def __init__(self, fleet, *, journal=None, registry=None,
                 timeout_s: float = 60.0, manifest=None):
        self.fleet = fleet
        self.journal = journal
        # Optional crash-recovery FleetManifest (ISSUE 20): publications
        # are recorded there (name -> checkpoint dir/owner) so a
        # --recover incarnation can converge straggler replicas through
        # this very re-publish path after adopting the fleet.
        self.manifest = manifest
        self.timeout_s = float(timeout_s)
        # One publication at a time: two concurrent walks interleaving
        # their flips could leave replicas on different generations with
        # BOTH walks reporting success.
        self._lock = threading.Lock()
        self._seq = 0
        self._m_publishes = self._m_hops_failed = self._m_fallbacks = None
        if registry is not None:
            self._m_publishes = registry.counter(
                f"{PREFIX}_publishes",
                "fleet-wide adapter publications coordinated (any outcome)")
            self._m_hops_failed = registry.counter(
                f"{PREFIX}_publish_hops_failed",
                "per-replica publication hops that failed (replica kept "
                "its previous adapter)")
            self._m_fallbacks = registry.counter(
                f"{PREFIX}_publish_fallbacks",
                "publications aborted mid-walk (chaos/crash): stragglers "
                "keep the old adapter until a re-publish converges them")

    def run(self, op: str, name: str, directory: str = "",
            owner: str = "") -> tuple[int, dict]:
        """Walk every routable replica with one lifecycle op; returns
        ``(http_status, payload)`` for the gateway handler to relay.
        200 = every replica converged; 502 = partial (the payload says
        exactly which replicas are on which side); 503 = no live
        replica; 4xx = refused before any replica was touched."""
        if op not in _OPS:
            return 400, {"error": {"message": f"unknown adapter op {op!r}"}}
        if not name:
            return 400, {"error": {"message":
                f"adapter {op} wants a non-empty 'name'"}}
        step = -1
        if op != "evict":
            if not directory:
                return 400, {"error": {"message":
                    f"adapter {op} wants 'dir' (a manifest-carrying "
                    f"adapter checkpoint directory)"}}
            # Edge verification: manifest+crc over the exact on-disk bytes
            # BEFORE any replica hop — a torn trainer save is refused here
            # with one reason instead of N per-replica 422s.
            try:
                directory = adapterfmt.resolve_latest(directory)
                state, why = adapterfmt.verify_dir(directory)
            except OSError as e:
                state, why = "corrupt", str(e)
            if state != "verified":
                self._journal("adapter.publish.refused", op=op, name=name,
                              checkpoint=directory, why=why)
                return 422, {"error": {"message":
                    f"adapter checkpoint {directory} failed verification "
                    f"at the gateway: {why}"}}
            try:
                step = int(adapterfmt.read_meta(directory).get("step", -1))
            except (OSError, ValueError):
                step = -1
        with self._lock:
            return self._walk(op, name, directory, owner, step)

    def _walk(self, op: str, name: str, directory: str, owner: str,
              step: int) -> tuple[int, dict]:
        if self._m_publishes is not None:
            self._m_publishes.inc()
        self._seq += 1
        pub_id = f"pub-{self._seq:04d}"
        views = sorted(self.fleet.routable(), key=lambda v: v.id)
        self._journal("adapter.publish.start", pub_id=pub_id, op=op,
                      name=name, checkpoint=directory, step=step,
                      replicas=[v.id for v in views])
        if not views:
            self._journal("adapter.publish.done", pub_id=pub_id, op=op,
                          name=name, ok=[], failed=[], aborted=False)
            return 503, {"error": {"message": "no live replica"}}
        body = json.dumps({
            "name": name,
            **({"dir": directory, "owner": owner} if op != "evict" else {}),
        }).encode()
        ok: list[dict] = []
        failed: list[dict] = []
        aborted = False
        for view in views:
            # Chaos seam (the SIGKILL-mid-publish drill): a fault here is
            # the coordinator dying BETWEEN hops — the walk aborts, every
            # not-yet-reached replica keeps its old verified adapter, and
            # the journal shows exactly which replicas flipped.
            try:
                maybe_inject("adapter.publish")
            except InjectedFault:
                aborted = True
                if self._m_fallbacks is not None:
                    self._m_fallbacks.inc()
                self._journal("adapter.publish.hop_lost", pub_id=pub_id,
                              replica=view.id, chaos=True)
                break
            try:
                status, _, data = self.fleet.pool.request(
                    view.id, view.address, "POST", f"/v1/adapters/{op}",
                    body=body,
                    headers={"Content-Type": "application/json"},
                    timeout=self.timeout_s,
                )
                answer = json.loads(data) if data else {}
            except (OSError, ValueError) as e:
                self.fleet.note_failure(view.id)
                failed.append({"replica": view.id, "error": str(e)})
                if self._m_hops_failed is not None:
                    self._m_hops_failed.inc()
                self._journal("adapter.publish.hop_failed", pub_id=pub_id,
                              replica=view.id, error=str(e))
                continue
            if status == 200:
                hop = {"replica": view.id,
                       "generation": answer.get("generation"),
                       "row": answer.get("row")}
                ok.append(hop)
                self._journal("adapter.publish.hop", pub_id=pub_id,
                              replica=view.id, name=name,
                              generation=answer.get("generation"),
                              row=answer.get("row"))
            else:
                msg = (answer.get("error") or {}).get("message", str(status))
                failed.append({"replica": view.id, "status": status,
                               "error": msg})
                if self._m_hops_failed is not None:
                    self._m_hops_failed.inc()
                self._journal("adapter.publish.hop_failed", pub_id=pub_id,
                              replica=view.id, status=status, error=msg)
        self._journal("adapter.publish.done", pub_id=pub_id, op=op,
                      name=name, step=step,
                      ok=[h["replica"] for h in ok],
                      failed=[f["replica"] for f in failed],
                      aborted=aborted)
        complete = not aborted and not failed and len(ok) == len(views)
        if self.manifest is not None:
            # Crash-recovery record (ISSUE 20): any walk that flipped at
            # least one replica is worth remembering — the dir/owner here
            # is exactly what recovery's reconcile pass needs to converge
            # stragglers (and a PARTIAL walk is the case with stragglers
            # to converge). A complete evict forgets the name.
            if op in ("publish", "load") and ok:
                self.manifest.note_adapter(name, directory, owner, step)
            elif op == "evict" and complete:
                self.manifest.forget_adapter(name)
        payload = {
            "op": op, "name": name, "pub_id": pub_id, "step": step,
            "complete": complete, "aborted": aborted,
            "replicas_total": len(views), "ok": ok, "failed": failed,
        }
        if aborted:
            # Everything from the lost hop onward never saw the new bytes.
            payload["skipped"] = [v.id
                                  for v in views[len(ok) + len(failed):]]
        return (200 if complete else 502), payload

    def _journal(self, event: str, **attrs) -> None:
        if self.journal is not None:
            try:
                self.journal.event(event, **attrs)
            except Exception:  # noqa: BLE001 - journaling never blocks a swap
                logger.exception("publish journal write failed")
