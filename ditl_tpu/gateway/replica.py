"""Replica handles and fleet supervision for the serving gateway (ISSUE 4).

A *replica* is one ``infer/server.py`` instance — spawned in a thread
(:class:`InProcessReplica`, how tests and single-host fleets run) or as a
subprocess (:class:`SubprocessReplica`, how ``launch.py gateway`` runs).
The handle owns the replica's lifecycle (start / drain / stop / kill /
restart) and its liveness probe (GET ``/health``, which since ISSUE 4 also
carries the load signal: queue depth, active slots, draining state).

:class:`Fleet` is the shared routing state the gateway reads on every
request (live/draining flags, gateway-tracked outstanding counts, the last
health snapshot), and :class:`FleetSupervisor` is the control loop that
reuses the elastic playbook from ``runtime/elastic.py`` at the serving
layer: health-check failure -> **died** -> **drain** (stop routing, let
in-flight finish) -> **relaunch** -> **re-admit**, every transition
journaled through ``telemetry/journal.py`` so "what happened when replica
r1 died" is an ordered artifact, not interleaved log archaeology. The same
loop's :meth:`FleetSupervisor.rolling_restart` drains and restarts the
fleet one replica at a time — with the gateway routing around the draining
replica, a rolling restart completes with zero failed requests.

Everything here is stdlib-only (no jax): the supervisor must stay
responsive while a replica wedges, and the gateway must be importable
without a backend.
"""

from __future__ import annotations

import collections
import dataclasses
import http.client
import json
import os
import signal
import subprocess
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Sequence

from ditl_tpu.chaos.plane import maybe_inject
from ditl_tpu.gateway.pool import ConnectionPool
from ditl_tpu.telemetry.journal import EventJournal
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "Fleet",
    "FleetSupervisor",
    "InProcessReplica",
    "ReplicaHandle",
    "ReplicaView",
    "SubprocessReplica",
    "gateway_journal_path",
]


def gateway_journal_path(directory: str) -> str:
    """The gateway's journal file — an ``events-*.jsonl`` sibling of the
    elastic controller's, so ``merge_journals`` folds serving and training
    events into one pod timeline when they share a directory."""
    return os.path.join(directory, "events-gateway.jsonl")


class ReplicaHandle:
    """Lifecycle + probe surface every replica kind implements.

    ``role`` (ISSUE 9) tags the replica's serving shape in a disaggregated
    fleet — ``"hybrid"`` (default), ``"prefill_heavy"`` or
    ``"decode_heavy"`` (gateway/roles.py). The handle's role is what the
    spawner CONFIGURED; the replica's /health echoes it back so the two
    can be cross-checked, and the Fleet's routing views prefer the health
    report when present (a subprocess replica relaunched with different
    args must not route under a stale tag)."""

    def __init__(self, replica_id: str, role: str = "hybrid"):
        self.id = replica_id
        self.role = role
        # Optional ConnectionPool the owning Fleet installs (ISSUE 14):
        # when present, _get rides a kept-alive pooled connection instead
        # of a fresh urlopen per probe. An attribute (not a fetch_health
        # parameter) so test fakes overriding the probe methods keep
        # their signatures.
        self.pool = None

    # lifecycle ------------------------------------------------------------
    def start(self) -> None:
        raise NotImplementedError

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def restart(self) -> None:
        """Stop (hard, if still up) and start fresh. After a ``kill`` the
        stop side is a no-op; after a graceful drain it already happened."""
        self.stop(drain=False, timeout=0.0)
        self.start()

    def alive(self) -> bool:
        raise NotImplementedError

    # probes ---------------------------------------------------------------
    @property
    def address(self) -> tuple[str, int] | None:
        raise NotImplementedError

    def _get(self, path: str, timeout: float) -> dict | None:
        addr = self.address
        if addr is None:
            return None
        pool = getattr(self, "pool", None)
        if pool is not None:
            # Pooled probe (ISSUE 14): health polls are the steadiest
            # upstream traffic in the system — N replicas every freshness
            # interval — and ride the fleet's keep-alive pool instead of a
            # fresh connect each. Any failure reads as "no answer",
            # exactly like the urlopen path below.
            try:
                return pool.get_json(self.id, addr, path, timeout=timeout)
            except (OSError, http.client.HTTPException, ValueError):
                return None
        try:
            with urllib.request.urlopen(
                f"http://{addr[0]}:{addr[1]}{path}", timeout=timeout
            ) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def fetch_health(self, timeout: float = 2.0) -> dict | None:
        return self._get("/health", timeout)

    def fetch_stats(self, timeout: float = 2.0) -> dict | None:
        return self._get("/stats", timeout)


class InProcessReplica(ReplicaHandle):
    """A replica served on a thread inside this process. ``server_factory``
    builds a fresh (unstarted) ``DrainableHTTPServer`` — typically a
    closure over ``infer.server.make_server`` binding port 0, so every
    (re)launch gets a fresh port and the engine behind it can be reused
    across restarts ("adopt" semantics: the expensive compiled engine
    outlives the HTTP front that died)."""

    def __init__(self, replica_id: str, server_factory: Callable[[], object],
                 *, role: str = "hybrid"):
        super().__init__(replica_id, role=role)
        self._factory = server_factory
        self._server = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._server = self._factory()
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"replica-{self.id}",
        )
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        server, self._server = self._server, None
        if server is None:
            return
        try:
            if drain:
                server.close(drain=True, timeout=timeout)
            else:
                server.kill()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def kill(self) -> None:
        """Abrupt death (the in-process stand-in for kill -9): sever the
        listening socket and every open connection; see
        ``DrainableHTTPServer.kill``."""
        server, self._server = self._server, None
        if server is not None:
            server.kill()

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive() \
            and self._server is not None

    @property
    def address(self) -> tuple[str, int] | None:
        server = self._server
        if server is None:
            return None
        host, port = server.server_address[:2]
        return (host, port)


class SubprocessReplica(ReplicaHandle):
    """A replica in its own OS process (``python -m ditl_tpu.infer.server
    ...``). ``build_argv(port)`` produces the command line; each
    (re)launch binds a fresh port (a SIGKILLed listener can linger in
    TIME_WAIT — the same reason runtime/elastic.py bumps its coordinator
    port per generation). ``stop(drain=True)`` sends SIGTERM, which the
    server satellite turns into a graceful drain.

    **Adoption (ISSUE 20).** A SIGKILLed gateway orphans its replica
    subprocesses — they reparent to init and keep serving. A recovering
    gateway calls :meth:`adopt` with the pid/port its predecessor's
    manifest recorded instead of relaunching: the handle then tracks the
    process by pid (signal 0 for liveness, SIGTERM/SIGKILL for stops —
    ``Popen.wait`` is impossible on a non-child, so stops poll for pid
    death). ``adopt`` only verifies pid liveness; the caller MUST
    cross-check with a /health probe on the recorded port before routing
    (a recycled pid or a rebound port must never alias — see
    gateway/recovery.py)."""

    def __init__(
        self,
        replica_id: str,
        build_argv: Callable[[int], Sequence[str]],
        *,
        host: str = "127.0.0.1",
        port_factory: Callable[[], int] | None = None,
        env: dict | None = None,
        role: str = "hybrid",
    ):
        super().__init__(replica_id, role=role)
        self._build_argv = build_argv
        self._host = host
        if port_factory is None:
            from ditl_tpu.runtime.elastic import free_port

            port_factory = free_port
        self._port_factory = port_factory
        self._env = env
        self._proc: subprocess.Popen | None = None
        self._port: int | None = None
        # Adoption state (ISSUE 20): a pid inherited from a previous
        # gateway incarnation's manifest. Mutually exclusive with _proc
        # (a handle either spawned its process or adopted it).
        self._adopted_pid: int | None = None

    def start(self) -> None:
        self._adopted_pid = None
        self._port = self._port_factory()
        self._proc = subprocess.Popen(
            list(self._build_argv(self._port)), env=self._env
        )

    # -- adoption (ISSUE 20) ------------------------------------------------

    def adopt(self, pid, port) -> bool:
        """Take ownership of a still-running replica process from a
        previous gateway incarnation. Verifies pid liveness (signal 0)
        only — the caller cross-checks with a /health probe on the port
        before routing anything. Returns False (and adopts nothing) on
        a dead/invalid pid."""
        try:
            pid = int(pid)
            port = int(port)
        except (TypeError, ValueError):
            return False
        if pid <= 0 or port <= 0:
            return False
        try:
            os.kill(pid, 0)
        except OSError:
            return False
        self._proc = None
        self._adopted_pid = pid
        self._port = port
        return True

    def abandon_adoption(self) -> None:
        """Forget an adoption that failed its health cross-check WITHOUT
        signaling the pid (it may belong to an innocent recycled-pid
        stranger). The next ``start()`` relaunches on a fresh port."""
        self._adopted_pid = None
        self._port = None

    @property
    def pid(self) -> int | None:
        """The replica process id — spawned or adopted — for the fleet
        manifest. None when not running."""
        if self._proc is not None:
            return self._proc.pid
        return self._adopted_pid

    def _adopted_wait(self, timeout: float) -> bool:
        """Poll an adopted (non-child, un-``wait``-able) pid for death;
        True once it is gone."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                os.kill(self._adopted_pid, 0)
            except OSError:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def _stop_adopted(self, drain: bool, timeout: float) -> None:
        try:
            if drain:
                os.kill(self._adopted_pid, signal.SIGTERM)
                if self._adopted_wait(timeout):
                    self._adopted_pid = None
                    return
                logger.warning(
                    "adopted replica %s did not drain in %.1fs; killing",
                    self.id, timeout,
                )
            os.kill(self._adopted_pid, signal.SIGKILL)
            self._adopted_wait(10.0)
        except OSError:
            pass
        self._adopted_pid = None

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        if self._adopted_pid is not None:
            self._stop_adopted(drain, timeout)
            return
        proc, self._proc = self._proc, None
        if proc is None or proc.poll() is not None:
            return
        try:
            if drain:
                proc.terminate()  # SIGTERM -> server drains and exits
                try:
                    proc.wait(timeout=timeout)
                    return
                except subprocess.TimeoutExpired:
                    logger.warning(
                        "replica %s did not drain in %.1fs; killing",
                        self.id, timeout,
                    )
            proc.kill()
            proc.wait(timeout=10.0)
        except OSError:
            pass

    def kill(self) -> None:
        if self._adopted_pid is not None:
            self._stop_adopted(drain=False, timeout=0.0)
            return
        proc, self._proc = self._proc, None
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):
                pass

    def alive(self) -> bool:
        if self._proc is not None:
            return self._proc.poll() is None
        if self._adopted_pid is not None:
            try:
                os.kill(self._adopted_pid, 0)
                return True
            except OSError:
                return False
        return False

    @property
    def address(self) -> tuple[str, int] | None:
        if self._port is None:
            return None
        return (self._host, self._port)


@dataclasses.dataclass(frozen=True)
class ReplicaView:
    """Immutable routing snapshot of one replica (what router policies
    see). ``outstanding`` is the gateway's own in-flight count (instant);
    ``queue_depth``/``active_slots`` come from the last health poll
    (slightly stale, refreshed every supervisor interval)."""

    id: str
    address: tuple[str, int]
    outstanding: int
    queue_depth: int
    active_slots: int
    capacity: int
    live: bool
    draining: bool
    # Measured prefix-cache accounting from the replica's last /health poll
    # (ISSUE 8): lifetime reused vs prefilled prompt tokens. The gateway's
    # /metrics derives per-replica and token-weighted fleet hit ratios from
    # these, next to the routing-side affinity hit-rate — the measurement
    # the affinity router's "routed hit => KV reuse" claim is validated
    # against. 0/0 on engines without the accounting (lockstep replicas).
    cache_hit_tokens: int = 0
    cache_miss_tokens: int = 0
    # Windowed hit/miss token deltas over the last few health polls
    # (ISSUE 9): the lifetime counters above go stale-sticky on long-lived
    # replicas (an hour of 90% hits pins the ratio near 0.9 no matter what
    # the replica is doing NOW), so the Fleet keeps per-poll deltas and the
    # router's spill steering consumes the windowed ratio instead. 0/0 when
    # the window is empty or the replica has been idle long enough for the
    # window to age out — recent_cache_hit_ratio is then None ("stale") and
    # the spill walk falls back to its deterministic ring order.
    recent_cache_hit_tokens: int = 0
    recent_cache_miss_tokens: int = 0
    # Disaggregated-fleet role (ISSUE 9): "hybrid" | "prefill_heavy" |
    # "decode_heavy" — health-reported when present, else the handle's
    # configured role.
    role: str = "hybrid"
    # Latency snapshot from the replica's last /health poll (lifetime
    # histogram quantiles): the per-role TTFT/TPOT aggregation on gateway
    # /metrics reads these; None on replicas that have served nothing.
    ttft_p95_s: float | None = None
    tpot_p95_s: float | None = None
    # Measured time-to-first-ready the replica stamped on /health
    # (ISSUE 12): process start -> port bound, compile cache included.
    # The autoscale planner's scale-to-zero wake budget is derived from
    # this, never from a constant. None until the replica reports one.
    cold_start_s: float | None = None
    # KV handoff inputs (ISSUE 13), all health-reported: whether the
    # replica serves the /internal KV endpoints, its measured device_put
    # bandwidth (MB/s over imports), its measured prefill tok/s, and its
    # KV bytes per token — the gateway's transfer-cost model reads these
    # (None = unmeasured; the model falls back to the configured floors).
    kv_handoff: bool = False
    kv_put_mbps: float | None = None
    prefill_tok_per_s: float | None = None
    kv_bytes_per_token: float | None = None
    # Event-loop lag p95 from the replica's watchdog (ISSUE 18): how long
    # its loop sits busy per iteration. None when the watchdog is unarmed
    # or has no observations yet (absent != 0) — a degrading loop is
    # visible to the planner before TPOT storms are.
    loop_lag_p95_s: float | None = None

    @property
    def cache_hit_ratio(self) -> float | None:
        total = self.cache_hit_tokens + self.cache_miss_tokens
        if total == 0:
            return None
        return self.cache_hit_tokens / total

    @property
    def recent_cache_hit_ratio(self) -> float | None:
        """Hit ratio over the last few health-poll windows; None when no
        prompt tokens moved recently (stale — routers must not steer on
        it)."""
        total = self.recent_cache_hit_tokens + self.recent_cache_miss_tokens
        if total == 0:
            return None
        return self.recent_cache_hit_tokens / total

    @property
    def slot_pressure(self) -> float:
        """active_slots / capacity in [0, 1] — the load signal the
        autoscaling roadmap item consumes from the same view (ISSUE 9
        de-risk hook)."""
        return self.active_slots / max(1, self.capacity)


@dataclasses.dataclass
class _ReplicaState:
    handle: ReplicaHandle
    live: bool = False
    draining: bool = False
    restarting: bool = False
    outstanding: int = 0
    fails: int = 0
    health: dict = dataclasses.field(default_factory=dict)
    restarts: int = 0
    # Actuation-plane states (ISSUE 12). ``deactivated``: parked by a
    # scale-down — stopped on purpose, excluded from routing AND from the
    # supervisor's recovery (a parked replica must not be "healed" back
    # up); a scale-up reverses it. ``quarantined``: the crash-loop
    # breaker — stopped, excluded from supervision, and NOT reversed by
    # demand (an operator or a fresh launch clears it).
    deactivated: bool = False
    quarantined: bool = False
    # Windowed prefix-cache accounting (ISSUE 9): the last observed
    # lifetime (hit, miss) counters and a bounded deque of per-poll
    # deltas. Idle polls append (0, 0), so activity ages out of the window
    # naturally — that IS the freshness signal.
    last_cache: tuple[int, int] | None = None
    cache_window: collections.deque = dataclasses.field(
        default_factory=collections.deque
    )


class Fleet:
    """Thread-safe shared state over a set of replica handles — the data
    plane's view (gateway reads it per request) and the control plane's
    (the supervisor writes it per poll)."""

    def __init__(self, handles: Sequence[ReplicaHandle],
                 default_capacity: int = 8,
                 cache_window_polls: int = 8):
        if not handles:
            raise ValueError("a fleet needs at least one replica")
        ids = [h.id for h in handles]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        if cache_window_polls < 1:
            raise ValueError(
                f"cache_window_polls must be >= 1, got {cache_window_polls}"
            )
        self.default_capacity = default_capacity
        self.cache_window_polls = cache_window_polls
        # Optional FleetManifest (gateway/recovery.py, ISSUE 20): when
        # installed, every fleet mutation below re-records the
        # crash-consistent on-disk snapshot a --recover incarnation
        # adopts from. None on manifest-less fleets (tests, ephemeral
        # gateways) — zero overhead then.
        self._manifest = None
        # Upstream keep-alive pool (ISSUE 14): shared by the gateway's
        # relay plane, the supervisor's health polls, and the fan-out
        # probes — one pool per fleet so lifecycle invalidation has one
        # place to land. make_gateway applies the config's caps.
        self.pool = ConnectionPool()
        self._lock = threading.Lock()
        self._states = {
            h.id: _ReplicaState(
                handle=h,
                cache_window=collections.deque(maxlen=cache_window_polls),
            )
            for h in handles
        }
        for h in handles:
            # Health polls ride the fleet's pool (ISSUE 14) — installed
            # on the handle so probe-method overrides in tests keep their
            # signatures.
            h.pool = self.pool

    @property
    def manifest(self):
        return self._manifest

    @manifest.setter
    def manifest(self, manifest) -> None:
        """Installing a manifest wires its fleet back-reference in the
        same breath — record() reads ``manifest.fleet``, and a manifest
        installed without the back-reference would silently no-op on
        every mutation (exactly the bug this setter exists to prevent)."""
        self._manifest = manifest
        if manifest is not None:
            manifest.fleet = self

    @property
    def ids(self) -> list[str]:
        return list(self._states)

    def handle(self, replica_id: str) -> ReplicaHandle:
        return self._states[replica_id].handle

    # -- lifecycle ----------------------------------------------------------

    def start_all(self, wait_healthy_s: float = 0.0) -> None:
        """Start every replica; optionally block until each answers
        /health (subprocess replicas pay a jax import + engine build before
        the port even opens).

        Recovery-aware (ISSUE 20): replicas that are already alive
        (adopted from a previous incarnation) are not restarted, and
        replicas restored as parked/quarantined are down on purpose —
        both are skipped. On a fresh fleet neither condition holds and
        every replica starts, as before."""
        for st in self._states.values():
            if st.deactivated or st.quarantined:
                continue
            if st.handle.alive():
                continue
            st.handle.start()
        if wait_healthy_s > 0:
            deadline = time.monotonic() + wait_healthy_s
            for rid in self.ids:
                st = self._states[rid]
                if st.deactivated or st.quarantined:
                    continue
                while time.monotonic() < deadline:
                    if self.probe(rid):
                        break
                    time.sleep(0.1)
                else:
                    raise TimeoutError(
                        f"replica {rid} not healthy after "
                        f"{wait_healthy_s:.0f}s"
                    )
        self._record_manifest()

    def stop_all(self, drain: bool = True, timeout: float = 30.0) -> None:
        # Parked upstream sockets must not hold the replicas' drains open
        # (an idle kept-alive connection parks a handler thread at the
        # replica); the pool is terminal after this.
        self.pool.close()
        for st in self._states.values():
            st.handle.stop(drain=drain, timeout=timeout)
            with self._lock:
                st.live = False
        self._record_manifest()

    def probe(self, replica_id: str, timeout: float = 2.0) -> bool:
        """One health poll, folded into the routing state. Returns True if
        the replica answered."""
        st = self._states[replica_id]
        health = st.handle.fetch_health(timeout=timeout)
        with self._lock:
            if health is None:
                st.fails += 1
                # One refused connect is already definitive when the
                # process/thread is gone; stale-but-alive needs the
                # supervisor's threshold.
                if not st.handle.alive():
                    st.live = False
            else:
                st.fails = 0
                st.live = True
                st.health = health
                self._note_cache_window(st, health)
                # A replica draining ITSELF (SIGTERM) must fall out of
                # routing even if the gateway didn't initiate the drain.
                if health.get("draining"):
                    st.draining = True
        return health is not None

    @staticmethod
    def _note_cache_window(st: _ReplicaState, health: dict) -> None:
        """Fold one health poll into the windowed hit/miss deltas
        (ISSUE 9). The /health counters are lifetime-cumulative, so the
        recent ratio is built from per-poll differences; a counter that
        went BACKWARDS means the replica restarted with a fresh engine —
        the window resets rather than recording a nonsense negative delta.
        Caller holds the fleet lock."""
        if "cache_hit_tokens" not in health \
                and "cache_miss_tokens" not in health:
            return
        cur = (int(health.get("cache_hit_tokens", 0)),
               int(health.get("cache_miss_tokens", 0)))
        prev, st.last_cache = st.last_cache, cur
        if prev is None or cur[0] < prev[0] or cur[1] < prev[1]:
            st.cache_window.clear()
            return
        st.cache_window.append((cur[0] - prev[0], cur[1] - prev[1]))

    # -- routing-plane accessors -------------------------------------------

    def _view(self, st: _ReplicaState) -> ReplicaView | None:
        addr = st.handle.address
        if addr is None:
            return None
        h = st.health
        n_slots = int(h.get("n_slots", 0)) or self.default_capacity
        ttft = h.get("ttft_p95_s")
        tpot = h.get("tpot_p95_s")
        cold = h.get("cold_start_s")

        def _num(key):
            v = h.get(key)
            return float(v) if isinstance(v, (int, float)) else None

        return ReplicaView(
            id=st.handle.id,
            address=addr,
            outstanding=st.outstanding,
            queue_depth=int(h.get("queue_depth", 0)),
            active_slots=int(h.get("active_slots", 0)),
            capacity=n_slots,
            live=st.live,
            draining=st.draining,
            cache_hit_tokens=int(h.get("cache_hit_tokens", 0)),
            cache_miss_tokens=int(h.get("cache_miss_tokens", 0)),
            recent_cache_hit_tokens=sum(d[0] for d in st.cache_window),
            recent_cache_miss_tokens=sum(d[1] for d in st.cache_window),
            role=str(h.get("role") or st.handle.role or "hybrid"),
            ttft_p95_s=float(ttft) if isinstance(ttft, (int, float)) else None,
            tpot_p95_s=float(tpot) if isinstance(tpot, (int, float)) else None,
            cold_start_s=float(cold) if isinstance(cold, (int, float))
            else None,
            kv_handoff=bool(h.get("kv_handoff", False)),
            kv_put_mbps=_num("kv_put_mbps"),
            prefill_tok_per_s=_num("prefill_tok_per_s"),
            kv_bytes_per_token=_num("kv_bytes_per_token"),
            loop_lag_p95_s=_num("loop_lag_p95_s"),
        )

    def routable(self, exclude: Sequence[str] = ()) -> list[ReplicaView]:
        """Live, non-draining, non-parked replicas (minus ``exclude`` —
        the ones this request already failed on)."""
        with self._lock:
            views = [
                self._view(st) for rid, st in self._states.items()
                if st.live and not st.draining and not st.deactivated
                and not st.quarantined and rid not in exclude
            ]
        return [v for v in views if v is not None]

    def views(self) -> list[ReplicaView]:
        with self._lock:
            views = [self._view(st) for st in self._states.values()]
        return [v for v in views if v is not None]

    def live_count(self) -> int:
        with self._lock:
            return sum(st.live for st in self._states.values())

    def draining_count(self) -> int:
        with self._lock:
            return sum(st.draining for st in self._states.values())

    # -- data-plane bookkeeping --------------------------------------------

    def inc_outstanding(self, replica_id: str) -> None:
        with self._lock:
            self._states[replica_id].outstanding += 1

    def dec_outstanding(self, replica_id: str) -> None:
        with self._lock:
            st = self._states[replica_id]
            st.outstanding = max(0, st.outstanding - 1)

    def outstanding(self, replica_id: str) -> int:
        with self._lock:
            return self._states[replica_id].outstanding

    def note_failure(self, replica_id: str) -> None:
        """The gateway observed a connection error proxying to this
        replica: mark it down IMMEDIATELY if its process/thread is gone
        (routing must not wait a poll interval to stop feeding a corpse);
        otherwise bump the failure count for the supervisor's threshold."""
        with self._lock:
            st = self._states[replica_id]
            st.fails += 1
            if not st.handle.alive():
                st.live = False

    def mark_draining(self, replica_id: str, draining: bool) -> None:
        with self._lock:
            self._states[replica_id].draining = draining
        self._record_manifest()

    # -- actuation-plane state (ISSUE 12) -----------------------------------

    def set_deactivated(self, replica_id: str, deactivated: bool) -> None:
        with self._lock:
            self._states[replica_id].deactivated = deactivated
        if deactivated:
            # A scale-down park takes the replica's process down; parked
            # keep-alive sockets to it are dead weight that would read as
            # a stale-socket storm later (ISSUE 14 lifecycle hook).
            self.pool.invalidate(replica_id)
        self._record_manifest()

    def set_quarantined(self, replica_id: str, quarantined: bool) -> None:
        with self._lock:
            self._states[replica_id].quarantined = quarantined
        if quarantined:
            self.pool.invalidate(replica_id)
        self._record_manifest()

    def active_ids(self) -> list[str]:
        """Replicas participating in serving (not parked, not
        quarantined) — the autoscale planner's fleet-size denominator;
        liveness is separate (a crashed-but-recovering replica is still
        active)."""
        with self._lock:
            return [rid for rid, st in self._states.items()
                    if not st.deactivated and not st.quarantined]

    def parked_ids(self) -> list[str]:
        """Scale-down-parked replicas — the scale-up candidate pool."""
        with self._lock:
            return [rid for rid, st in self._states.items()
                    if st.deactivated and not st.quarantined]

    def quarantined_ids(self) -> list[str]:
        with self._lock:
            return [rid for rid, st in self._states.items()
                    if st.quarantined]

    def _state(self, replica_id: str) -> _ReplicaState:
        return self._states[replica_id]

    # -- crash-recovery manifest (ISSUE 20) ----------------------------------

    def manifest_snapshot(self) -> dict:
        """One locked snapshot of every replica's recoverable identity:
        pid (None on handle kinds that cannot be adopted), address, role
        and the down-on-purpose flags — the per-replica records a
        FleetManifest writes."""
        with self._lock:
            out = {}
            for rid, st in self._states.items():
                addr = st.handle.address
                out[rid] = {
                    "pid": getattr(st.handle, "pid", None),
                    "host": addr[0] if addr else None,
                    "port": addr[1] if addr else None,
                    "role": st.handle.role,
                    "live": st.live,
                    "draining": st.draining,
                    "deactivated": st.deactivated,
                    "quarantined": st.quarantined,
                    "restarts": st.restarts,
                }
            return out

    def _record_manifest(self) -> None:
        """Re-record the crash-recovery manifest after a fleet mutation.
        Called OUTSIDE the fleet state lock (record() re-enters it via
        manifest_snapshot). No-op on manifest-less fleets."""
        manifest = self.manifest
        if manifest is not None:
            manifest.record()


class FleetSupervisor:
    """Health-poll loop + recovery state machine over a :class:`Fleet`.

    Poll every ``interval_s``; a replica whose process died, or that missed
    ``fail_threshold`` consecutive health checks, takes the recovery path::

        replica.died -> replica.drain -> replica.relaunch -> replica.readmit

    each step journaled (``events-gateway.jsonl``). The same primitives
    compose into :meth:`rolling_restart`, the zero-downtime fleet restart.
    """

    def __init__(
        self,
        fleet: Fleet,
        *,
        interval_s: float = 0.5,
        fail_threshold: int = 3,
        probe_timeout_s: float = 2.0,
        restart_timeout_s: float = 120.0,
        max_restarts_per_replica: int = 10,
        journal: EventJournal | None = None,
        log: Callable[[str], None] | None = None,
        anomaly=None,
        metrics=None,
        autoscaler=None,
    ):
        """``anomaly``: optional telemetry.anomaly.GatewayAnomalyMonitor —
        notified of each replica death (the death-rate detector's input,
        ISSUE 10) and polled once per supervision pass so spill/error
        storms and fleet SLO burns are evaluated headlessly. ``metrics``:
        optional GatewayMetrics whose ``replica_deaths`` counter this
        supervisor increments on every death — unconditionally, not gated
        on the anomaly plane, so the /metrics family is honest on
        unarmed gateways too. ``autoscaler``: optional
        gateway.autoscale.Actuator — notified of each death (the
        quarantine planner's crash-loop input) and polled once per
        supervision pass (the planner cadence rides the health loop like
        the anomaly monitor's, ISSUE 12)."""
        self.fleet = fleet
        self.interval_s = interval_s
        self.fail_threshold = fail_threshold
        self.probe_timeout_s = probe_timeout_s
        self.restart_timeout_s = restart_timeout_s
        self.max_restarts_per_replica = max_restarts_per_replica
        self._journal = journal
        self._journal_lock = threading.Lock()
        self._log = log or (lambda msg: logger.info("%s", msg))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._recoveries: dict[str, threading.Thread] = {}
        self._given_up: set[str] = set()
        self.anomaly = anomaly
        self.metrics = metrics
        self.autoscaler = autoscaler
        # THE fleet-mutation lock (ISSUE 12 satellite): crash recovery,
        # rolling restarts, and autoscale/remediation actuation each
        # change fleet membership over seconds (drain -> stop -> start ->
        # await-healthy); before this lock a relaunch racing a concurrent
        # membership change was only safe by luck of thread timing. Every
        # mutation cycle — _recover, rolling_restart's per-replica leg,
        # and gateway.autoscale.Actuator.apply (which shares this very
        # Lock object) — runs start-to-finish under it. Held across
        # await-healthy on purpose: a half-started replica is exactly the
        # state a concurrent mutation must not observe.
        self.fleet_lock = threading.Lock()
        # Which replica the current mutation cycle is changing ("" =
        # none) — the lock-discipline-enforced witness that every
        # membership mutation path actually holds fleet_lock.
        self._mutating = ""  # guarded-by: fleet_lock

    def journal_event(self, event: str, **attrs) -> None:
        if self._journal is not None:
            with self._journal_lock:
                self._journal.event(event, **attrs)

    # -- loop ---------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-supervisor"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # Recovery threads watch _stop inside _await_healthy; give them a
        # moment to unwind (daemon threads — a wedged restart never blocks
        # process exit).
        for t in list(self._recoveries.values()):
            t.join(timeout=5.0)
        self._recoveries.clear()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            # Chaos seam (ISSUE 20): the gateway-process SIGKILL the
            # crash-recovery drill injects. The kill is orchestrated
            # here — journaled FIRST (line-buffered, so the crash row
            # survives the kill and the merged timeline reads
            # gateway.crash -> recovery.start in causal order with
            # chaos attribution) — then executed, uncatchable.
            fault = maybe_inject("gateway.crash", handles=("kill",))
            if fault is not None and fault.action == "kill":
                self.journal_event("gateway.crash", chaos=True,
                                   site=fault.site)
                fault.kill_now()
            try:
                self.poll_once()
            except Exception:
                logger.exception("fleet supervisor poll failed")
            manifest = self.fleet.manifest
            if manifest is not None:
                # Bounded-staleness refresh: keeps the slow-moving
                # non-mutation parts of the manifest (admission bucket
                # levels, liveness bits) at most a couple of seconds
                # stale without a write per request.
                manifest.maybe_refresh()
            if self.anomaly is not None:
                # Headless anomaly cadence (ISSUE 10): the health loop is
                # the gateway's only periodic thread, so storm detectors
                # and SLO burn evaluation ride it (the monitor rate-limits
                # itself and never raises).
                self.anomaly.poll()
            if self.autoscaler is not None:
                # Actuation cadence (ISSUE 12): plan + apply once per
                # supervision pass, against the health state this pass
                # just refreshed. The actuator never raises.
                self.autoscaler.poll()

    def poll_once(self) -> None:
        for rid in self.fleet.ids:
            if self._stop.is_set():
                return
            st = self.fleet._state(rid)
            if st.restarting or rid in self._given_up:
                continue
            if st.deactivated or st.quarantined:
                # Parked/quarantined replicas are DOWN ON PURPOSE: probing
                # them would count failures, and recovering them would
                # undo the action that parked them (the actuator owns
                # their lifecycle).
                continue
            self.fleet.probe(rid, timeout=self.probe_timeout_s)
            dead = (not st.handle.alive()) or st.fails >= self.fail_threshold
            if dead and not st.restarting:
                # Recover on a per-replica thread: a relaunch can block up
                # to restart_timeout_s, and the poll loop must keep probing
                # (and recovering) the REST of the fleet meanwhile.
                st.restarting = True
                t = threading.Thread(
                    target=self._recover, args=(rid,), daemon=True,
                    name=f"recover-{rid}",
                )
                self._recoveries[rid] = t
                t.start()

    # -- recovery -----------------------------------------------------------

    def _recover(self, rid: str) -> None:
        """Run one died -> drain -> relaunch -> re-admit cycle. The caller
        (poll_once / tests) sets ``st.restarting`` BEFORE invoking so the
        poll loop cannot double-recover; this method clears it. The whole
        cycle runs under the fleet-mutation lock, serialized against
        rolling restarts and autoscale actuation."""
        st = self.fleet._state(rid)
        try:
            with self.fleet_lock:
                self._mutating = rid
                try:
                    self._recover_cycle_locked(rid, st)
                finally:
                    self._mutating = ""
        finally:
            st.restarting = False

    def _recover_cycle_locked(self, rid: str, st: _ReplicaState) -> None:
        """The recovery cycle proper; caller holds ``fleet_lock``."""
        if st.deactivated or st.quarantined:
            # The replica was parked/quarantined while this recovery
            # waited on the fleet-mutation lock (a scale-down racing a
            # kill): it is down ON PURPOSE now — relaunching it would
            # undo the action that won the lock first.
            self._log(f"replica {rid}: parked/quarantined while awaiting "
                      "recovery; leaving down")
            return
        if st.restarts >= self.max_restarts_per_replica:
            self._log(f"replica {rid}: restart budget exhausted "
                      f"({st.restarts}); leaving dead")
            st.live = False
            self._given_up.add(rid)
            return
        st.live = False
        # Pooled sockets to a dead replica are all stale; invalidating
        # here (not lazily at the next checkout) frees them en masse and
        # makes the discard count an honest death signature (ISSUE 14).
        self.fleet.pool.invalidate(rid)
        self.journal_event("replica.died", replica=rid,
                          fails=st.fails,
                          process_alive=st.handle.alive())
        if self.metrics is not None:
            self.metrics.replica_deaths.inc()
        if self.anomaly is not None:
            # Death-rate input (ISSUE 10): one crash self-heals; a
            # crash loop crosses the detector's windowed threshold and
            # becomes an incident bundle.
            self.anomaly.note_replica_death(rid)
        if self.autoscaler is not None:
            # Quarantine input (ISSUE 12): the planner's per-replica death
            # window — past the threshold it plans the quarantine that
            # breaks the crash loop this recovery would otherwise feed.
            self.autoscaler.note_death(rid)
        self._log(f"replica {rid}: died (failed health checks: "
                  f"{st.fails}); draining routing")
        # Drain: routing already stopped (live=False); anything still
        # in flight on the gateway side fails over via its retry path.
        self.fleet.mark_draining(rid, True)
        self.journal_event("replica.drain", replica=rid)
        st.restarts += 1
        self.journal_event("replica.relaunch", replica=rid,
                          attempt=st.restarts)
        self._log(f"replica {rid}: relaunching "
                  f"(attempt {st.restarts})")
        st.handle.restart()
        if self._await_healthy(rid):
            st.fails = 0
            self.fleet.mark_draining(rid, False)
            self.journal_event("replica.readmit", replica=rid,
                              address=list(st.handle.address or ()))
            self._log(f"replica {rid}: healthy again; re-admitted")
        else:
            self.journal_event("replica.restart_failed", replica=rid,
                              attempt=st.restarts)
            self._log(f"replica {rid}: relaunch did not become healthy "
                      f"within {self.restart_timeout_s:.0f}s")
            # fails stays >= threshold: next poll retries recovery.
            st.fails = max(st.fails, self.fail_threshold)

    def drain_stop_locked(self, rid: str, st: _ReplicaState,
                          timeout_s: float) -> None:
        """Graceful stop of one replica whose routing has already been
        cut (draining/parked): wait for the gateway's own in-flight
        proxies to clear — the replica-side ``close(drain=True)`` then
        has nothing (or only direct clients) to wait on — then stop it.
        Caller holds ``fleet_lock``; the ONE drain-stop spelling shared
        by rolling restarts and the autoscale actuator's scale-down and
        drain paths."""
        deadline = time.monotonic() + timeout_s
        while (self.fleet.outstanding(rid) > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        # Idle pooled sockets would wedge the replica-side drain (each
        # parks a handler thread there) and are useless after the stop
        # either way — rolling restarts and the actuator's scale-down/
        # drain paths all come through here (ISSUE 14 lifecycle hook).
        self.fleet.pool.invalidate(rid)
        st.handle.stop(drain=True, timeout=timeout_s)
        st.live = False

    def _await_healthy(self, rid: str) -> bool:
        deadline = time.monotonic() + self.restart_timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            if self.fleet.probe(rid, timeout=self.probe_timeout_s):
                return True
            time.sleep(min(0.2, self.interval_s))
        return False

    # -- rolling restart ----------------------------------------------------

    def rolling_restart(self, drain_timeout_s: float = 60.0) -> None:
        """Restart every replica one at a time with zero failed requests:
        drain (gateway stops routing to it; in-flight work finishes inside
        the replica's own ``close(drain=True)``), relaunch, wait healthy,
        re-admit — then the next replica. Requires >= 2 replicas to be
        zero-downtime (the rest of the fleet absorbs the traffic). Each
        per-replica leg runs under the fleet-mutation lock, serialized
        against crash recovery and autoscale actuation (a scale-up landing
        mid-rolling-restart waits its turn instead of racing the drain)."""
        for rid in self.fleet.ids:
            st = self.fleet._state(rid)
            if st.deactivated or st.quarantined:
                # Parked/quarantined replicas are down on purpose; a
                # rolling restart must not resurrect them.
                continue
            st.restarting = True  # the poll loop must not double-recover
            try:
                with self.fleet_lock:
                    self._mutating = rid
                    try:
                        self._rolling_one_locked(rid, st, drain_timeout_s)
                    finally:
                        self._mutating = ""
            finally:
                st.restarting = False

    def _rolling_one_locked(self, rid: str, st: _ReplicaState,
                            drain_timeout_s: float) -> None:
        """One replica's drain -> restart -> re-admit leg; caller holds
        ``fleet_lock``."""
        if st.deactivated or st.quarantined:
            # Parked/quarantined while this leg WAITED on the lock (an
            # autoscale action won it first): down on purpose now —
            # restarting it would leave a running process the fleet
            # believes is parked. Same re-check _recover_cycle_locked
            # makes.
            self._log(f"rolling restart: {rid} parked/quarantined while "
                      "awaiting the lock; skipping")
            return
        self.fleet.mark_draining(rid, True)
        self.journal_event("replica.drain", replica=rid,
                          rolling=True)
        self._log(f"rolling restart: draining {rid}")
        self.drain_stop_locked(rid, st, drain_timeout_s)
        # A planned restart does NOT consume the crash-restart
        # budget (max_restarts_per_replica guards crash LOOPS);
        # nightly rolling restarts must never leave a replica
        # permanently dead on its first real failure.
        self.journal_event("replica.relaunch", replica=rid,
                          rolling=True)
        st.handle.start()
        if not self._await_healthy(rid):
            self.journal_event("replica.restart_failed",
                              replica=rid, rolling=True)
            raise TimeoutError(
                f"rolling restart: {rid} not healthy within "
                f"{self.restart_timeout_s:.0f}s"
            )
        st.fails = 0
        self.fleet.mark_draining(rid, False)
        self.journal_event("replica.readmit", replica=rid,
                          rolling=True)
        self._log(f"rolling restart: {rid} re-admitted")
