"""Serving gateway subsystem (ISSUE 4): one OpenAI-compatible front door
over a fleet of engine replicas — cache-affinity routing (router.py),
drain/restart supervision (replica.py), per-tenant admission
(admission.py), and the proxying HTTP gateway itself (gateway.py).

Stdlib-only by design: importing this package never touches jax, so the
gateway can run as a thin front process and its logic is unit-testable
against stub replicas."""

from ditl_tpu.gateway.admission import (
    AdmissionDecision,
    TenantAdmission,
    TokenBucket,
    sanitize_label,
    tenant_label,
)
from ditl_tpu.gateway.autoscale import (
    Action,
    ActionPlanner,
    Actuator,
    FleetSignals,
    ReplicaSecondsSampler,
    TrafficRecorder,
    load_trace,
)
from ditl_tpu.gateway.gateway import GatewayMetrics, make_gateway
from ditl_tpu.gateway.pool import ConnectionPool
from ditl_tpu.gateway.recovery import (
    FleetManifest,
    load_manifest,
    manifest_path,
    reconcile_adapters,
    recover_fleet,
    replay_action_tail,
)
from ditl_tpu.gateway.replica import (
    Fleet,
    FleetSupervisor,
    InProcessReplica,
    ReplicaHandle,
    ReplicaView,
    SubprocessReplica,
    gateway_journal_path,
)
from ditl_tpu.gateway.roles import (
    ROLES,
    parse_roles,
    role_candidates,
    role_knobs,
)
from ditl_tpu.gateway.router import (
    CacheAffinityPolicy,
    LeastOutstandingPolicy,
    RoundRobinPolicy,
    affinity_key,
    make_policy,
    prompt_token_estimate,
    stable_hash,
)

__all__ = [
    "Action",
    "ActionPlanner",
    "Actuator",
    "AdmissionDecision",
    "CacheAffinityPolicy",
    "ConnectionPool",
    "Fleet",
    "FleetManifest",
    "FleetSignals",
    "FleetSupervisor",
    "GatewayMetrics",
    "InProcessReplica",
    "LeastOutstandingPolicy",
    "ROLES",
    "ReplicaHandle",
    "ReplicaSecondsSampler",
    "ReplicaView",
    "RoundRobinPolicy",
    "SubprocessReplica",
    "TenantAdmission",
    "TokenBucket",
    "TrafficRecorder",
    "affinity_key",
    "gateway_journal_path",
    "load_manifest",
    "load_trace",
    "make_gateway",
    "make_policy",
    "manifest_path",
    "parse_roles",
    "prompt_token_estimate",
    "reconcile_adapters",
    "recover_fleet",
    "replay_action_tail",
    "role_candidates",
    "role_knobs",
    "sanitize_label",
    "stable_hash",
    "tenant_label",
]
