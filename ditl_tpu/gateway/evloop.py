"""Event-driven gateway data plane (ISSUE 17): a single-threaded
``selectors`` loop that holds every client connection and every live SSE
stream without a parked thread.

``ThreadingHTTPServer`` spends one handler thread (+~8 MB stack) per open
connection, so open-stream concurrency dies at a few hundred no matter
how cheap PR 14 made each request. This module replaces the TRANSPORT
only — the control plane (admission, routing, retries, hedging, KV
handoff, usage, tracing) is the same battle-tested ``_GatewayHandler``
code, run verbatim against an in-memory request/response pair on a small
bounded offload pool. Division of labor:

- **The loop** (thread name irrelevant; runs wherever ``serve_forever``
  is called, like ``ThreadingHTTPServer``): non-blocking accept, HTTP/1.1
  request framing (request line + headers split at CRLFCRLF, body by
  Content-Length), response write-out with partial-write buffering,
  keep-alive / pipelining, idle sweep, and — the point of the exercise —
  every detached SSE relay, both fds readiness-driven.
- **Offload workers** (``gw-offload``): one ``handle_one_request`` per
  framed request over a ``BytesIO`` pair. Non-streaming relays park a
  worker for the upstream duration (so the pool size caps concurrent
  non-stream relays); streams park a worker only until the FIRST upstream
  chunk, then detach: the handler returns, and the loop relays
  upstream→client from the raw sockets until EOF (SSE is close-delimited
  — no chunk decoding needed).

Detached streams carry deferred terminal state (``_evloop_detached`` in
gateway.py): admission release, e2e/usage rows, span ends, and the
counted pool discard all run at STREAM end, not handler return, so the
books read exactly as they do on the threaded path.

Functions that run on the loop are marked ``@event_loop`` and checked by
the ``event-loop-hygiene`` rule (analysis/rules_evloop.py): no sleep, no
sendall, no join, no un-witnessed lock wait. Cross-thread input arrives
through a lock-free ``deque.append`` plus a wakeup byte on a socketpair.

Stdlib-only, like everything under ditl_tpu/gateway (the import-layering
rule keeps this tree provably jax-free).
"""

from __future__ import annotations

import collections
import io
import select
import selectors
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ditl_tpu.annotations import event_loop
from ditl_tpu.chaos import maybe_inject
from ditl_tpu.config import GatewayConfig
from ditl_tpu.telemetry.prof import LoopHeartbeat, OffloadPoolMonitor
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["EventLoopGateway"]

# Framing caps: headers beyond this never parse (400 + close); bodies are
# bounded so a lying Content-Length cannot balloon the inbuf.
_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 64 * 1024 * 1024
_READ_CHUNK = 65536
# Client write backpressure: past this many buffered-but-unsent bytes the
# stream's upstream fd leaves the selector until the client drains — a
# slow consumer stalls ITS stream, never the loop or the replica pool.
_OUTBUF_PAUSE = 1 << 20

# Sticky fast path: after fully sending a keep-alive response, the
# offload worker camps on the (quiet) client socket this long for the
# next request before handing the connection back to the loop. Keeps a
# request-per-response closed loop entirely on one worker — the exact
# blocking pattern the threaded path wins with at low concurrency —
# while the guard in _handle_dispatch stops camping the moment workers get
# scarce, so high fan-in still degrades to pure event-driven dispatch.
_STICK_S = 0.01

_RESP_400 = (b"HTTP/1.1 400 Bad Request\r\nContent-Type: application/json\r\n"
             b"Content-Length: 26\r\nConnection: close\r\n\r\n"
             b'{"error": "bad request"}\r\n')


class _BadRequest(Exception):
    """Client bytes that cannot frame (malformed/oversized Content-Length,
    header block past the cap)."""


def _frame_request(buf: bytearray) -> int | None:
    """Length of the first complete request in ``buf`` (request line +
    headers + Content-Length body), ``None`` if more bytes are needed.
    Raises :class:`_BadRequest` on a malformed or oversized frame."""
    idx = buf.find(b"\r\n\r\n")
    if idx < 0:
        if len(buf) > _MAX_HEADER_BYTES:
            raise _BadRequest("header block exceeds cap")
        return None
    content_length = 0
    for line in bytes(buf[:idx]).split(b"\r\n")[1:]:
        if line[:15].lower() == b"content-length:":
            try:
                content_length = int(line[15:])
            except ValueError:
                raise _BadRequest("malformed Content-Length") from None
    if content_length < 0 or content_length > _MAX_BODY_BYTES:
        raise _BadRequest("Content-Length out of range")
    total = idx + 4 + content_length
    return total if len(buf) >= total else None


def _run_stream_terminal(det: dict, ok: bool, blame: bool) -> None:
    """Deferred terminal accounting for a detached SSE stream — the exact
    bookkeeping the threaded path runs inline when ``_relay_stream``
    returns (route-level complete/abort counters, relay + root span ends,
    admission release, e2e observation, usage row, counted pool discard).
    Runs on an offload worker (it writes ledgers), inline only during
    ``server_close`` teardown. ``blame`` distinguishes the replica dying
    mid-stream (note_failure feeds the supervisor, threaded parity) from
    a client-side abort or a drain sever — severing a healthy stream must
    not push a healthy replica toward fail_threshold."""
    h = det["handler"]
    view = det["view"]
    try:
        if blame:
            h.fleet.note_failure(view.id)
            logger.warning("replica %s died mid-stream", view.id)
        det["complete"](ok)
        rspan = det.get("rspan")
        if rspan is not None:
            rspan.end(outcome="done" if ok else "aborted")
        det["finish"]("200" if ok else "cancel")
        root = det.get("root")
        if root is not None:
            root.end()
    except Exception:
        logger.exception("evloop: deferred stream accounting failed")
    finally:
        # Counted discard (ISSUE 14 parity), then release the fd: for a
        # Connection: close response the socket belongs to the RESPONSE
        # (conn.sock is already None), so the discard alone would leak it.
        try:
            h.fleet.pool.discard(det["conn"])
        except OSError:
            pass
        try:
            det["resp"].close()
        except OSError:
            pass


def _stream_socket(upstream, resp):
    """The live socket under a detached SSE response. http.client nulls
    ``conn.sock`` in ``getresponse()`` for Connection: close responses
    ("the connection passes to the response") — the fd stays open through
    the response's buffered reader (``resp.fp``, a BufferedReader over
    SocketIO), so recover the socket object from there."""
    if getattr(upstream, "sock", None) is not None:
        return upstream.sock
    raw = getattr(getattr(resp, "fp", None), "raw", None)
    return getattr(raw, "_sock", None)


class _Conn:
    """One client connection's state machine. States:

    ``idle``        reading/awaiting a request (keep-alive included)
    ``dispatched``  a worker is running the handler for its request
    ``writing``     flushing a buffered response
    ``streaming``   an SSE relay owns it (``stream`` is set)
    ``closed``      socket gone (terminal)
    """

    __slots__ = ("sock", "fd", "addr", "inbuf", "outbuf", "out_off",
                 "out_bytes", "state", "close_after", "last_activity",
                 "stream", "mask", "defer_close")

    def __init__(self, sock, addr):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.inbuf = bytearray()
        self.outbuf: collections.deque = collections.deque()
        self.out_off = 0
        self.out_bytes = 0
        self.state = "idle"
        self.close_after = False
        self.last_activity = time.monotonic()
        self.stream = None
        self.mask = 0
        # Close arrived while a worker may be mid-optimistic-send on this
        # fd: the actual sock.close() is deferred to _on_handled so the
        # OS can never reuse the fd number under the worker's send.
        self.defer_close = False


class _Stream:
    """One detached SSE relay: upstream raw socket → client outbuf."""

    __slots__ = ("conn", "det", "usock", "timeout_s",
                 "last_upstream", "eof", "paused", "registered")

    def __init__(self, conn: _Conn, det: dict, usock, timeout_s: float):
        self.conn = conn
        self.det = det
        self.usock = usock
        self.timeout_s = timeout_s
        self.last_upstream = time.monotonic()
        self.eof = False
        self.paused = False
        self.registered = False


class EventLoopGateway:
    """Drop-in transport for :class:`GatewayHTTPServer`: same four-method
    surface (``serve_forever``/``shutdown``/``server_close``/
    ``server_address``) plus ``drain(timeout_s)``, same handler-visible
    server attributes (``_rate_samples``, ``_hedge_pool``,
    ``_fanout_pool``, ``draining``). ``make_gateway`` picks it when
    ``gateway.data_plane = "evloop"`` (the default)."""

    allow_reuse_address = True

    def __init__(self, server_address, RequestHandlerClass, *,
                 config: GatewayConfig | None = None, metrics=None):
        self.RequestHandlerClass = RequestHandlerClass
        self.gwcfg = config if config is not None else GatewayConfig()
        self.gw = metrics  # GatewayMetrics (loop_* instruments) or None
        self.draining = False
        # Handler-visible attributes (GatewayHTTPServer parity; the bound
        # handler reads these off `self.server`).
        self._rate_samples: collections.deque = collections.deque(maxlen=64)
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="gw-hedge")
        self._fanout_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="gw-fanout")
        self._offload = ThreadPoolExecutor(
            max_workers=self.gwcfg.evloop_offload_workers,
            thread_name_prefix="gw-offload")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind(server_address)
            self._listener.listen(512)
            self._listener.setblocking(False)
        except BaseException:
            self._listener.close()
            self._hedge_pool.shutdown(wait=False)
            self._fanout_pool.shutdown(wait=False)
            self._offload.shutdown(wait=False)
            raise
        self.server_address = self._listener.getsockname()[:2]
        self._selector = selectors.DefaultSelector()
        # Cross-thread wakeup: worker callbacks append to the mailbox
        # (deque.append is atomic) and poke the socketpair so a sleeping
        # select returns immediately.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._mailbox: collections.deque = collections.deque()
        # Dispatches framed during a tick; submitted to the offload pool
        # just before the loop parks in select (see serve_forever).
        self._submits: list = []
        self._in_select = False
        self._conns: dict[int, _Conn] = {}
        self._streams: set[_Stream] = set()
        self._dispatched = 0
        self._shutdown_request = threading.Event()
        self._stopped = threading.Event()
        self._stopped.set()  # not serving yet: shutdown() must not block
        self._closed = False
        self._drain_done: threading.Event | None = None
        self._drain_deadline = 0.0
        self._ticks: collections.deque = collections.deque(maxlen=512)
        self._tick_count = 0
        # Stall attribution (ISSUE 18): the loop stamps this heartbeat
        # every iteration; a LoopWatchdog (attached by make_gateway when
        # telemetry.loop_stall_threshold_s > 0) converts busy age into
        # lag and convicts the blocking frame. Offload-pool accounting
        # distinguishes "pool starved" from "loop blocked".
        self.heartbeat = LoopHeartbeat()
        self.watchdog = None  # telemetry.prof.LoopWatchdog | None
        self.profiler = None  # telemetry.prof.SamplingProfiler | None
        self._pool_monitor = (
            OffloadPoolMonitor(
                self.gw.loop_offload_queue, self.gw.loop_offload_busy,
                self.gw.loop_offload_workers,
                self.gwcfg.evloop_offload_workers)
            if self.gw is not None else None)

    # ------------------------------------------------------------------
    # lifecycle (ThreadingHTTPServer-parity surface)

    def serve_forever(self, poll_interval: float = 0.5):
        """Run the event loop on the calling thread until ``shutdown()``."""
        self._stopped.clear()
        interval = min(max(poll_interval, 0.01), 0.5)
        self._selector.register(
            self._listener, selectors.EVENT_READ, ("accept", None))
        self._selector.register(
            self._wake_r, selectors.EVENT_READ, ("wake", None))
        self.heartbeat.attach()
        if self.watchdog is not None:
            self.watchdog.start()
        last_sweep = time.monotonic()
        try:
            while not self._shutdown_request.is_set():
                if self._submits:
                    # Submit LAST, right before the loop parks: on a
                    # busy box the worker can only run once this thread
                    # releases the GIL inside select — submitting any
                    # earlier in the tick just lengthens the handoff
                    # (measured ~200us p50 at 3 kept-alive clients on
                    # one core when submitted mid-tick, ~15us here).
                    submits, self._submits = self._submits, []
                    for raw, carry, conn, queued_ts in submits:
                        future = self._offload.submit(
                            self._run_handler, raw, carry, conn, queued_ts)
                        future.add_done_callback(
                            lambda f, c=conn: self._post(("handled", c, f)))
                # Heartbeat (ISSUE 18): idle while parked in select (a
                # parked loop is healthy — only BUSY age is lag), busy
                # the moment the tick starts processing. One tuple write
                # each: @hot_path-cheap, read lock-free by the watchdog.
                self.heartbeat.idle()
                self._in_select = True
                # A mailbox item that raced the end of the previous tick
                # must not wait out a parked select: skip the park.
                events = () if self._mailbox \
                    else self._selector.select(interval)
                self._in_select = False
                self.heartbeat.busy()
                t0 = time.perf_counter()
                self._tick(events)
                now = time.monotonic()
                if now - last_sweep >= 1.0:
                    last_sweep = now
                    self._sweep(now)
                if self._drain_done is not None:
                    self._check_drain(now)
                self._observe_tick(time.perf_counter() - t0, len(events))
        finally:
            # A dead loop is not a stalled loop: park the heartbeat so
            # the watchdog never convicts the exit path, then stop it.
            self.heartbeat.idle()
            if self.watchdog is not None:
                self.watchdog.stop()
            for key in (self._listener, self._wake_r):
                try:
                    self._selector.unregister(key)
                except (KeyError, ValueError):
                    pass
            self._shutdown_request.clear()
            self._stopped.set()

    def shutdown(self):
        """Stop the loop and block until it exits (``BaseServer.shutdown``
        parity). Open connections/streams are torn down by
        ``server_close``, as on the threaded path."""
        self._shutdown_request.set()
        self._wake()
        self._stopped.wait()

    def drain(self, timeout_s: float = 30.0) -> None:
        """Graceful drain: stop accepting, close idle keep-alives, let
        in-flight requests and live SSE streams finish; after
        ``timeout_s`` sever what remains — every severed stream runs its
        deferred accounting as an abort (counted ``stream_aborts``), so
        completed + aborted always equals opened: zero silent drops.
        Callable from any thread; returns when the drain settles."""
        self.draining = True
        if self._stopped.is_set():
            return  # loop not running: nothing in flight to wait on
        done = threading.Event()
        self._post(("drain", done, float(timeout_s)))
        done.wait(float(timeout_s) + 10.0)

    def server_close(self):
        """Tear down sockets and executors. Safe without ``serve_forever``
        ever having run; call ``shutdown()`` first when it has (the same
        contract ``ThreadingHTTPServer`` imposes). Live streams still
        open here run their deferred accounting inline as aborts."""
        if self._closed:
            return
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.profiler is not None:
            self.profiler.stop()
        try:
            for stream in list(self._streams):
                self._streams.discard(stream)
                try:
                    _run_stream_terminal(stream.det, ok=False, blame=False)
                except Exception:
                    logger.exception("evloop: teardown accounting failed")
            for conn in list(self._conns.values()):
                dispatched = conn.state == "dispatched"
                conn.state = "closed"
                if dispatched:
                    # A worker may still be mid-optimistic-send here;
                    # leave the fd to the socket object's finalizer
                    # rather than risk fd reuse under the send.
                    conn.defer_close = True
                    continue
                try:
                    conn.sock.close()
                except OSError:
                    pass
            self._conns.clear()
            for sock in (self._listener, self._wake_r, self._wake_w):
                try:
                    sock.close()
                except OSError:
                    pass
            self._selector.close()
        finally:
            self._offload.shutdown(wait=False, cancel_futures=True)
            self._hedge_pool.shutdown(wait=False, cancel_futures=True)
            self._fanout_pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------------
    # cross-thread mailbox

    def _post(self, item) -> None:
        """Any-thread → loop handoff: atomic append, plus a wakeup byte
        only when the loop may be parked in select. A mid-tick append
        needs no wake — the tick drains the mailbox on its way out, and
        the pre-select mailbox check in serve_forever closes the race
        between that drain and the park."""
        self._mailbox.append(item)
        if self._in_select:
            self._wake()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # wakeup already pending (buffer full) or torn down

    # ------------------------------------------------------------------
    # the loop

    @event_loop
    def _tick(self, events) -> None:
        # Chaos seam for THE stall drill: ``loop.block:delay@...`` turns
        # this into a real single-threaded loop stall (every connected
        # stream freezes) that the watchdog must convict at this line.
        maybe_inject("loop.block")
        for key, mask in events:
            kind, obj = key.data
            if kind == "client":
                if obj.state != "closed":
                    self._client_ready(obj, mask)
            elif kind == "upstream":
                if obj.conn.stream is obj:
                    self._upstream_ready(obj)
            elif kind == "accept":
                self._accept_ready()
            else:  # wake
                self._drain_wakeups()
        while True:
            try:
                item = self._mailbox.popleft()
            except IndexError:
                break
            if item[0] == "handled":
                self._on_handled(item[1], item[2])
            elif item[0] == "drain":
                self._on_drain(item[1], item[2])

    @event_loop
    def _drain_wakeups(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    @event_loop
    def _accept_ready(self) -> None:
        cap = self.gwcfg.evloop_max_connections
        for _ in range(128):
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self.draining:
                sock.close()
                continue
            if cap and len(self._conns) >= cap:
                if self.gw is not None:
                    self.gw.loop_accept_backlog_drops.inc()
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr)
            self._conns[conn.fd] = conn
            self._selector.register(
                sock, selectors.EVENT_READ, ("client", conn))
            conn.mask = selectors.EVENT_READ

    @event_loop
    def _client_ready(self, conn: _Conn, mask: int) -> None:
        if mask & selectors.EVENT_WRITE:
            self._flush_client(conn)
        if conn.state != "closed" and mask & selectors.EVENT_READ:
            self._read_client(conn)

    @event_loop
    def _read_client(self, conn: _Conn) -> None:
        for _ in range(8):
            try:
                data = conn.sock.recv(_READ_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._client_gone(conn)
                return
            if not data:
                self._client_gone(conn)
                return
            conn.last_activity = time.monotonic()
            if conn.state == "streaming":
                continue  # one-way fan-through: drop client chatter
            conn.inbuf += data
            if conn.state != "idle" and len(conn.inbuf) > _MAX_HEADER_BYTES:
                # Flooding ahead of its own response: abusive, close.
                self._client_gone(conn)
                return
            if len(data) < _READ_CHUNK:
                break
        if conn.state == "idle":
            self._maybe_dispatch(conn)

    @event_loop
    def _client_gone(self, conn: _Conn) -> None:
        """EOF or socket error from the client. A streaming conn aborts
        its relay (client-side cancel: counted, never blamed on the
        replica); a dispatched conn closes now — ``_on_handled`` finds it
        closed and routes any detach state straight to an abort."""
        stream, conn.stream = conn.stream, None
        self._close_client(conn)
        if stream is not None:
            self._streams.discard(stream)
            self._unregister_upstream(stream)
            self._finalize(stream.det, ok=False, blame=False)

    @event_loop
    def _close_client(self, conn: _Conn) -> None:
        if conn.state == "closed":
            return
        deferred = conn.state == "dispatched"
        conn.state = "closed"
        self._conns.pop(conn.fd, None)
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        if deferred:
            # A worker may be about to optimistic-send the response on
            # this fd; closing now could hand the fd number to a fresh
            # socket and misdeliver the bytes. The conn is already
            # invisible to the loop (out of _conns, unregistered) —
            # _on_handled performs the real close.
            conn.defer_close = True
            return
        try:
            conn.sock.close()
        except OSError:
            pass

    @event_loop
    def _maybe_dispatch(self, conn: _Conn) -> None:
        if conn.state != "idle":
            return
        try:
            total = _frame_request(conn.inbuf)
        except _BadRequest:
            conn.outbuf.append(memoryview(_RESP_400))
            conn.out_bytes += len(_RESP_400)
            conn.close_after = True
            conn.state = "writing"
            self._flush_client(conn)
            return
        if total is None:
            self._update_interest(conn)
            return
        raw = bytes(conn.inbuf[:total])
        carry = bytes(conn.inbuf[total:])
        conn.inbuf.clear()
        conn.state = "dispatched"
        self._dispatched += 1
        # The worker owns the socket exclusively while dispatched — it
        # may read the next pipelined/sticky request straight off the fd
        # — so the loop must stop watching it (two concurrent readers
        # would interleave frames). mask == 0 records "unregistered";
        # _update_interest re-registers on the way back. Bytes already
        # read past the framed request travel with the dispatch (carry)
        # and come back via the result's leftover.
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.mask = 0
        # Queued, not submitted: serve_forever flushes this right before
        # it parks in select so the offload worker starts the moment the
        # loop releases the GIL, not after the rest of the tick.
        self._submits.append((raw, carry, conn, time.monotonic()))

    def _run_handler(self, raw: bytes, carry: bytes, conn: _Conn,
                     queued_ts: float = 0.0):
        """Offload-pool accounting shim around :meth:`_handle_dispatch`:
        observes queue-wait (submit → worker pickup) and worker
        occupancy so "loop is fine, pool is starved" is distinguishable
        from a blocked loop (ISSUE 18)."""
        mon = self._pool_monitor
        if mon is not None:
            mon.job_started(queued_ts)
        try:
            return self._handle_dispatch(raw, carry, conn)
        finally:
            if mon is not None:
                mon.job_finished()

    def _handle_dispatch(self, raw: bytes, carry: bytes, conn: _Conn):
        """Offload worker: run the bound gateway handler against an
        in-memory request/response pair (the 'pseudo-handler' — same
        class, same ``handle_one_request``, same control plane as the
        threaded path; only the transport differs). Returns
        ``(response_bytes, close_connection, detach_state, sent,
        leftover)``.

        ``sent`` is the optimistic DIRECT send: while this connection is
        dispatched the worker owns its socket outright (the loop has
        unregistered the fd, never writes it, and defers any close to
        _on_handled), so the worker pushes the response bytes itself —
        no mailbox-wakeup loop round-trip on the latency path. The
        socket is non-blocking; whatever doesn't fit is flushed by the
        loop. ``sent == -1`` means the client vanished under the send.

        After a FULLY sent keep-alive response the worker goes sticky:
        it camps on the socket up to ``_STICK_S`` for the client's next
        request and handles it in place — request N+1 never touches the
        loop while the conversation stays hot. ``leftover`` is whatever
        trailing bytes the worker read past the last request it framed;
        they go back into the conn's inbuf."""
        handler_cls = self.RequestHandlerClass
        buf = bytearray(carry)
        while True:
            h = handler_cls.__new__(handler_cls)
            h.server = self
            h.client_address = conn.addr
            h.connection = None
            h.request = None
            h.rfile = io.BytesIO(raw)
            h.wfile = io.BytesIO()
            h.close_connection = True
            try:
                h.handle_one_request()
            except Exception:
                # Threaded parity: an exploding handler thread drops the
                # connection; here the worker survives and the loop
                # closes it.
                logger.exception("evloop: handler raised")
                return b"", True, None, 0, bytes(buf)
            det = getattr(h, "_evloop_detached", None)
            body = h.wfile.getvalue()
            sent = 0
            if body and not conn.defer_close:
                try:
                    sent = conn.sock.send(body)
                except (BlockingIOError, InterruptedError):
                    sent = 0
                except OSError:
                    sent = -1
            if (det is not None or h.close_connection or not body
                    or sent != len(body) or conn.defer_close
                    or self.draining):
                return body, h.close_connection, det, sent, bytes(buf)
            nxt = self._next_request(conn, buf)
            if nxt is None:
                return body, False, None, sent, bytes(buf)
            raw = nxt

    def _next_request(self, conn: _Conn, buf: bytearray) -> bytes | None:
        """Sticky read (offload worker, never the loop): frame the next
        request from ``buf``/the socket, waiting up to ``_STICK_S`` for
        it to arrive. ``None`` hands the connection back to the loop —
        on timeout, worker scarcity, EOF, error, or a frame the loop
        should 400 itself (bad bytes stay in ``buf`` for the loop's own
        framing to reject, so the 400-and-close path stays in one
        place)."""
        deadline = time.monotonic() + _STICK_S
        while True:
            try:
                total = _frame_request(buf)
            except _BadRequest:
                return None
            if total is not None:
                raw = bytes(buf[:total])
                del buf[:total]
                return raw
            # Scarcity guard: camping is only free while most workers
            # are idle. _dispatched is loop-owned; a stale read just
            # ends one stick early/late — never corrupts state.
            if (self.draining or conn.defer_close
                    or self._dispatched * 2 >
                    self.gwcfg.evloop_offload_workers):
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                ready, _, _ = select.select([conn.sock], [], [], remaining)
            except (OSError, ValueError):
                return None
            if not ready:
                return None
            try:
                data = conn.sock.recv(_READ_CHUNK)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                return None  # loop's re-registered READ will see the error
            if not data:
                return None  # EOF: ditto, recv()==b"" on the loop side
            buf += data

    @event_loop
    def _on_handled(self, conn: _Conn, future) -> None:
        self._dispatched -= 1
        try:
            body, close_conn, det, sent, leftover = future.result()
        except Exception:
            logger.exception("evloop: offload dispatch failed")
            body, close_conn, det, sent, leftover = b"", True, None, 0, b""
        if conn.state == "closed":
            # Client went away mid-dispatch: nothing to deliver; a
            # detached stream aborts with its accounting intact.
            if det is not None:
                self._finalize(det, ok=False, blame=False)
            if conn.defer_close:
                conn.defer_close = False
                try:
                    conn.sock.close()
                except OSError:
                    pass
            return
        if leftover:
            conn.inbuf += leftover
        if sent < 0 or not body:
            if det is not None:
                self._finalize(det, ok=False, blame=False)
            self._close_client(conn)
            return
        if sent < len(body):
            conn.outbuf.append(memoryview(body)[sent:])
            conn.out_bytes += len(body) - sent
        conn.last_activity = time.monotonic()
        if det is not None:
            self._start_stream(conn, det)
        else:
            conn.close_after = bool(close_conn) or self.draining
            conn.state = "writing"
        if conn.state != "closed":
            self._flush_client(conn)

    @event_loop
    def _start_stream(self, conn: _Conn, det: dict) -> None:
        """Take ownership of a detached SSE relay: flip the upstream
        socket non-blocking, drain any bytes http.client buffered past
        the worker's first-chunk read, then relay readiness-driven until
        upstream EOF (SSE is close-delimited)."""
        upstream = det["conn"]
        timeout_s = getattr(upstream, "timeout", None) \
            or self.gwcfg.request_timeout_s
        usock = _stream_socket(upstream, det.get("resp"))
        stream = _Stream(conn, det, usock, float(timeout_s))
        try:
            usock.setblocking(False)
        except (OSError, AttributeError):
            conn.state = "streaming"
            conn.stream = stream
            self._streams.add(stream)
            self._end_stream(stream, ok=False, blame=True)
            return
        # Residue sweep: the worker's read1(64 KiB) drains http.client's
        # 8 KiB BufferedReader, but be robust to buffering changes — pull
        # whatever is still buffered before handing the raw fd to the
        # selector. A falsy chunk here is AMBIGUOUS (on a non-blocking
        # raw, read1 returns b"" for "no data yet" as well as for EOF),
        # so never infer upstream close from it: register the raw socket
        # and let recv() == b"" — which is unambiguous — end the stream.
        fp = getattr(det.get("resp"), "fp", None)
        while fp is not None:
            try:
                chunk = fp.read1(_READ_CHUNK)
            except (BlockingIOError, ValueError, OSError):
                break
            if not chunk:
                break
            conn.outbuf.append(memoryview(chunk))
            conn.out_bytes += len(chunk)
        conn.state = "streaming"
        conn.stream = stream
        self._streams.add(stream)
        self._register_upstream(stream)
        self._update_interest(conn)

    @event_loop
    def _register_upstream(self, stream: _Stream) -> None:
        if stream.registered:
            return
        try:
            self._selector.register(
                stream.usock, selectors.EVENT_READ, ("upstream", stream))
            stream.registered = True
        except (KeyError, ValueError, OSError):
            self._end_stream(stream, ok=False, blame=True)

    @event_loop
    def _unregister_upstream(self, stream: _Stream) -> None:
        if not stream.registered:
            return
        stream.registered = False
        try:
            self._selector.unregister(stream.usock)
        except (KeyError, ValueError, OSError):
            pass

    @event_loop
    def _upstream_ready(self, stream: _Stream) -> None:
        conn = stream.conn
        for _ in range(8):
            try:
                data = stream.usock.recv(_READ_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._end_stream(stream, ok=False, blame=True)
                return
            if not data:
                stream.eof = True
                self._unregister_upstream(stream)
                if not conn.outbuf:
                    self._end_stream(stream, ok=True, blame=False)
                else:
                    self._flush_client(conn)  # finish once drained
                return
            stream.last_upstream = time.monotonic()
            conn.outbuf.append(memoryview(data))
            conn.out_bytes += len(data)
            if len(data) < _READ_CHUNK:
                break
        if conn.out_bytes > _OUTBUF_PAUSE and not stream.paused:
            # Slow client: park the upstream fd until the outbuf drains.
            stream.paused = True
            self._unregister_upstream(stream)
        self._flush_client(conn)

    @event_loop
    def _flush_client(self, conn: _Conn) -> None:
        if conn.state == "closed":
            return
        while conn.outbuf:
            buf = conn.outbuf[0]
            view = buf[conn.out_off:] if conn.out_off else buf
            try:
                sent = conn.sock.send(view)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._client_gone(conn)
                return
            conn.out_bytes -= sent
            conn.last_activity = time.monotonic()
            if conn.out_off + sent == len(buf):
                conn.outbuf.popleft()
                conn.out_off = 0
            else:
                conn.out_off += sent
                break
        if not conn.outbuf:
            self._outbuf_drained(conn)
        if conn.state != "closed":
            self._update_interest(conn)

    @event_loop
    def _outbuf_drained(self, conn: _Conn) -> None:
        if conn.state == "writing":
            if conn.close_after or self.draining:
                self._close_client(conn)
            else:
                conn.state = "idle"
                self._maybe_dispatch(conn)  # pipelined next request
        elif conn.state == "streaming":
            stream = conn.stream
            if stream is None:
                return
            if stream.eof:
                self._end_stream(stream, ok=True, blame=False)
            elif stream.paused:
                stream.paused = False
                self._register_upstream(stream)

    @event_loop
    def _end_stream(self, stream: _Stream, ok: bool, blame: bool) -> None:
        conn = stream.conn
        if conn.stream is not stream:
            return  # already ended
        conn.stream = None
        self._streams.discard(stream)
        self._unregister_upstream(stream)
        self._close_client(conn)  # SSE is close-delimited: EOF = done
        self._finalize(stream.det, ok=ok, blame=blame)

    def _finalize(self, det: dict, ok: bool, blame: bool) -> None:
        """Hand the deferred terminal accounting to a worker (it writes
        usage ledgers — not loop work); inline only if the executor is
        already torn down."""
        try:
            self._offload.submit(_run_stream_terminal, det, ok, blame)
        except RuntimeError:
            _run_stream_terminal(det, ok, blame)

    # ------------------------------------------------------------------
    # housekeeping

    @event_loop
    def _update_interest(self, conn: _Conn) -> None:
        if conn.state in ("dispatched", "closed"):
            # A dispatched conn's fd belongs to its worker (and a closed
            # one is gone): _flush_client's tail reaches here after
            # _outbuf_drained may have re-dispatched a pipelined request
            # — re-registering now would put two readers on one socket.
            return
        mask = selectors.EVENT_READ
        if conn.outbuf:
            mask |= selectors.EVENT_WRITE
        if mask == conn.mask:
            return
        prev, conn.mask = conn.mask, mask
        try:
            if prev:
                self._selector.modify(conn.sock, mask, ("client", conn))
            else:
                # mask 0 = unregistered (the dispatch window, where the
                # worker owns the fd): coming back means register anew.
                self._selector.register(conn.sock, mask, ("client", conn))
        except (KeyError, ValueError, OSError):
            self._close_client(conn)

    @event_loop
    def _sweep(self, now: float) -> None:
        """Close idle keep-alives past the idle cap (threaded parity:
        KeepAliveHandlerMixin.timeout) and abort streams whose upstream
        went silent past its per-read timeout (threaded parity: the
        pooled socket's settimeout → OSError → aborted)."""
        idle_cap = self.gwcfg.evloop_idle_timeout_s
        for conn in list(self._conns.values()):
            if (conn.state == "idle" and not conn.outbuf
                    and now - conn.last_activity > idle_cap):
                self._close_client(conn)
        for stream in list(self._streams):
            if now - stream.last_upstream > stream.timeout_s:
                self._end_stream(stream, ok=False, blame=True)

    @event_loop
    def _on_drain(self, done: threading.Event, timeout_s: float) -> None:
        self.draining = True
        self._drain_done = done
        self._drain_deadline = time.monotonic() + timeout_s
        for conn in list(self._conns.values()):
            if conn.state == "idle" and not conn.inbuf and not conn.outbuf:
                self._close_client(conn)

    @event_loop
    def _check_drain(self, now: float) -> None:
        if not self._dispatched and not self._streams \
                and not any(c.outbuf for c in self._conns.values()):
            done, self._drain_done = self._drain_done, None
            done.set()
            return
        if now < self._drain_deadline:
            return
        # Deadline: sever survivors. Streams run their deferred
        # accounting as aborts (counted stream_aborts — no silent drops);
        # dispatched requests finish on their workers and find the
        # connection closed.
        for stream in list(self._streams):
            self._end_stream(stream, ok=False, blame=False)
        for conn in list(self._conns.values()):
            self._close_client(conn)
        if not self._dispatched:
            done, self._drain_done = self._drain_done, None
            done.set()

    @event_loop
    def _observe_tick(self, duration: float, n_ready: int) -> None:
        gw = self.gw
        if gw is None:
            return
        self._ticks.append(duration)
        self._tick_count += 1
        gw.loop_tick.observe(duration)
        gw.loop_ready_queue_depth.set(float(n_ready))
        gw.loop_open_connections.set(float(len(self._conns)))
        gw.loop_open_sse_streams.set(float(len(self._streams)))
        if self._tick_count % 128 == 0:
            ordered = sorted(self._ticks)
            gw.loop_tick_p95.set(
                ordered[int(0.95 * (len(ordered) - 1))])
