"""Upstream keep-alive connection pool (ISSUE 14 tentpole).

Every upstream hop the gateway's data plane makes — relay attempts,
hedged secondaries, KV-handoff legs, health polls, and the /metrics//
incidents fan-out probes — used to pay a fresh TCP connect (plus a fresh
server-side handler thread at the replica). With N replicas health-polled
every interval and every relay connecting fresh, the steadiest traffic in
the system was needless SYN/FIN churn. This module is the fix: a
per-replica bounded pool of kept-alive ``http.client`` connections with
checkout/checkin semantics.

Contract:

- **Checkout** hands back a parked connection for ``(replica_id,
  address)`` when a healthy one exists (a *hit*), else a fresh unconnected
  ``HTTPConnection`` (a *miss* — the connect happens lazily on the first
  request, exactly like before the pool existed).
- Parked connections are vetted at checkout: wrong address (the replica
  relaunched on a new port), past the age cap, or *stale* — readable
  while idle means the peer closed it (or worse, sent unsolicited bytes);
  either way it is discarded-and-counted, never handed out. The
  stale-socket check is the standard zero-timeout ``select`` probe.
- **Checkin** parks a connection for reuse only when it is provably
  reusable: the response was fully read and the upstream did not ask to
  close (``Connection: close`` — SSE relays — or HTTP/1.0 upstreams).
  Anything else is closed and counted as a discard. The pool never holds
  more than ``max_idle_per_replica`` parked connections per replica;
  ``0`` disables pooling entirely (every checkout is a fresh connect —
  the microbench's fresh-connect A/B leg).
- A **mid-request error** is the caller's to report via :meth:`discard`:
  the connection is closed and counted, and the caller's existing retry
  path engages (full-read-before-relay already makes that
  idempotent-safe).
- **Invalidate** closes every parked connection for one replica — wired
  into supervisor relaunch, rolling restart, scale-down park, and
  quarantine, so a fleet mutation never leaves sockets parked against a
  replica the control plane just took down.

Thread-safety: the idle map is lock-protected; the hit/miss/discard
counters are GIL-cheap int adds (the telemetry-registry idiom — a racing
pair may lose one update, values never go backwards). A checked-out
connection belongs to exactly one caller until checked back in.

stdlib-only (no jax): this rides inside ``ditl_tpu/gateway`` and the
import-layering rule proves it stays that way.
"""

from __future__ import annotations

import collections
import http.client
import json
import select
import socket
import threading
import time

__all__ = ["ConnectionPool"]


class _PooledHTTPConnection(http.client.HTTPConnection):
    """HTTPConnection with TCP_NODELAY: http.client sends headers and
    body as separate small segments, and on a kept-alive connection the
    second one stalls behind the peer's delayed ACK (~40 ms on Linux)
    unless Nagle is off — the whole point of the pool is to NOT close the
    connection, so the close-time flush that hid this is gone."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1,
            )
        except OSError:
            pass


def _socket_stale(conn: http.client.HTTPConnection) -> bool:
    """True when a parked connection cannot be reused: no socket at all,
    or readable while idle (EOF from a closed peer, or protocol garbage —
    a kept-alive connection with no request in flight must be silent).
    Probes via poll(), not select(): select raises ValueError for fds >=
    FD_SETSIZE (1024), which would misjudge EVERY parked connection stale
    exactly in the high-fd-count regime the pool exists for."""
    sock = conn.sock
    if sock is None:
        return True
    try:
        if hasattr(select, "poll"):
            poller = select.poll()
            poller.register(
                sock, select.POLLIN | select.POLLERR | select.POLLHUP,
            )
            return bool(poller.poll(0))
        readable, _, _ = select.select([sock], [], [], 0)
        return bool(readable)
    except (OSError, ValueError):
        return True


class ConnectionPool:
    """Bounded per-replica keep-alive connection pool. One instance per
    :class:`~ditl_tpu.gateway.replica.Fleet`, shared by the gateway's
    relay plane and the supervisor's health polls."""

    def __init__(self, max_idle_per_replica: int = 8,
                 max_age_s: float = 30.0):
        if max_idle_per_replica < 0:
            raise ValueError(
                f"max_idle_per_replica must be >= 0, got "
                f"{max_idle_per_replica}"
            )
        if max_age_s <= 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        self.max_idle_per_replica = max_idle_per_replica
        self.max_age_s = max_age_s
        self._lock = threading.Lock()
        # replica_id -> deque of (conn, (host, port), born_monotonic),
        # newest at the right (LIFO reuse keeps the working set warm and
        # lets the tail age out).
        self._idle: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # Lifetime accounting (GIL-cheap adds; rendered as stats-mirror
        # gauges on the gateway /metrics and embedded in bench rows).
        self.hits = 0
        self.misses = 0
        self.discards = 0

    def configure(self, max_idle_per_replica: int | None = None,
                  max_age_s: float | None = None) -> None:
        """Apply config-derived caps (make_gateway wires GatewayConfig's
        pool knobs through here — the Fleet is usually built first)."""
        if max_idle_per_replica is not None:
            if max_idle_per_replica < 0:
                raise ValueError(
                    f"max_idle_per_replica must be >= 0, got "
                    f"{max_idle_per_replica}"
                )
            self.max_idle_per_replica = max_idle_per_replica
            if max_idle_per_replica == 0:
                self.close_idle()
        if max_age_s is not None:
            if max_age_s <= 0:
                raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
            self.max_age_s = max_age_s

    # -- checkout / checkin --------------------------------------------------

    def checkout(self, replica_id: str, address: tuple[str, int],
                 timeout: float) -> http.client.HTTPConnection:
        """A connection to ``address``, pooled when possible. The caller
        owns it until :meth:`checkin` or :meth:`discard`; ``timeout``
        applies to the socket either way."""
        now = time.monotonic()
        while True:
            with self._lock:
                dq = self._idle.get(replica_id)
                expired = self._expire_left_locked(dq, now) if dq else []
                entry = dq.pop() if dq else None
            for conn, _addr, _born in expired:
                self._drop(conn)
            if entry is None:
                break
            conn, addr, born = entry
            if (addr != tuple(address)
                    or now - born > self.max_age_s
                    or _socket_stale(conn)):
                self._drop(conn)
                continue
            conn.timeout = timeout
            try:
                conn.sock.settimeout(timeout)
            except OSError:
                self._drop(conn)
                continue
            self.hits += 1
            return conn
        self.misses += 1
        conn = _PooledHTTPConnection(
            address[0], address[1], timeout=timeout,
        )
        conn._ditl_born = now
        return conn

    def checkin(self, replica_id: str, conn: http.client.HTTPConnection,
                response=None) -> None:
        """Park ``conn`` for reuse — or close-and-count it when it is not
        PROVABLY reusable: the caller must hand over the completed
        ``response`` (fully read, upstream didn't say close). ``response
        is None`` means unverified protocol state — a response could still
        be in flight, and handing that socket to the next caller would
        cross-wire two requests' payloads — so it is discarded, never
        parked."""
        if conn.sock is None:
            # Never connected (a checkout whose request never fired) —
            # nothing to pool, nothing to count.
            return
        reusable = (
            response is not None
            and response.isclosed() and not response.will_close
        )
        expired: list = []
        with self._lock:
            if (self._closed or self.max_idle_per_replica <= 0
                    or not reusable):
                dq = None
            else:
                dq = self._idle.setdefault(replica_id, collections.deque())
                # Age out the OLDEST parked entries here too: LIFO reuse
                # only ever pops the newest, so without this sweep a
                # burst's tail would sit past max_age_s forever, each
                # entry pinning a handler thread at the replica.
                expired = self._expire_left_locked(dq, time.monotonic())
                if len(dq) >= self.max_idle_per_replica:
                    dq = None
            if dq is not None:
                dq.append((
                    conn, (conn.host, conn.port),
                    getattr(conn, "_ditl_born", time.monotonic()),
                ))
                conn = None
        for old, _addr, _born in expired:
            self._drop(old)
        if conn is not None:
            self._drop(conn)

    def _expire_left_locked(self, dq, now: float) -> list:
        """Pop over-age entries off the OLD end of one replica's deque;
        caller holds ``_lock`` and closes the returned connections."""
        out = []
        while dq and now - dq[0][2] > self.max_age_s:
            out.append(dq.popleft())
        return out

    def discard(self, conn: http.client.HTTPConnection) -> None:
        """Close a checked-out connection that errored mid-request (or
        whose response cannot be drained) and count the discard — the
        caller's retry path takes it from here."""
        self._drop(conn)

    def _drop(self, conn) -> None:
        self.discards += 1
        try:
            conn.close()
        except OSError:
            pass

    # -- one-shot request helpers -------------------------------------------

    def request(self, replica_id: str, address: tuple[str, int],
                method: str, path: str, *, body: bytes | None = None,
                headers: dict | None = None,
                timeout: float = 5.0) -> tuple[int, dict, bytes]:
        """One pooled request, fully read: ``(status, headers, body)``.
        Transport failures discard the connection and re-raise
        (``OSError`` / ``http.client.HTTPException``)."""
        conn = self.checkout(replica_id, address, timeout)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
        except BaseException:
            self.discard(conn)
            raise
        self.checkin(replica_id, conn, response=resp)
        return resp.status, dict(resp.getheaders()), data

    def get_json(self, replica_id: str, address: tuple[str, int],
                 path: str, timeout: float = 5.0) -> dict:
        """Pooled GET expecting a 200 JSON object; anything else raises
        ``ValueError`` (the same "absent, skip it" semantics callers had
        with ``urlopen`` raising ``HTTPError`` on non-2xx)."""
        status, _, data = self.request(
            replica_id, address, "GET", path, timeout=timeout,
        )
        if status != 200:
            raise ValueError(f"{path} answered {status}")
        return json.loads(data)

    def get_text(self, replica_id: str, address: tuple[str, int],
                 path: str, timeout: float = 5.0) -> str:
        status, _, data = self.request(
            replica_id, address, "GET", path, timeout=timeout,
        )
        if status != 200:
            raise ValueError(f"{path} answered {status}")
        return data.decode("utf-8", "replace")

    # -- lifecycle -----------------------------------------------------------

    def invalidate(self, replica_id: str) -> None:
        """Close every parked connection for one replica — the fleet
        mutation hook (relaunch / rolling restart / park / quarantine)."""
        with self._lock:
            dq = self._idle.pop(replica_id, None)
        for conn, _addr, _born in (dq or ()):
            self._drop(conn)

    def close_idle(self) -> None:
        """Close every parked connection (all replicas); the pool stays
        usable — subsequent checkouts connect fresh."""
        with self._lock:
            idle, self._idle = self._idle, {}
        for dq in idle.values():
            for conn, _addr, _born in dq:
                self._drop(conn)

    def close(self) -> None:
        """Terminal: close everything parked and refuse future checkins
        (checkouts still work — they just always connect fresh)."""
        with self._lock:
            self._closed = True
        self.close_idle()

    # -- accounting ----------------------------------------------------------

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(dq) for dq in self._idle.values())

    def hit_ratio(self) -> float | None:
        total = self.hits + self.misses
        if total == 0:
            return None
        return self.hits / total

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "discards": self.discards,
            "idle": self.idle_count(),
        }
