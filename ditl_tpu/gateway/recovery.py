"""Gateway crash recovery: fleet manifest + adoption replay (ISSUE 20).

Every plane below the gateway is crash-consistent — manifested
checkpoints, journaled bulk jobs, append-only usage ledgers, atomic
adapter publication — but the gateway process itself was the last
unprotected state: kill -9 it and the fleet roster, parked/quarantined
autoscale decisions, adapter generation map and tenant admission levels
all vanished, orphaning healthy subprocess replicas that then had to be
cold-restarted. This module makes a gateway restart a non-event:

- :class:`FleetManifest` — a crash-consistent JSON snapshot
  (``gateway-manifest.json``) rewritten atomically (tmp + ``os.replace``,
  the checkpoint/bulk idiom) on every fleet mutation: spawn, park,
  quarantine, drain, relaunch, adapter publish. It records each
  replica's pid/port/role/state, the admission plane's token-bucket
  levels (keyed on credential-safe tenant labels — raw bearers never
  leave admission.py, the ISSUE 15 discipline), and the adapter
  publication map.
- :func:`recover_fleet` — on ``--recover DIR`` the new incarnation
  **adopts** still-running subprocess replicas (pid liveness via signal
  0 AND a live /health answer on the recorded port — a recycled pid or
  a stranger on the port fails the cross-check and the replica is
  relaunched on a fresh port instead; stale state never aliases, the
  same vetting rule the connection pool applies to its sockets) and
  restores parked/quarantined flags BEFORE anything starts, so the
  supervisor keeps treating down-on-purpose replicas as down on purpose.
- :func:`replay_action_tail` — rebuilds the ActionPlanner's cooldown
  stamps (``_last_scale``, per-target remediation recency) from the
  ``action.executed`` tail of the previous incarnation's journal, so a
  recovered gateway does not immediately re-plan an action whose
  cooldown had not expired when the old gateway died.
- :func:`reconcile_adapters` — reads every routable replica's live
  ``GET /v1/adapters`` (the replicas, not the manifest, are the source
  of truth for what is actually loaded), takes the fleet view as the max
  generation per name, and converges stragglers through the existing
  re-publish path (AdapterPublisher.run is idempotent per ISSUE 16).

Everything here is stdlib-only (no jax), like the rest of ``gateway/``
— the import-layering analysis rule.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ditl_tpu.telemetry.journal import merge_journals
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "FleetManifest",
    "load_manifest",
    "manifest_path",
    "recover_fleet",
    "reconcile_adapters",
    "replay_action_tail",
]

MANIFEST_VERSION = 1
MANIFEST_FILENAME = "gateway-manifest.json"


def manifest_path(directory: str) -> str:
    return os.path.join(directory, MANIFEST_FILENAME)


def load_manifest(directory: str) -> dict | None:
    """Parse the manifest in ``directory``. Returns None when absent or
    unreadable (a torn write cannot exist — writes are atomic — so a
    parse failure means no manifest was ever completed there)."""
    try:
        with open(manifest_path(directory)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "replicas" not in data:
        return None
    return data


class FleetManifest:
    """Crash-consistent fleet state snapshot, rewritten whole on every
    mutation.

    The owner wires ``fleet`` (a gateway Fleet) and optionally
    ``admission`` (a TenantAdmission) after construction; ``record()``
    then reads both and writes one atomic JSON file. Adapter
    publications are pushed in via :meth:`note_adapter` /
    :meth:`forget_adapter` (the publisher calls them on a converged
    walk). A periodic :meth:`maybe_refresh` keeps the admission bucket
    levels from going stale between fleet mutations — bucket levels
    drain per request and journaling per request would be far too hot.
    """

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.fleet = None
        self.admission = None
        self._adapters: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._last_write = float("-inf")

    # -- adapter map (pushed by AdapterPublisher) ---------------------------

    def note_adapter(self, name: str, directory: str, owner: str = "",
                     step: int = -1) -> None:
        with self._lock:
            self._adapters[name] = {
                "dir": directory, "owner": owner, "step": step,
            }
        self.record()

    def forget_adapter(self, name: str) -> None:
        with self._lock:
            self._adapters.pop(name, None)
        self.record()

    def seed_adapters(self, adapters: dict) -> None:
        """Carry the previous incarnation's publication map forward into
        this manifest (recovery path) without triggering a write."""
        with self._lock:
            for name, rec in (adapters or {}).items():
                if isinstance(rec, dict):
                    self._adapters.setdefault(name, dict(rec))

    # -- writing ------------------------------------------------------------

    def record(self) -> None:
        """Snapshot fleet + admission + adapters and atomically replace
        the on-disk manifest. Never raises: the manifest is a recovery
        aid, and a full disk must not take down the serving path."""
        fleet = self.fleet
        if fleet is None:
            return
        try:
            replicas = fleet.manifest_snapshot()
        except Exception:  # noqa: BLE001 - recovery aid, never fatal
            logger.exception("manifest fleet snapshot failed")
            return
        admission = None
        if self.admission is not None:
            try:
                admission = self.admission.bucket_snapshot()
            except Exception:  # noqa: BLE001
                logger.exception("manifest admission snapshot failed")
        with self._lock:
            data = {
                "version": MANIFEST_VERSION,
                "gateway_pid": os.getpid(),
                "ts": time.time(),
                "replicas": replicas,
                "admission": admission,
                "adapters": dict(self._adapters),
            }
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(data, f, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError:
                logger.exception("manifest write failed: %s", self.path)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return
            self._last_write = time.monotonic()

    def maybe_refresh(self, min_interval_s: float = 2.0) -> None:
        """Periodic refresh (the supervisor loop calls this once per
        poll): rewrite at most every ``min_interval_s`` so admission
        bucket levels in the manifest are bounded-stale without turning
        every request into a disk write."""
        if time.monotonic() - self._last_write >= min_interval_s:
            self.record()


# -- recovery orchestration (the --recover path) ---------------------------


def recover_fleet(fleet, manifest: dict, *, journal=None, metrics=None,
                  probe_timeout_s: float = 2.0, log=None) -> dict:
    """Adopt still-alive replicas and restore parked/quarantined flags
    from a previous incarnation's manifest. Call BEFORE ``start_all``
    and BEFORE the supervisor starts: ``start_all`` skips replicas that
    are already alive (adopted) or down on purpose (restored flags), and
    the supervisor must never observe a half-restored roster.

    Returns a report dict: ``{"adopted": [...], "relaunched": [...],
    "parked": [...], "quarantined": [...]}``. Every decision is
    journaled (``recovery.start`` -> per-replica ``recovery.adopted`` /
    ``recovery.relaunched`` / ``recovery.restored`` ->
    ``recovery.done``) so the merged timeline reads
    ``gateway.crash -> recovery.start -> recovery.adopted x N ->
    recovery.done`` in causal order."""
    log = log or (lambda msg: logger.info("%s", msg))
    records = manifest.get("replicas") or {}
    _journal(journal, "recovery.start",
             manifest_pid=manifest.get("gateway_pid"),
             manifest_ts=manifest.get("ts"),
             replicas=sorted(records))
    if metrics is not None:
        metrics.recovery_runs.inc()
    report = {"adopted": [], "relaunched": [], "parked": [],
              "quarantined": []}
    for rid in fleet.ids:
        rec = records.get(rid)
        if not isinstance(rec, dict):
            # Unknown to the previous incarnation (fleet grew):
            # start_all launches it fresh, nothing to restore.
            continue
        if rec.get("quarantined"):
            # Down on purpose — the crash-loop breaker is NOT reversed
            # by a gateway restart (only an operator clears it). Restore
            # the flag before the supervisor can try to "heal" it. Never
            # adopt: even a live pid under a quarantined id stays
            # excluded.
            fleet.set_quarantined(rid, True)
            report["quarantined"].append(rid)
            _journal(journal, "recovery.restored", replica=rid,
                     state="quarantined")
            log(f"recovery: {rid} restored quarantined (stays excluded)")
            continue
        if rec.get("deactivated"):
            fleet.set_deactivated(rid, True)
            report["parked"].append(rid)
            _journal(journal, "recovery.restored", replica=rid,
                     state="parked")
            log(f"recovery: {rid} restored parked (stays parked)")
            continue
        why = _try_adopt(fleet, rid, rec, probe_timeout_s)
        if why is None:
            report["adopted"].append(rid)
            if metrics is not None:
                metrics.recovery_adopted.inc()
            _journal(journal, "recovery.adopted", replica=rid,
                     pid=rec.get("pid"), port=rec.get("port"))
            log(f"recovery: adopted {rid} "
                f"(pid {rec.get('pid')}, port {rec.get('port')})")
        else:
            report["relaunched"].append(rid)
            if metrics is not None:
                metrics.recovery_relaunched.inc()
            _journal(journal, "recovery.relaunched", replica=rid,
                     pid=rec.get("pid"), port=rec.get("port"), why=why)
            log(f"recovery: {rid} not adoptable ({why}); relaunching")
    _journal(journal, "recovery.done", **{k: sorted(v)
                                          for k, v in report.items()})
    return report


def _try_adopt(fleet, rid: str, rec: dict,
               probe_timeout_s: float) -> str | None:
    """Adopt one replica from its manifest record. Returns None on
    success, else the reason the record is stale. The stale-manifest
    signature is exactly this pair of checks failing:

    - pid liveness (signal 0) — the process the old gateway spawned is
      gone; and/or
    - a /health answer on the recorded port — a pid alone proves
      nothing (pids recycle), and a listener alone proves nothing (the
      port may have been rebound by a stranger). Only both together
      adopt; anything less relaunches on a FRESH port, so a stale
      record can never alias live traffic onto the wrong process — the
      same never-alias rule the connection pool applies at checkout."""
    handle = fleet.handle(rid)
    adopt = getattr(handle, "adopt", None)
    if adopt is None:
        return "handle has no adopt support"
    if not adopt(rec.get("pid"), rec.get("port")):
        return "recorded pid not alive"
    if not fleet.probe(rid, timeout=probe_timeout_s):
        # Pid exists but nothing answers /health on the recorded port:
        # recycled pid, wedged process, or rebound port. Abandon WITHOUT
        # signaling — the pid may belong to an innocent stranger.
        handle.abandon_adoption()
        return "no /health answer on recorded port"
    return None


def replay_action_tail(journal_dir: str, planner, *,
                       journal=None) -> int:
    """Rebuild the ActionPlanner's cooldown stamps from the previous
    incarnation's ``action.executed`` journal tail. Only cooldown
    recency is replayed (when did the last scale land, when was each
    target last remediated) — parked/quarantined MEMBERSHIP comes from
    the manifest, which is authoritative for state, while the journal
    is authoritative for timing. Returns the number of rows replayed."""
    replayed = 0
    for rec in merge_journals(journal_dir):
        if rec.get("event") != "action.executed":
            continue
        kind = rec.get("kind")
        if not kind:
            continue
        planner.note_replayed(str(kind), str(rec.get("target") or ""),
                              float(rec["ts"]))
        replayed += 1
    if replayed:
        _journal(journal, "recovery.actions_replayed", rows=replayed)
    return replayed


def reconcile_adapters(fleet, manifest: dict, publisher, *,
                       journal=None, timeout_s: float = 5.0) -> dict:
    """Rebuild the fleet adapter view from each routable replica's live
    ``GET /v1/adapters`` and converge stragglers via re-publish.

    The replicas — not the dead gateway's manifest — are the source of
    truth for what is actually loaded; the manifest contributes only
    the checkpoint directory/owner needed to re-run a publication. The
    fleet view per name is the MAX generation any replica reports;
    replicas missing the name or behind on generation are stragglers,
    and one idempotent ``publisher.run("publish", ...)`` walk converges
    them (ISSUE 16's crash-equivalent abort semantics make re-running
    always safe). Returns ``{name: {"generation": max_gen,
    "stragglers": [...], "republished": bool}}``."""
    known = {name: rec for name, rec in
             (manifest.get("adapters") or {}).items()
             if isinstance(rec, dict)}
    views = sorted(fleet.routable(), key=lambda v: v.id)
    per_replica: dict[str, dict[str, int]] = {}
    for view in views:
        try:
            listing = fleet.pool.get_json(
                view.id, view.address, "/v1/adapters", timeout=timeout_s)
        except (OSError, ValueError):
            continue
        if not isinstance(listing, dict):
            continue
        per_replica[view.id] = {
            str(a.get("name")): int(a.get("generation") or 0)
            for a in listing.get("adapters") or []
            if a.get("name")
        }
    names = set(known)
    for gens in per_replica.values():
        names.update(gens)
    out: dict[str, dict] = {}
    for name in sorted(names):
        fleet_gen = max((gens.get(name, 0)
                         for gens in per_replica.values()), default=0)
        stragglers = sorted(
            rid for rid, gens in per_replica.items()
            if gens.get(name, 0) < fleet_gen or name not in gens
        )
        republished = False
        rec = known.get(name)
        if stragglers and rec and rec.get("dir"):
            # The existing re-publish path: verify at the edge, walk
            # every routable replica, journal every hop. Failure is
            # non-fatal here — the operator re-runs the publication.
            try:
                status, _ = publisher.run(
                    "publish", name, rec.get("dir", ""),
                    rec.get("owner", ""))
                republished = status == 200
            except Exception:  # noqa: BLE001 - recovery must finish
                logger.exception("adapter re-publish failed: %s", name)
        out[name] = {"generation": fleet_gen, "stragglers": stragglers,
                     "republished": republished}
    if names:
        _journal(journal, "recovery.adapters",
                 fleet_view={n: out[n]["generation"] for n in out},
                 stragglers={n: out[n]["stragglers"]
                             for n in out if out[n]["stragglers"]})
    return out


def _journal(journal, event: str, **attrs) -> None:
    if journal is None:
        return
    try:
        journal.event(event, **attrs)
    except Exception:  # noqa: BLE001 - journaling never blocks recovery
        logger.exception("recovery journal write failed")
