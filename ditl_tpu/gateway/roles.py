"""Replica roles for disaggregated prefill/decode serving (ISSUE 9).

DistServe (Zhong et al., OSDI'24) and Splitwise (Patel et al., ISCA'24)
make the case that prefill-heavy and decode-heavy serving want DIFFERENT
machine configurations: prefill is a throughput problem (big per-tick
token budgets, large chunks, deep page pools), decode is a latency problem
(many concurrent slots, small budgets so no tick stalls a stream). A
homogeneous fleet forces one compromise config on both; a heterogeneous
fleet lets the router steer each request class to the replicas shaped for
it, which removes prefill/decode interference at the ROUTING layer — on
top of whatever the per-tick token budget (ISSUE 8) already bounds inside
one replica.

Three roles:

- ``hybrid`` — today's default: the base config untouched. A fleet of
  hybrids is exactly the pre-ISSUE-9 fleet.
- ``prefill_heavy`` — fewer decode slots, 4x the prefill chunk, 4x the
  token budget, 2x the page pool: a replica shaped to chew through long
  prompts (batch / best_effort work) without a latency SLO to protect.
- ``decode_heavy`` — 2x the decode slots with the TIGHTEST legal token
  budget (one full decode tick + one chunk of prefill progress): a replica
  shaped so interactive streams never absorb a long co-scheduled prefill.

Everything here is pure stdlib host code over plain numbers and
``ReplicaView`` snapshots — unit-testable without jax, importable by the
gateway (which must stay jax-free) and by bench.py/launchers alike.
"""

from __future__ import annotations

__all__ = ["ROLES", "handoff_sources", "parse_roles", "role_candidates",
           "role_knobs"]

ROLES = ("hybrid", "prefill_heavy", "decode_heavy")


def parse_roles(spec: str, n_replicas: int) -> list[str]:
    """Parse a comma-separated role spec (``"prefill_heavy,decode_heavy"``)
    into one role per replica. Shorter specs pad with ``hybrid`` (the
    un-opinionated default); longer specs are a config error, not a silent
    truncation. Empty spec = all hybrid (the homogeneous fleet)."""
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    roles = [r.strip() for r in spec.split(",") if r.strip()] if spec else []
    for r in roles:
        if r not in ROLES:
            raise ValueError(f"unknown replica role {r!r} (one of {ROLES})")
    if len(roles) > n_replicas:
        raise ValueError(
            f"{len(roles)} roles specified for {n_replicas} replica(s): "
            f"{roles}"
        )
    return roles + ["hybrid"] * (n_replicas - len(roles))


def role_knobs(
    role: str,
    *,
    n_slots: int,
    decode_chunk: int = 8,
    prefill_chunk: int = 0,
    token_budget: int = 0,
) -> dict:
    """Derive one replica's engine knobs from its role and the fleet's base
    config. Returns ``{"n_slots", "prefill_chunk", "token_budget",
    "pages_scale"}`` — concrete values for the first three (the scaling
    preserves every engine invariant: budgets cover a full decode tick,
    chunk multiples of the page size stay multiples), and a multiplier for
    whatever page-pool size the caller would otherwise use (the pool's
    default is derived from slot count, which these knobs change).

    A base of 0 for ``prefill_chunk``/``token_budget`` means "feature off"
    and stays 0 — a role must not silently arm chunking or budgeting the
    operator disabled (whole-prompt prefill IS the biggest chunk there is,
    which suits prefill_heavy fine)."""
    if role not in ROLES:
        raise ValueError(f"unknown replica role {role!r} (one of {ROLES})")
    if n_slots < 1:
        raise ValueError(f"n_slots must be >= 1, got {n_slots}")
    if role == "hybrid":
        return {"n_slots": n_slots, "prefill_chunk": prefill_chunk,
                "token_budget": token_budget, "pages_scale": 1.0}
    if role == "prefill_heavy":
        slots = max(1, n_slots // 2)
        chunk = prefill_chunk * 4
        budget = 0 if token_budget == 0 else max(
            token_budget * 4, slots * decode_chunk + max(chunk, 1)
        )
        return {"n_slots": slots, "prefill_chunk": chunk,
                "token_budget": budget, "pages_scale": 2.0}
    # decode_heavy: double the slots, keep the chunk, and shrink the budget
    # to the tightest legal value — one full decode tick plus one chunk of
    # prefill progress (the engine's at-least-one-chunk rule needs that
    # headroom; anything less would reject at construction).
    slots = n_slots * 2
    budget = 0 if token_budget == 0 else (
        slots * decode_chunk + max(prefill_chunk, 1)
    )
    return {"n_slots": slots, "prefill_chunk": prefill_chunk,
            "token_budget": budget, "pages_scale": 1.0}


def role_candidates(
    candidates,
    slo_class: str | None,
    prompt_tokens: int = 0,
    long_prompt_tokens: int = 0,
):
    """Class -> role steering over ``ReplicaView`` candidates, layered
    UNDER whatever routing policy runs next (the policy picks within the
    returned set; affinity keeps its ring semantics on the subset).

    - interactive (and unclassed — the engine's default class) requests
      avoid ``prefill_heavy`` replicas: their big budgets exist to absorb
      long prefills, exactly the interference a latency-sensitive stream
      must not sit behind;
    - batch / best_effort requests whose prompt is long (>=
      ``long_prompt_tokens`` whitespace tokens; 0 = all of them) avoid
      ``decode_heavy`` replicas: a long prefill there would stall the very
      streams the role protects;
    - a homogeneous (all-hybrid) candidate set is returned untouched, and
      an EMPTY preferred set falls back to the full candidate set — a dead
      prefill_heavy replica degrades the fleet to hybrid serving; no
      request class is ever unroutable while any replica lives.
    """
    candidates = list(candidates)
    roles = {getattr(v, "role", "hybrid") for v in candidates}
    if roles <= {"hybrid"}:
        return candidates
    if slo_class in (None, "", "interactive"):
        pref = [v for v in candidates
                if getattr(v, "role", "hybrid") != "prefill_heavy"]
    elif (slo_class in ("batch", "best_effort")
          and (long_prompt_tokens <= 0
               or prompt_tokens >= long_prompt_tokens)):
        pref = [v for v in candidates
                if getattr(v, "role", "hybrid") != "decode_heavy"]
    else:
        pref = candidates
    return pref or candidates


def handoff_sources(candidates, decode_id: str):
    """The replicas eligible to run a prefill on the DECODE replica's
    behalf for a KV handoff (ISSUE 13): live ``prefill_heavy`` views that
    serve the /internal KV endpoints (``kv_handoff`` health flag), minus
    the chosen decode replica itself. Empty means the relay leg has
    nobody to ship from — the gateway's orchestration skips the handoff
    and the decode replica prefills locally, exactly the hybrid-serving
    degradation ``role_candidates`` guarantees for routing."""
    return [
        v for v in candidates
        if getattr(v, "role", "hybrid") == "prefill_heavy"
        and getattr(v, "kv_handoff", False)
        and v.id != decode_id
    ]
