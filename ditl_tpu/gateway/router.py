"""Routing policies for the serving gateway (ISSUE 4 tentpole).

The fleet-level scheduling question is "which replica should serve this
request?", and the answer depends on what you optimize:

- ``round_robin`` — spread blindly; the baseline every comparison runs
  against.
- ``least_outstanding`` — spread by LIVE load (gateway-tracked in-flight
  count plus the replica's last-reported queue depth); the Orca/vLLM-style
  answer once replicas run continuous batching, because a replica mid-way
  through long generations is not an equal target.
- ``affinity`` — consistent hashing over a request's affinity key (an
  explicit ``session_id``, else the prompt's leading tokens) so same-prefix
  and same-session traffic lands on the SAME replica, whose
  ``PageAllocator.match_prefix`` (infer/paged_cache.py) then reuses the
  prefix KV automatically — the SGLang/RadixAttention observation that
  prefix-cache hit rate is a *routing* property at fleet scale. Saturated
  home replicas spill with a MEASURED bias (ISSUE 9): among the unsaturated
  peers, prefer the one whose windowed prefix-cache hit ratio
  (``ReplicaView.recent_cache_hit_ratio``, fed by /health-poll deltas) says
  it is actively reusing prefixes — it most plausibly still holds this one;
  when every peer's ratio is absent or stale the spill falls back to the
  deterministic ring walk (correctness first, locality second). Consistent
  hashing confines the remap blast radius of a dead replica to that
  replica's own keys.

Role/class steering (disaggregated prefill/decode fleets, ISSUE 9) is a
candidate-set restriction layered UNDER these policies — see
``gateway/roles.role_candidates``; every policy then picks within the
role-filtered subset, so affinity keeps its ring semantics per role.

Policies are pure host code over ``ReplicaView`` snapshots (replica.py);
no jax, no I/O — unit-testable with plain namedtuples. ``pick`` accepts
the request's SLO class and a prompt-size estimate so policies MAY
specialize; the built-ins ignore them (steering already happened in the
candidate set).
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import threading

__all__ = ["CacheAffinityPolicy", "LeastOutstandingPolicy",
           "RoundRobinPolicy", "affinity_key", "make_policy",
           "prompt_token_estimate", "stable_hash"]

POLICIES = ("round_robin", "least_outstanding", "affinity")


def stable_hash(s: str) -> int:
    """Process-independent 64-bit hash (Python's ``hash`` is salted per
    process; a routing ring must agree across gateway restarts)."""
    return int.from_bytes(
        hashlib.sha1(s.encode("utf-8", "surrogatepass")).digest()[:8], "big"
    )


def _payload_text(payload: dict) -> str:
    """The routable text of a request body (prompt or concatenated chat
    messages) — shared by the affinity key and the prompt-size estimate."""
    if isinstance(payload.get("messages"), list):
        return "\x1e".join(
            str(m.get("content", "")) for m in payload["messages"]
            if isinstance(m, dict)
        )
    prompt = payload.get("prompt")
    if isinstance(prompt, list):
        prompt = prompt[0] if prompt else ""
    return prompt if isinstance(prompt, str) else ""


def affinity_key(payload: dict, prefix_tokens: int) -> str | None:
    """The request's routing key: an explicit ``session_id`` (or OpenAI
    ``user``) wins; otherwise the first ``prefix_tokens`` whitespace tokens
    of the prompt/conversation. Whitespace tokens, not model tokens — the
    gateway has no tokenizer, and any stable prefix function partitions
    same-prefix traffic identically. None = no key (sampled spread)."""
    sid = payload.get("session_id") or payload.get("user")
    if sid:
        return f"sid:{sid}"
    toks = _payload_text(payload).split()
    if not toks:
        return None
    return "pfx:" + " ".join(toks[:max(1, prefix_tokens)])


def prompt_token_estimate(payload: dict) -> int:
    """Whitespace-token count of the request's prompt text — the gateway's
    tokenizer-free prompt-size signal, consumed by the long-prompt
    steering rule (``gateway/roles.role_candidates``). Same caveat as the
    affinity key: not model tokens, but any monotone estimate separates
    long batch prompts from short interactive turns identically."""
    return len(_payload_text(payload).split())


def _load(view) -> tuple:
    """Comparable load: gateway-observed in-flight + replica-reported queue
    depth, tie-broken by id for determinism."""
    return (view.outstanding + view.queue_depth, view.id)


class RoundRobinPolicy:
    name = "round_robin"

    def __init__(self):
        self._counter = itertools.count()

    def pick(self, key, candidates, slo_class=None, prompt_tokens=0,
             info=None):
        ordered = sorted(candidates, key=lambda v: v.id)
        return ordered[next(self._counter) % len(ordered)]


class LeastOutstandingPolicy:
    name = "least_outstanding"

    def pick(self, key, candidates, slo_class=None, prompt_tokens=0,
             info=None):
        return min(candidates, key=_load)


class CacheAffinityPolicy:
    """Consistent-hash ring with ``vnodes`` virtual nodes per replica.
    The ring is built from the CANDIDATE set (live, non-draining replicas)
    and cached by membership, so a dead replica remaps only its own keys
    while every other key keeps its home — the property that preserves the
    fleet's accumulated prefix caches through churn."""

    name = "affinity"

    def __init__(self, vnodes: int = 64):
        self.vnodes = vnodes
        self._rings: dict[frozenset, tuple[list[int], list[str]]] = {}
        self._lock = threading.Lock()
        self._fallback = LeastOutstandingPolicy()

    def _ring(self, ids: frozenset) -> tuple[list[int], list[str]]:
        with self._lock:
            ring = self._rings.get(ids)
            if ring is None:
                points = sorted(
                    (stable_hash(f"{rid}#{v}"), rid)
                    for rid in ids for v in range(self.vnodes)
                )
                ring = ([h for h, _ in points], [r for _, r in points])
                # Membership churn is tiny (fleet size); keep the cache from
                # growing without bound across many generations anyway.
                if len(self._rings) > 64:
                    self._rings.clear()
                self._rings[ids] = ring
        return ring

    def home(self, key: str, candidates) -> object:
        """The key's home replica on the current ring (ignoring load)."""
        by_id = {v.id: v for v in candidates}
        hashes, rids = self._ring(frozenset(by_id))
        i = bisect.bisect_left(hashes, stable_hash(key)) % len(rids)
        return by_id[rids[i]]

    def pick(self, key, candidates, slo_class=None, prompt_tokens=0,
             info=None):
        """Pick a replica for ``key``. When the caller passes ``info`` (a
        dict), ``info["spill"]`` is set to whether the pick landed away
        from the key's home — the gateway's per-role spill counters read
        it here instead of re-walking the ring."""
        if info is not None:
            info["spill"] = False
        if key is None:
            return self._fallback.pick(key, candidates)
        by_id = {v.id: v for v in candidates}
        hashes, rids = self._ring(frozenset(by_id))
        start = bisect.bisect_left(hashes, stable_hash(key))
        # Walk the ring from the key's position. The first DISTINCT rid is
        # the key's home: unsaturated, it wins immediately (the common
        # fast path — no full-ring walk). A saturated home costs the rest
        # of the walk, collecting the unsaturated peers in walk order —
        # the deterministic spill ranking, so the same key spills to a
        # consistent secondary.
        seen: set[str] = set()
        home_rid: str | None = None
        walk: list = []
        for j in range(len(rids)):
            rid = rids[(start + j) % len(rids)]
            if rid in seen:
                continue
            seen.add(rid)
            view = by_id[rid]
            unsaturated = (view.outstanding + view.queue_depth
                           < max(1, view.capacity))
            if home_rid is None:
                home_rid = rid
                if unsaturated:
                    return view  # home takes it
            if unsaturated:
                walk.append(view)
        if info is not None:
            info["spill"] = True  # home saturated: every path below spills
        if not walk:
            return self._fallback.pick(key, candidates)
        # Spill (ISSUE 9): the home is saturated, so locality is already
        # lost — steer the spill by MEASURED reuse instead of ring position
        # alone. A peer whose windowed hit ratio (health-poll hit/miss
        # token deltas, replica.py) is > 0 is demonstrably reusing prefixes
        # right now — the best available evidence it still holds this one.
        # Absent/stale ratios (no recent tokens -> None) keep the
        # deterministic ring-walk target; ties break toward walk order
        # (max() keeps the first maximal element).
        rated = [v for v in walk
                 if (getattr(v, "recent_cache_hit_ratio", None) or 0) > 0]
        if rated:
            return max(rated,
                       key=lambda v: round(v.recent_cache_hit_ratio, 4))
        return walk[0]


def make_policy(name: str):
    if name == "round_robin":
        return RoundRobinPolicy()
    if name == "least_outstanding":
        return LeastOutstandingPolicy()
    if name == "affinity":
        return CacheAffinityPolicy()
    raise ValueError(f"unknown router policy {name!r} (one of {POLICIES})")
