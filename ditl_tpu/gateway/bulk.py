"""Offline bulk-inference lane (ISSUE 19 tentpole): a crash-consistent
bulk job manager soaking spare decode capacity at zero interactive SLO
burn.

"Millions of users" is not only interactive chat — it is overnight
embedding jobs, eval sweeps, and synthetic-data generation. Every
primitive this lane needs already exists and is test-pinned: the
``best_effort`` SLO class (ISSUE 8) guarantees the interactive stall
bound (batch/interactive preempt bulk token-by-token at the engine), the
actuation plane (ISSUE 12) can treat bulk demand as a scale-up signal,
and per-tenant usage ledgers (ISSUE 15) make bulk work billable. This
module adds the missing piece: a journaled job manager behind the
gateway's ``/v1/bulk/jobs`` endpoints that decomposes a job into
per-prompt work items and dispatches them through the existing relay
path pinned to ``best_effort``.

Crash consistency is the design center, the checkpoint-resume story
applied to serving:

- **Spec before ack**: a job's prompts are written to
  ``bulk-items-<id>.jsonl`` and its spec/state to ``bulk-job-<id>.json``
  (atomic tmp+rename) BEFORE the submit response — an acknowledged job
  is always resumable.
- **One ``bulk.item`` journal row per terminal outcome**: line-buffered
  through telemetry/journal.py (segment-rotated like spans/usage), each
  row carries the full result, so it is on disk before the results file
  or any counter moves.
- **Ordered results with a contiguous-prefix flush**:
  ``bulk-results-<id>.jsonl`` only ever holds items ``0..k`` in order;
  out-of-order completions wait in memory (bounded by the in-flight
  window) until the gap fills. The journal row is the durable record for
  the waiters, so a SIGKILL between journal and flush loses nothing.
- **Resume = results prefix ∪ journal rows**: a restarted manager
  re-dispatches only items with NO terminal journal row — at most the
  in-flight window is re-dispatched, and no item is ever billed twice
  (usage rows are written with the terminal journal row, which is
  written exactly once per item). Drilled with a real SIGKILL via the
  ``bulk.dispatch`` chaos site.

Like everything in gateway/, this module is stdlib-only and jax-free on
import. The relay dependency is INJECTED (``bind(dispatch=...)``) so the
manager is unit-testable against a fake fleet and reusable from bench.

CLI over the on-disk state (no live gateway needed)::

    python -m ditl_tpu.gateway.bulk --dir D --list
    python -m ditl_tpu.gateway.bulk --dir D --show JOB_ID
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re
import threading
import time
import uuid
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

from ditl_tpu.chaos import InjectedFault, maybe_inject
from ditl_tpu.config import BulkConfig
from ditl_tpu.gateway.admission import sanitize_label
from ditl_tpu.telemetry.flight import BULK_RING
from ditl_tpu.telemetry.journal import EventJournal, read_journal
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "BulkJobManager",
    "JOB_STATES",
    "bulk_journal_path",
    "load_jobs",
    "main",
]

PREFIX = "ditl_bulk"

# Journal schema stamp (the usage-ledger discipline): readers of an old
# journal know which row vocabulary produced it.
BULK_SCHEMA = 1

JOB_STATES = ("queued", "running", "completed", "cancelled", "failed")

# Dispatch outcomes that merit another attempt: fleet saturation and
# replica death/timeout are transient by definition (the idempotent-safe
# relay already retried WITHIN one attempt; this is the slower outer
# loop), and "error" covers transport faults incl. injected chaos.
RETRYABLE_OUTCOMES = ("429", "503", "504", "error")


def bulk_journal_path(directory: str, source: str = "gateway") -> str:
    """``bulk-<source>.jsonl`` — deliberately OUTSIDE the ``events-*``
    glob merge_journals consumes (the usage-ledger naming lesson): item
    rows carry full result payloads and would swamp a merged timeline."""
    return os.path.join(directory, f"bulk-{source}.jsonl")


def _job_path(directory: str, job_id: str) -> str:
    return os.path.join(directory, f"bulk-job-{job_id}.json")


def _items_path(directory: str, job_id: str) -> str:
    return os.path.join(directory, f"bulk-items-{job_id}.jsonl")


def _results_path(directory: str, job_id: str) -> str:
    return os.path.join(directory, f"bulk-results-{job_id}.jsonl")


_JOB_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


class BulkMetrics:
    """The ``ditl_bulk_*`` families (telemetry/catalog.py registers them;
    all optional — they exist only on a bulk-armed gateway). Registered
    lazily on the gateway's own registry so /metrics carries the lane
    next to the interactive families."""

    def __init__(self, registry):
        r = registry
        self.jobs_submitted = r.counter(
            f"{PREFIX}_jobs_submitted", "bulk jobs accepted at submit")
        self.jobs_completed = r.counter(
            f"{PREFIX}_jobs_completed", "bulk jobs that ran to completion")
        self.jobs_cancelled = r.counter(
            f"{PREFIX}_jobs_cancelled", "bulk jobs cancelled by a client")
        self.jobs_failed = r.counter(
            f"{PREFIX}_jobs_failed",
            "bulk jobs terminal with at least one permanently failed item")
        self.jobs_resumed = r.counter(
            f"{PREFIX}_jobs_resumed",
            "incomplete bulk jobs resumed from the journal after a "
            "gateway restart")
        self.items_dispatched = r.counter(
            f"{PREFIX}_items_dispatched",
            "bulk work items dispatched through the relay path "
            "(attempts, so retries count again)")
        self.items_completed = r.counter(
            f"{PREFIX}_items_completed",
            "bulk work items that reached a terminal journal row")
        self.items_retried = r.counter(
            f"{PREFIX}_items_retried",
            "bulk dispatch attempts retried after a transient outcome")
        self.items_preempted = r.counter(
            f"{PREFIX}_items_preempted",
            "bulk dispatch attempts bounced by fleet saturation (429) — "
            "the lane yielding to interactive load, working as designed")
        self.items_failed = r.counter(
            f"{PREFIX}_items_failed",
            "bulk work items terminally failed after exhausting retries")
        self.backlog = r.gauge(
            f"{PREFIX}_backlog_items",
            "bulk work items not yet terminal across non-terminal jobs "
            "(the autoscale planner's scale-up signal)")
        self.jobs_active = r.gauge(
            f"{PREFIX}_jobs_active", "bulk jobs currently queued or running")
        self.completion_tokens = r.counter(
            f"{PREFIX}_completion_tokens",
            "completion tokens generated by the bulk lane")
        self.tokens_per_s = r.gauge(
            f"{PREFIX}_tokens_per_s",
            "recent bulk-lane completion tokens/sec (windowed over the "
            "manager's rate samples; 0 when the lane is idle)")


class _Job:
    """In-memory state of one job; the durable truth lives in the job
    file + journal. All mutable fields are guarded by ``lock``."""

    def __init__(self, job_id: str, tenant: str, params: dict,
                 n_items: int, state: str = "queued",
                 created_ts: float | None = None):
        self.id = job_id
        self.tenant = tenant  # credential-safe label, never the bearer
        self.params = params
        self.n_items = n_items
        self.state = state
        self.created_ts = time.time() if created_ts is None else created_ts
        self.lock = threading.Lock()
        self.cancel_requested = False
        # Contiguous-prefix flush state (guarded-by: lock).
        self.flushed = 0  # items 0..flushed-1 are in the results file
        self.pending: dict[int, dict] = {}  # journaled, awaiting the gap
        self.done: set[int] = set()  # terminal (journaled) item idxs
        self.n_failed = 0
        self.n_retried = 0
        self.n_dispatched = 0
        self.thread: threading.Thread | None = None

    def counters(self) -> dict:
        with self.lock:
            return {
                "n_items": self.n_items,
                "n_done": len(self.done),
                "n_flushed": self.flushed,
                "n_failed": self.n_failed,
                "n_retried": self.n_retried,
                "n_dispatched": self.n_dispatched,
            }


class BulkJobManager:
    """The journaled bulk job manager. Construction wires the durable
    state (directory + journal); :meth:`bind` wires the live gateway
    pieces (the relay dispatch closure, the idle-fleet probe); and
    :meth:`start` resumes incomplete jobs and begins dispatching.

    ``dispatch(item) -> dict`` is the injected relay: it receives one
    work-item dict (``job``, ``idx``, ``rid``, ``prompt``, ``tenant``,
    ``adapter``, ``max_new``, ``sampling``) and returns ``{"outcome":
    "200"|"429"|"503"|"504"|"error", "text": ..., "completion_tokens":
    N, "retry_after_s": S}``. The gateway builds it over
    ``_route_and_relay`` pinned to ``best_effort`` with a STABLE
    per-item request id (``bulk-<job>-<idx>``) so replica-death retries
    ride the existing idempotent-safe relay."""

    def __init__(self, directory: str, config=None, *, journal=None,
                 registry=None, flight=None, plane=None, usage=None,
                 admission=None, source: str = "gateway",
                 max_bytes: int | None = None):
        if not directory:
            raise ValueError("bulk manager needs a directory")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.config = config if config is not None else BulkConfig()
        self.journal = journal if journal is not None else EventJournal(
            bulk_journal_path(directory, source), source=f"bulk-{source}",
            max_bytes=max_bytes,
        )
        self.metrics = BulkMetrics(registry) if registry is not None else None
        self.flight = flight
        self.plane = plane
        self.usage = usage
        self.admission = admission
        self._dispatch = None
        self._idle_fn = None
        self._jobs: dict[str, _Job] = {}
        self._jobs_lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        # (wall_time, cumulative items completed): the best_effort
        # Retry-After derivation (telemetry/serving.backlog_retry_after)
        # reads this exactly like the gateway reads _rate_samples.
        self.rate_samples: collections.deque = collections.deque(maxlen=64)
        # (wall_time, cumulative completion tokens): the lane tokens/sec
        # gauge's window.
        self._token_samples: collections.deque = collections.deque(maxlen=64)
        self._items_completed = 0
        self._tokens_total = 0
        self._progress_lock = threading.Lock()
        self._last_progress = time.time()
        self._stall_fired_at = 0.0

    # -- wiring --------------------------------------------------------------

    def bind(self, dispatch, idle_fn=None) -> "BulkJobManager":
        """Attach the live relay closure (and optionally a zero-arg
        ``idle_fn`` reporting "the fleet has idle decode capacity" — the
        backlog-stall detector's second input)."""
        self._dispatch = dispatch
        if idle_fn is not None:
            self._idle_fn = idle_fn
        return self

    def start(self) -> int:
        """Resume every incomplete job found on disk, then accept new
        submissions. Returns the number of jobs resumed. Idempotent."""
        if self._started:
            return 0
        self._started = True
        resumed = 0
        for rec in load_jobs(self.directory):
            if rec.get("state") not in ("queued", "running"):
                continue
            job = self._rebuild_job(rec)
            if job is None:
                continue
            with self._jobs_lock:
                self._jobs[job.id] = job
            if self.admission is not None:
                # Quota state is in-memory and died with the old gateway:
                # re-register resumed work so NEW submissions see it —
                # resumed jobs themselves are already-accepted work and
                # must not be re-admitted against their own footprint.
                self.admission.reacquire_bulk(
                    job.tenant, job.n_items - len(job.done))
            if self.metrics is not None:
                self.metrics.jobs_resumed.inc()
            self.journal.event("bulk.job", schema=BULK_SCHEMA, job=job.id,
                               state="resumed",
                               tenant=sanitize_label(job.tenant),
                               n_items=job.n_items, n_done=len(job.done))
            self._launch(job)
            resumed += 1
        self._refresh_gauges()
        return resumed

    def _rebuild_job(self, rec: dict) -> _Job | None:
        """Resume state = results-file contiguous prefix ∪ journal
        ``bulk.item`` rows. The results file persists everything already
        flushed (rotation-proof); the journal covers the tail that was
        journaled but not yet flushed when the process died — bounded by
        the in-flight window, so segment rotation cannot out-age it."""
        job_id = rec.get("id") or ""
        if not _JOB_ID_RE.match(job_id):
            return None
        job = _Job(job_id, str(rec.get("tenant") or "anonymous"),
                   dict(rec.get("params") or {}),
                   int(rec.get("n_items") or 0), state="running",
                   created_ts=rec.get("created_ts"))
        job.n_failed = int(rec.get("n_failed") or 0)
        # 1) the flushed prefix (count whole lines; a torn tail line is
        #    simply re-flushed from its journal row).
        flushed_rows = _read_jsonl(_results_path(self.directory, job_id))
        job.flushed = 0
        for row in flushed_rows:
            if row.get("idx") == job.flushed:
                job.done.add(job.flushed)
                job.flushed += 1
            else:
                break
        # 2) journaled terminal rows beyond the prefix (this journal plus
        #    its rotated segments — EventJournal resumes the segment
        #    counter, so globbing the stem finds them all).
        for jrec in self._journal_rows():
            if jrec.get("event") != "bulk.item" or jrec.get("job") != job_id:
                continue
            idx = jrec.get("idx")
            if not isinstance(idx, int) or idx in job.done:
                continue
            job.done.add(idx)
            job.pending[idx] = {
                k: jrec[k] for k in
                ("idx", "status", "text", "completion_tokens", "attempts")
                if k in jrec
            }
            if jrec.get("status") != "ok":
                job.n_failed += 1
        self._flush_locked_job(job)
        return job

    def _journal_rows(self) -> list[dict]:
        stem, ext = os.path.splitext(self.journal.path)
        paths = sorted(glob.glob(f"{stem}.r[0-9][0-9][0-9][0-9]{ext}"))
        paths.append(self.journal.path)
        rows: list[dict] = []
        for p in paths:
            rows.extend(read_journal(p))
        return rows

    # -- submission ----------------------------------------------------------

    def submit(self, tenant: str, prompts: list[str],
               params: dict | None = None) -> dict:
        """Accept one job: persist spec+items (durable BEFORE the ack),
        journal it, and start dispatching. ``tenant`` is the
        credential-safe label. Raises ValueError on a bad spec — the
        handler maps that to a 400."""
        cfg = self.config
        if not prompts:
            raise ValueError("bulk job needs at least one prompt")
        if len(prompts) > cfg.max_items_per_job:
            raise ValueError(
                f"bulk job holds {len(prompts)} items; cap is "
                f"{cfg.max_items_per_job} (bulk.max_items_per_job)")
        if not all(isinstance(p, str) and p for p in prompts):
            raise ValueError("every bulk item needs a non-empty prompt")
        params = dict(params or {})
        sampling = params.get("sampling")
        if sampling is not None and not isinstance(sampling, dict):
            raise ValueError("sampling must be a JSON object")
        max_new = params.get("max_new", cfg.default_max_new)
        if not isinstance(max_new, int) or max_new <= 0:
            raise ValueError("max_new must be a positive integer")
        job_id = f"bj-{uuid.uuid4().hex[:12]}"
        job = _Job(job_id, tenant, {
            "adapter": str(params.get("adapter") or ""),
            "max_new": int(max_new),
            "sampling": dict(sampling or {}),
        }, len(prompts))
        # Items first, then the job file: a job file without its items
        # would resume as an empty job; items without a job file are an
        # orphan sweep-up, never a wrong answer.
        with open(_items_path(self.directory, job_id), "w") as f:
            for idx, prompt in enumerate(prompts):
                f.write(json.dumps({"idx": idx, "prompt": prompt}) + "\n")
        self._save_job(job)
        self.journal.event("bulk.job", schema=BULK_SCHEMA, job=job_id,
                           state="queued", tenant=sanitize_label(tenant),
                           n_items=job.n_items)
        with self._jobs_lock:
            self._jobs[job_id] = job
        if self.metrics is not None:
            self.metrics.jobs_submitted.inc()
        if self._started:
            self._launch(job)
        self._refresh_gauges()
        return self.status(job_id)

    def _save_job(self, job: _Job) -> None:
        """Atomic spec+state snapshot (the checkpoint-commit idiom):
        readers (resume, the CLI) never observe a torn job file."""
        path = _job_path(self.directory, job.id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with job.lock:
            rec = {
                "schema": BULK_SCHEMA,
                "id": job.id,
                "tenant": job.tenant,
                "state": job.state,
                "params": job.params,
                "n_items": job.n_items,
                "n_done": len(job.done),
                "n_failed": job.n_failed,
                "created_ts": job.created_ts,
            }
        with open(tmp, "w") as f:
            json.dump(rec, f, sort_keys=True)
        os.replace(tmp, path)

    # -- queries -------------------------------------------------------------

    def get(self, job_id: str) -> _Job | None:
        with self._jobs_lock:
            return self._jobs.get(job_id)

    def status(self, job_id: str) -> dict | None:
        job = self.get(job_id)
        if job is None:
            # Terminal jobs of past incarnations still answer from disk.
            for rec in load_jobs(self.directory):
                if rec.get("id") == job_id:
                    return {**rec, "results":
                            _results_path(self.directory, job_id)}
            return None
        with job.lock:
            state = job.state
        return {
            "id": job.id,
            "tenant": job.tenant,
            "state": state,
            "params": job.params,
            "created_ts": job.created_ts,
            **job.counters(),
            "results": _results_path(self.directory, job.id),
        }

    def jobs(self) -> list[dict]:
        with self._jobs_lock:
            ids = list(self._jobs)
        out = [self.status(i) for i in ids]
        seen = {o["id"] for o in out if o}
        for rec in load_jobs(self.directory):
            if rec.get("id") not in seen:
                out.append(rec)
        return sorted([o for o in out if o],
                      key=lambda r: r.get("created_ts") or 0.0)

    def results_path(self, job_id: str) -> str:
        return _results_path(self.directory, job_id)

    def backlog(self) -> int:
        """Work items not yet terminal across non-terminal jobs — the
        autoscale scale-up signal and the best_effort Retry-After input."""
        total = 0
        with self._jobs_lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            with job.lock:
                if job.state in ("queued", "running"):
                    total += job.n_items - len(job.done)
        return total

    def active_jobs(self) -> int:
        with self._jobs_lock:
            jobs = list(self._jobs.values())
        n = 0
        for job in jobs:
            with job.lock:
                n += job.state in ("queued", "running")
        return n

    def tokens_per_s(self) -> float:
        """Windowed lane token rate over the recent samples (the
        backlog_retry_after estimator shape)."""
        now = time.time()
        recent = [(t, c) for t, c in tuple(self._token_samples)
                  if now - t <= 60.0]
        if len(recent) >= 2:
            (t0, c0), (t1, c1) = recent[0], recent[-1]
            if t1 - t0 >= 0.5 and c1 > c0:
                return (c1 - c0) / (t1 - t0)
        return 0.0

    def tokens_total(self) -> int:
        """Cumulative lane completion tokens this incarnation — bench
        snapshots it around the timed region to grade the soak rate."""
        with self._progress_lock:
            return self._tokens_total

    def cancel(self, job_id: str) -> bool:
        job = self.get(job_id)
        if job is None:
            return False
        with job.lock:
            if job.state not in ("queued", "running"):
                return True  # idempotent: cancelling a terminal job is a no-op
            job.cancel_requested = True
        return True

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until no job is queued/running (tests, bench). Returns
        False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.active_jobs() == 0:
                return True
            time.sleep(0.02)
        return self.active_jobs() == 0

    # -- the dispatch loop ---------------------------------------------------

    def _launch(self, job: _Job) -> None:
        if self._dispatch is None:
            raise RuntimeError(
                "bulk manager is not bound to a dispatch path; call "
                "bind(dispatch=...) before start()/submit()")
        t = threading.Thread(target=self._run_job, args=(job,),
                             name=f"bulk-job-{job.id}", daemon=True)
        job.thread = t
        with job.lock:
            job.state = "running"
        self._save_job(job)
        t.start()

    def _load_prompts(self, job: _Job) -> dict[int, str]:
        prompts: dict[int, str] = {}
        for row in _read_jsonl(_items_path(self.directory, job.id)):
            idx = row.get("idx")
            if isinstance(idx, int) and isinstance(row.get("prompt"), str):
                prompts[idx] = row["prompt"]
        return prompts

    def _run_job(self, job: _Job) -> None:
        """One job's dispatch loop: a bounded in-flight window of relay
        workers, a contiguous-prefix results flush, and the stall
        detector riding the wait loop."""
        window = max(1, self.config.max_in_flight)
        prompts = self._load_prompts(job)
        with job.lock:
            todo = [i for i in range(job.n_items)
                    if i not in job.done and i in prompts]
            missing = [i for i in range(job.n_items)
                       if i not in job.done and i not in prompts]
        # Items whose spec line tore (death mid-submit cannot reach here —
        # submit acks only after the items file is fully written — but a
        # hand-edited or truncated file must fail loudly, not hang).
        for idx in missing:
            self._finish_item(job, idx, {"idx": idx, "status": "error",
                                         "text": "", "completion_tokens": 0,
                                         "attempts": 0})
        try:
            with ThreadPoolExecutor(
                    max_workers=window,
                    thread_name_prefix=f"bulk-{job.id}") as pool:
                futures = set()
                it = iter(todo)
                while True:
                    if self._stop.is_set():
                        return  # manager closing; the job resumes next start
                    cancelled = False
                    with job.lock:
                        cancelled = job.cancel_requested
                    if not cancelled:
                        for idx in it:
                            futures.add(pool.submit(
                                self._run_item, job, idx, prompts[idx]))
                            if len(futures) >= window:
                                break
                    if not futures:
                        break
                    done, futures = wait(futures,
                                         timeout=self.config.poll_interval_s,
                                         return_when=FIRST_COMPLETED)
                    for f in done:
                        exc = f.exception()
                        if exc is not None:
                            logger.exception("bulk: item worker died",
                                             exc_info=exc)
                    self._maybe_stall()
                    if cancelled:
                        # Stop issuing; in-flight items finish (their
                        # journal rows keep resume exact), queued todo is
                        # abandoned.
                        if not futures:
                            break
        finally:
            self._finalize_job(job)

    def _run_item(self, job: _Job, idx: int, prompt: str) -> None:
        """Dispatch one work item to a terminal outcome, retrying
        transient failures. The chaos seam sits BEFORE each attempt —
        ``bulk.dispatch:kill`` is the mid-job gateway death the resume
        drill injects; ``error`` rides the ordinary retry path."""
        cfg = self.config
        m = self.metrics
        attempts = 0
        result = {"outcome": "error", "text": "", "completion_tokens": 0}
        while True:
            attempts += 1
            # Journaled pre-attempt (line-buffered: on disk before the
            # dispatch, so a kill mid-attempt leaves the re-dispatch
            # countable — the resume drill's evidence).
            self.journal.event("bulk.dispatch", schema=BULK_SCHEMA,
                               job=job.id, idx=idx, attempt=attempts)
            try:
                maybe_inject("bulk.dispatch", request=idx + 1)
                result = self._dispatch({
                    "job": job.id,
                    "idx": idx,
                    "rid": f"bulk-{job.id}-{idx}",
                    "prompt": prompt,
                    "tenant": job.tenant,
                    "adapter": job.params.get("adapter") or "",
                    "max_new": int(job.params.get("max_new") or
                                   cfg.default_max_new),
                    "sampling": dict(job.params.get("sampling") or {}),
                }) or {"outcome": "error"}
            except InjectedFault:
                result = {"outcome": "error", "text": "",
                          "completion_tokens": 0}
            except Exception:  # noqa: BLE001 - a dispatch bug fails the item
                logger.exception("bulk: dispatch raised (job %s item %d)",
                                 job.id, idx)
                result = {"outcome": "error", "text": "",
                          "completion_tokens": 0}
            outcome = str(result.get("outcome") or "error")
            if m is not None:
                m.items_dispatched.inc()
            if self.flight is not None:
                # One ROUTING-style ring row per dispatch decision: the
                # black box shows which items the lane pushed, and what
                # the fleet said.
                self.flight.ring(BULK_RING).record(
                    job=job.id, idx=idx, attempt=attempts, outcome=outcome,
                    tenant=job.tenant,
                )
            if outcome == "200":
                self._finish_item(job, idx, {
                    "idx": idx, "status": "ok",
                    "text": str(result.get("text") or ""),
                    "completion_tokens":
                        int(result.get("completion_tokens") or 0),
                    "attempts": attempts,
                })
                return
            stopping = self._stop.is_set()
            with job.lock:
                stopping = stopping or job.cancel_requested
            if (outcome not in RETRYABLE_OUTCOMES
                    or attempts > max(1, cfg.retry_limit) or stopping):
                if stopping and outcome in RETRYABLE_OUTCOMES:
                    # Mid-shutdown/cancel: leave the item incomplete (no
                    # terminal row) rather than branding it failed — a
                    # resume re-dispatches it.
                    return
                self._finish_item(job, idx, {
                    "idx": idx, "status": "error", "text": "",
                    "completion_tokens": 0, "attempts": attempts,
                })
                return
            if m is not None:
                m.items_retried.inc()
                if outcome == "429":
                    m.items_preempted.inc()
            retry_after = result.get("retry_after_s")
            backoff = (float(retry_after) if isinstance(
                retry_after, (int, float)) and retry_after > 0
                else min(2.0, 0.05 * attempts))
            # Interruptible sleep: cancel/close must not wait out a backoff.
            if self._stop.wait(min(backoff, 5.0)):
                return

    def _finish_item(self, job: _Job, idx: int, row: dict) -> None:
        """One item's terminal path, in durability order: journal row
        first (the crash-consistent record), then the usage row, then the
        in-memory flush + counters. Exactly once per (job, idx) per
        process — and the resume scan skips journaled idxs, so exactly
        once across incarnations too."""
        self.journal.event("bulk.item", schema=BULK_SCHEMA, job=job.id,
                           **row)
        if self.usage is not None:
            # bulk_job attribution (ISSUE 15 coupling): the aggregator
            # bills bulk separately from interactive — rollups preserve
            # unknown fields, so the row stays filterable downstream.
            self.usage.record(
                tenant=job.tenant,
                outcome="200" if row["status"] == "ok" else "503",
                slo_class="best_effort",
                bulk_job=job.id,
                item=idx,
                completion_tokens=int(row.get("completion_tokens") or 0),
            )
        failed = row["status"] != "ok"
        with job.lock:
            if idx in job.done:
                return
            job.done.add(idx)
            job.pending[idx] = row
            job.n_dispatched += 1
            job.n_retried += max(0, int(row.get("attempts") or 1) - 1)
            if failed:
                job.n_failed += 1
        self._flush_locked_job(job)
        m = self.metrics
        if m is not None:
            m.items_completed.inc()
            if failed:
                m.items_failed.inc()
            m.completion_tokens.inc(int(row.get("completion_tokens") or 0))
        with self._progress_lock:
            self._last_progress = time.time()
            self._items_completed += 1
            self._tokens_total += int(row.get("completion_tokens") or 0)
            self.rate_samples.append((time.time(), self._items_completed))
            self._token_samples.append((time.time(), self._tokens_total))
        self._refresh_gauges()

    def _flush_locked_job(self, job: _Job) -> None:
        """Contiguous-prefix flush: append every pending row whose idx
        extends the flushed prefix — the results file is gap-free and
        order-stable BY CONSTRUCTION, resumable by byte range."""
        with job.lock:
            if job.flushed in job.pending:
                # Line-buffered append, the journal's durability posture.
                with open(_results_path(self.directory, job.id), "a",
                          buffering=1) as f:
                    while job.flushed in job.pending:
                        row = job.pending.pop(job.flushed)
                        f.write(json.dumps(row, sort_keys=True) + "\n")
                        job.flushed += 1

    def _finalize_job(self, job: _Job) -> None:
        if self._stop.is_set():
            return  # manager close: job stays "running" on disk -> resumes
        with job.lock:
            if job.cancel_requested and len(job.done) < job.n_items:
                job.state = "cancelled"
            elif job.n_failed:
                job.state = "failed"
            else:
                job.state = "completed"
            state = job.state
        self._save_job(job)
        self.journal.event("bulk.job", schema=BULK_SCHEMA, job=job.id,
                           state=state, tenant=sanitize_label(job.tenant),
                           n_items=job.n_items, n_done=len(job.done),
                           n_failed=job.n_failed)
        if self.admission is not None:
            self.admission.release_bulk(job.tenant, job.n_items)
        m = self.metrics
        if m is not None:
            {"completed": m.jobs_completed, "cancelled": m.jobs_cancelled,
             "failed": m.jobs_failed}[state].inc()
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        m = self.metrics
        if m is not None:
            m.backlog.set(self.backlog())
            m.jobs_active.set(self.active_jobs())
            m.tokens_per_s.set(round(self.tokens_per_s(), 3))

    # -- the backlog-stall detector ------------------------------------------

    def _maybe_stall(self) -> None:
        """backlog deep AND not draining AND replicas idle = the lane is
        wedged (dead dispatch path, mis-pinned class, quota livelock) —
        exactly one incident bundle via the anomaly plane's fingerprint
        cooldown, chaos-attributed like every bundle."""
        if self.plane is None or self._idle_fn is None:
            return
        cfg = self.config
        now = time.time()
        with self._progress_lock:
            stalled_s = now - self._last_progress
        if stalled_s < cfg.stall_after_s:
            return
        if now - self._stall_fired_at < cfg.stall_after_s:
            return  # local rate-limit under the plane's own cooldown
        backlog = self.backlog()
        if backlog <= 0:
            return
        try:
            idle = bool(self._idle_fn())
        except Exception:  # noqa: BLE001 - a broken probe reads busy
            idle = False
        if not idle:
            return  # busy replicas = the lane is yielding, not stuck
        self._stall_fired_at = now
        from ditl_tpu.telemetry.anomaly import Anomaly

        self.plane.trigger(Anomaly(
            kind="bulk.backlog_stall",
            severity="critical",
            detail={
                "fingerprint_key": "bulk",
                "backlog_items": backlog,
                "stalled_s": round(stalled_s, 3),
                "jobs_active": self.active_jobs(),
                "replicas_idle": True,
            },
        ))

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop dispatching and persist. In-flight items are abandoned
        without terminal rows (resume re-dispatches them); jobs stay
        ``running`` on disk, which is what makes them resumable."""
        self._stop.set()
        with self._jobs_lock:
            jobs = list(self._jobs.values())
        deadline = time.monotonic() + timeout_s
        for job in jobs:
            t = job.thread
            if t is not None and t.is_alive():
                t.join(timeout=max(0.1, deadline - time.monotonic()))
            self._save_job(job)
        self.journal.close()


# -- on-disk readers (shared by resume, status, and the CLI) -----------------


def _read_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line: skipped, never fatal
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def load_jobs(directory: str) -> list[dict]:
    """Every readable job file in ``directory`` (torn/partial files are
    skipped — the atomic save means those cannot exist short of disk
    corruption), sorted by creation time."""
    out: list[dict] = []
    for path in glob.glob(os.path.join(directory, "bulk-job-*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("id"):
            out.append(rec)
    return sorted(out, key=lambda r: r.get("created_ts") or 0.0)


def main(argv: list[str] | None = None) -> int:
    """``python -m ditl_tpu.gateway.bulk --dir D [--list|--show ID]`` —
    the journal/job-file reader for operators (no live gateway needed;
    troubleshooting §37 walks the stuck-job signatures)."""
    import argparse

    parser = argparse.ArgumentParser(prog="python -m ditl_tpu.gateway.bulk")
    parser.add_argument("--dir", required=True,
                        help="the bulk lane's state directory (bulk.dir)")
    parser.add_argument("--list", action="store_true",
                        help="one line per job: id, state, progress")
    parser.add_argument("--show", default="", metavar="ID",
                        help="full detail for one job: spec, counters, "
                        "last dispatch/terminal journal rows")
    args = parser.parse_args(argv)
    jobs = load_jobs(args.dir)
    if args.show:
        rec = next((j for j in jobs if j["id"] == args.show), None)
        if rec is None:
            print(f"no job {args.show!r} in {args.dir}")
            return 1
        results = _read_jsonl(_results_path(args.dir, args.show))
        rows: list[dict] = []
        stem, ext = os.path.splitext(
            bulk_journal_path(args.dir, "gateway"))
        for p in sorted(glob.glob(f"{stem}*{ext}")):
            rows.extend(r for r in read_journal(p)
                        if r.get("job") == args.show)
        print(json.dumps({
            **rec,
            "results_flushed": len(results),
            "journal_dispatches": sum(
                1 for r in rows if r["event"] == "bulk.dispatch"),
            "journal_terminal": sum(
                1 for r in rows if r["event"] == "bulk.item"),
            "journal_tail": rows[-10:],
        }, indent=2, sort_keys=True))
        return 0
    # --list (the default)
    if not jobs:
        print(f"no bulk jobs in {args.dir}")
        return 0
    for rec in jobs:
        n = rec.get("n_items") or 0
        done = rec.get("n_done") or 0
        print(f"{rec['id']}  {rec.get('state', '?'):9s}  "
              f"{done}/{n} items  tenant={rec.get('tenant', '?')}  "
              f"failed={rec.get('n_failed', 0)}")
    return 0


if __name__ == "__main__":
    import sys

    from ditl_tpu.utils.logging import setup_logging

    setup_logging()
    sys.exit(main())
