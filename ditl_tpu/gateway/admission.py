"""Per-tenant admission control for the serving gateway (ISSUE 4).

The single-replica server already protects the *device* (queue caps,
``max_pending``), but nothing protects tenants from EACH OTHER: one client
hammering the fleet starves everyone equally. This module is the fairness
layer the gateway applies before any routing happens:

- **Token-bucket rate limits** per tenant (requests/second with a burst
  allowance) — the classic leaky-bucket shape every API gateway speaks, so
  ``Retry-After`` can be computed exactly (time until the bucket holds a
  token again) instead of guessed.
- **Concurrency caps** per tenant — even a tenant within its rate can't
  occupy the whole fleet's slots with long generations.

Tenants are keyed on the request's API key (``Authorization: Bearer <key>``
— the gateway extracts it; requests without one share the ``anonymous``
tenant). Like everything in telemetry/, this is host-only stdlib code: no
jax, no locks on any device path.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import re
import threading
import time
from typing import Container

__all__ = ["AdmissionDecision", "SLO_CLASS_NAMES", "TenantAdmission",
           "TokenBucket", "sanitize_label", "tenant_label"]


def sanitize_label(s: str) -> str:
    """Metric-name-safe tenant/replica label. API keys may hold arbitrary
    bytes (and are secrets): keep only word characters and cap the length so
    a tenant id can ride in a Prometheus metric NAME without breaking the
    exposition — callers should pass tenant *names*, not live credentials,
    when secrecy matters (docs/troubleshooting.md §22)."""
    out = re.sub(r"[^A-Za-z0-9_]", "_", s or "")[:48]
    return out or "anonymous"


def tenant_label(tenant: str, known: Container[str] = ()) -> str:
    """Exposition-safe tenant identifier. Tenants are keyed on the raw
    Bearer token, which is usually a live credential — and /metrics and
    /stats are unauthenticated, so the raw value must never reach them.
    Explicitly configured tenant names (``TenantAdmission.per_tenant``
    keys) and the ``anonymous`` tenant are operator-chosen public
    identifiers and stay readable; EVERY other token is reduced to a short
    stable digest (``t_<sha256[:12]>`` — enough to correlate a tenant
    across scrapes without revealing the key; docs/troubleshooting.md §22
    shows how to map a digest back to a key you hold)."""
    if tenant == "anonymous" or tenant in known:
        return sanitize_label(tenant)
    digest = hashlib.sha256(
        tenant.encode("utf-8", "surrogatepass")
    ).hexdigest()[:12]
    return f"t_{digest}"


class TokenBucket:
    """Thread-safe token bucket: ``rate`` tokens/second refill up to
    ``burst`` capacity. ``try_take`` returns 0.0 on success or the seconds
    until the requested tokens will be available (the Retry-After)."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate

    def level(self) -> float:
        """Current token level, refilled to now — what an admission
        snapshot persists (ISSUE 20). Read-only: takes nothing."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            return self._tokens

    def restore(self, tokens: float, age_s: float = 0.0) -> None:
        """Re-warm the bucket from a persisted level (ISSUE 20 restart
        amnesty fix). ``age_s`` is how long ago the level was snapshotted
        on the WALL clock — monotonic clocks do not survive a process
        restart, so the refill earned while the gateway was down is
        credited explicitly, then clamped to burst as usual."""
        with self._lock:
            self._tokens = max(0.0, min(
                self.burst, float(tokens) + max(0.0, age_s) * self.rate
            ))
            self._t_last = time.monotonic()


# Mirror of infer/continuous.SLO_CLASSES — duplicated (not imported) so the
# gateway package stays provably jax-free on import; pinned equal by test.
SLO_CLASS_NAMES = ("interactive", "batch", "best_effort")


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    ok: bool
    retry_after_s: float = 0.0
    reason: str = ""
    # SLO class this tenant is pinned to ("" = no pin): the gateway stamps
    # it as X-SLO-Class on every relay, which OVERRIDES the payload at the
    # replica — a tenant cannot escape its pin by claiming interactive in
    # the request body (ISSUE 8).
    slo_class: str = ""
    # Adapter this tenant is pinned to ("" = no pin; ISSUE 16): stamped as
    # X-Adapter-Name on every relay, which OVERRIDES the payload's model
    # field at the replica — the tenant's traffic serves through its own
    # fine-tune regardless of what the request claims. Names are LIVE
    # registry state, so no static validation here: a pin naming an
    # evicted/unknown adapter 404s at the replica with a reason
    # (reject-don't-drop, never a silent fall-through to base).
    adapter: str = ""


@dataclasses.dataclass
class _TenantState:
    bucket: TokenBucket | None
    max_concurrent: int
    slo_class: str = ""
    adapter: str = ""
    active: int = 0
    admitted: int = 0
    throttled: int = 0
    # Bulk-lane quota state (ISSUE 19): concurrently queued/running bulk
    # jobs and their not-yet-terminal items, checked by acquire_bulk at
    # submit and returned by the manager when a job reaches a terminal
    # state. Limits resolve per tenant like every other knob here.
    bulk_max_jobs: int = 0
    bulk_max_items: int = 0
    bulk_jobs: int = 0
    bulk_items: int = 0
    bulk_throttled: int = 0


class TenantAdmission:
    """Admission policy over tenants. ``rate``/``burst``/``max_concurrent``
    are the defaults applied to every tenant (0 = unlimited); ``per_tenant``
    maps a tenant key to overrides, e.g. ``{"free-tier": {"rate": 1,
    "burst": 2, "max_concurrent": 2}}``.

    ``acquire`` is paired with ``release`` (the concurrency count); callers
    MUST release exactly once per successful acquire (the gateway does so in
    a ``finally``)."""

    def __init__(
        self,
        rate: float = 0.0,
        burst: float = 0.0,
        max_concurrent: int = 0,
        per_tenant: dict[str, dict] | None = None,
        max_tenants: int = 4096,
        slo_class: str = "",
        bulk_max_jobs: int = 0,
        bulk_max_queued_items: int = 0,
    ):
        self.default_rate = float(rate)
        self.default_burst = float(burst) if burst else max(1.0, float(rate))
        self.default_max_concurrent = int(max_concurrent)
        # Bulk-lane defaults (0 = unlimited); per_tenant "bulk_max_jobs" /
        # "bulk_max_queued_items" overrides win, same resolution as rate.
        self.default_bulk_max_jobs = int(bulk_max_jobs)
        self.default_bulk_max_items = int(bulk_max_queued_items)
        self.per_tenant = dict(per_tenant or {})
        self.max_tenants = int(max_tenants)
        # Default SLO-class pin for every tenant ("" = none); a per-tenant
        # "slo_class" override wins. Validated here (reject-don't-drop): a
        # typo'd class would otherwise 400 every request of that tenant at
        # the replica.
        self.default_slo_class = slo_class
        for name, cls in [("slo_class", slo_class)] + [
            (f"per_tenant[{t!r}].slo_class", cfg.get("slo_class", ""))
            for t, cfg in self.per_tenant.items()
        ]:
            if cls and cls not in SLO_CLASS_NAMES:
                raise ValueError(
                    f"{name}: unknown SLO class {cls!r} "
                    f"(one of {SLO_CLASS_NAMES})"
                )
        self._tenants: collections.OrderedDict[str, _TenantState] = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        # Restart re-warm state (ISSUE 20): label -> persisted bucket
        # level from the previous incarnation's manifest. None = not a
        # recovery (fresh buckets start full, no amnesty accounting).
        # Keyed on tenant_label digests, NEVER raw bearers — the manifest
        # is a world-readable file.
        self._rewarm: dict[str, dict] | None = None
        self._on_amnesty = None

    def rewarm(self, levels: dict | None, on_amnesty=None) -> None:
        """Arm restart re-warming (ISSUE 20): tenants seen after this
        call get their token bucket restored from ``levels`` (a
        :meth:`bucket_snapshot` read back from the manifest, keyed on
        tenant labels) instead of restarting full. A recovering tenant
        with NO persisted level falls back to a full bucket — the old
        amnesty behavior — but now counted via ``on_amnesty`` (the
        ``ditl_gateway_admission_amnesty_total`` hook), so silent
        rate-limit resets are visible. Pass ``levels=None``/empty on a
        manifest without an admission section: every rate-limited
        tenant then counts one amnesty."""
        with self._lock:
            self._rewarm = {
                str(label): rec for label, rec in (levels or {}).items()
                if isinstance(rec, dict)
            }
            self._on_amnesty = on_amnesty

    def _maybe_rewarm(self, tenant: str, st: _TenantState) -> None:
        """Restore a just-created tenant's bucket level from the armed
        re-warm map. Caller holds the lock. Tenants without a bucket
        (rate unlimited) have no level to restore and never count
        amnesty."""
        if self._rewarm is None or st.bucket is None:
            return
        rec = self._rewarm.pop(tenant_label(tenant, self.per_tenant), None)
        if rec is None:
            if self._on_amnesty is not None:
                try:
                    self._on_amnesty()
                except Exception:  # noqa: BLE001 - accounting only
                    pass
            return
        try:
            tokens = float(rec.get("tokens", st.bucket.burst))
            age_s = max(0.0, time.time() - float(rec.get("ts", 0.0)))
        except (TypeError, ValueError):
            return
        st.bucket.restore(tokens, age_s=age_s)

    def bucket_snapshot(self) -> dict:
        """Per-tenant token-bucket levels for the crash-recovery
        manifest (ISSUE 20), keyed on :func:`tenant_label` — raw API
        keys never leave this module. ``ts`` is the WALL clock (the only
        clock that survives a restart); the restore side credits the
        downtime refill from it."""
        now = time.time()
        with self._lock:
            return {
                tenant_label(t, self.per_tenant): {
                    "tokens": round(st.bucket.level(), 6),
                    "ts": now,
                }
                for t, st in self._tenants.items()
                if st.bucket is not None
            }

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            cfg = self.per_tenant.get(tenant, {})
            rate = float(cfg.get("rate", self.default_rate))
            burst = float(cfg.get("burst", 0.0)) or (
                self.default_burst if rate == self.default_rate
                else max(1.0, rate)
            )
            st = _TenantState(
                bucket=TokenBucket(rate, burst) if rate > 0 else None,
                max_concurrent=int(
                    cfg.get("max_concurrent", self.default_max_concurrent)
                ),
                slo_class=str(
                    cfg.get("slo_class", self.default_slo_class) or ""
                ),
                adapter=str(cfg.get("adapter", "") or ""),
                bulk_max_jobs=int(
                    cfg.get("bulk_max_jobs", self.default_bulk_max_jobs)
                ),
                bulk_max_items=int(
                    cfg.get("bulk_max_queued_items",
                            self.default_bulk_max_items)
                ),
            )
            self._tenants[tenant] = st
            # Restart re-warm (ISSUE 20): first sight of a tenant after
            # --recover restores its persisted bucket level (or counts
            # an amnesty when none survived).
            self._maybe_rewarm(tenant, st)
            # Tenants arrive as arbitrary unauthenticated bearer tokens:
            # without a cap, a client cycling random keys grows this map
            # (and the per-tenant metric families downstream) without
            # bound. Evict least-recently-seen INACTIVE tenants only —
            # an evicted-and-returning tenant just gets a fresh bucket
            # (strictly more permissive, never less fair).
            if len(self._tenants) > self.max_tenants:
                for key in list(self._tenants):
                    if len(self._tenants) <= self.max_tenants:
                        break
                    other = self._tenants[key]
                    # "Inactive" includes the bulk lane: evicting a tenant
                    # with live bulk jobs would forget its quota footprint.
                    if (key != tenant and other.active == 0
                            and other.bulk_jobs == 0):
                        del self._tenants[key]
        else:
            self._tenants.move_to_end(tenant)
        return st

    def acquire(self, tenant: str) -> AdmissionDecision:
        # Denials carry the tenant's SLO-class pin too: the gateway's
        # per-class 429 counters must attribute a throttled request to the
        # class it WOULD have been scheduled under (pin wins), the same
        # attribution its routed/relayed/saturated counters use.
        with self._lock:
            st = self._state(tenant)
            if st.max_concurrent > 0 and st.active >= st.max_concurrent:
                st.throttled += 1
                return AdmissionDecision(
                    False, retry_after_s=1.0,
                    reason=f"tenant concurrency cap ({st.max_concurrent}) "
                           "reached",
                    slo_class=st.slo_class,
                    adapter=st.adapter,
                )
            if st.bucket is not None:
                wait = st.bucket.try_take(1.0)
                if wait > 0:
                    st.throttled += 1
                    return AdmissionDecision(
                        False, retry_after_s=wait,
                        reason="tenant rate limit exceeded",
                        slo_class=st.slo_class,
                        adapter=st.adapter,
                    )
            st.active += 1
            st.admitted += 1
            return AdmissionDecision(True, slo_class=st.slo_class,
                                     adapter=st.adapter)

    def release(self, tenant: str) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None and st.active > 0:
                st.active -= 1

    def acquire_bulk(self, tenant: str, n_items: int) -> AdmissionDecision:
        """Admit one bulk job of ``n_items`` work items against the
        tenant's bulk quotas (ISSUE 19). Distinct from :meth:`acquire` on
        purpose: a bulk submit is one control-plane request carrying hours
        of decode work — it is gated on standing footprint (jobs, queued
        items), not on the interactive token bucket. Denials carry typed
        reasons so the gateway's 429 body says WHICH quota tripped. Paired
        with :meth:`release_bulk` when the job reaches a terminal state."""
        with self._lock:
            st = self._state(tenant)
            if st.bulk_max_jobs > 0 and st.bulk_jobs >= st.bulk_max_jobs:
                st.bulk_throttled += 1
                return AdmissionDecision(
                    False, retry_after_s=1.0,
                    reason=f"tenant bulk job quota ({st.bulk_max_jobs} "
                           "concurrent jobs) reached",
                    slo_class=st.slo_class, adapter=st.adapter,
                )
            if (st.bulk_max_items > 0
                    and st.bulk_items + n_items > st.bulk_max_items):
                st.bulk_throttled += 1
                return AdmissionDecision(
                    False, retry_after_s=1.0,
                    reason=f"tenant bulk item quota ({st.bulk_max_items} "
                           f"queued items) would be exceeded by "
                           f"{n_items} more",
                    slo_class=st.slo_class, adapter=st.adapter,
                )
            st.bulk_jobs += 1
            st.bulk_items += n_items
            return AdmissionDecision(True, slo_class=st.slo_class,
                                     adapter=st.adapter)

    def reacquire_bulk(self, tenant: str, n_items: int) -> None:
        """Re-register an ALREADY-ADMITTED job's footprint after a gateway
        restart (quota state is in-memory and died with the old process).
        Unconditional: resumed work was accepted by a past incarnation and
        must not bounce off its own quota — only NEW submissions contend."""
        with self._lock:
            st = self._state(tenant)
            st.bulk_jobs += 1
            st.bulk_items += max(0, int(n_items))

    def release_bulk(self, tenant: str, n_items: int) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None and st.bulk_jobs > 0:
                st.bulk_jobs -= 1
                st.bulk_items = max(0, st.bulk_items - max(0, int(n_items)))

    def snapshot(self) -> dict:
        """Per-tenant counters for /stats and the per-tenant metric names
        (keys reduced via :func:`tenant_label` — raw API keys never leave
        this module)."""
        with self._lock:
            return {
                tenant_label(t, self.per_tenant): {
                    "active": st.active,
                    "admitted": st.admitted,
                    "throttled": st.throttled,
                    "bulk_jobs": st.bulk_jobs,
                    "bulk_items": st.bulk_items,
                    "bulk_throttled": st.bulk_throttled,
                }
                for t, st in self._tenants.items()
            }
