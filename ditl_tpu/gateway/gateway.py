"""The serving gateway front door (ISSUE 4 tentpole): one OpenAI-compatible
HTTP endpoint over N engine replicas.

``infer/server.py`` is one listener over one engine; this module is the
layer above it that production serving actually needs — horizontal
scale-out (a fleet of replicas behind one URL), failover (idempotent
requests retry on surviving replicas when one dies mid-request),
cache-aware routing (router.py's consistent-hash affinity policy feeds
same-prefix/same-session traffic to the replica that already holds the
prefix KV), and tenant isolation (admission.py's per-tenant token buckets
and concurrency caps, applied before any routing).

Surface:

- ``POST /v1/completions``, ``/v1/chat/completions`` — routed + proxied,
  including SSE streaming pass-through (chunks relay as they arrive).
- ``POST /v1/embeddings``, ``/tokenize``, ``/detokenize`` — routed+proxied.
- ``GET /v1/models`` — proxied from a live replica.
- ``GET /health``, ``/stats`` — fleet state; ``GET /metrics`` — the
  gateway's own Prometheus exposition (per-replica routed/retried/hedged
  counts, affinity hit-rate, per-tenant throttles, fleet gauges).
- ``429`` with a backlog-aware ``Retry-After`` when the WHOLE fleet is
  saturated (every replica answered 429) or a tenant is over budget.

The gateway is stdlib-only (no jax import anywhere in ditl_tpu/gateway):
it must be runnable as a thin front process and unit-testable against stub
replicas. Wire-up lives in ``launch.py gateway`` (subprocess replicas) and
``bench.py --serve-replicas`` (in-process fleet benchmark).
"""

from __future__ import annotations

import collections
import http.client
import json
import math
import re
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import (
    FIRST_COMPLETED,
    ThreadPoolExecutor,
    TimeoutError as FutureTimeoutError,
    wait,
)
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ditl_tpu.chaos import InjectedFault, maybe_inject
from ditl_tpu.config import GatewayConfig
from ditl_tpu.gateway.admission import (
    SLO_CLASS_NAMES, TenantAdmission, sanitize_label, tenant_label,
)
from ditl_tpu.gateway.replica import Fleet, FleetSupervisor
from ditl_tpu.gateway.roles import handoff_sources, role_candidates
from ditl_tpu.gateway.router import (
    affinity_key, make_policy, prompt_token_estimate,
)
from ditl_tpu.telemetry.flight import ROUTING_RING
from ditl_tpu.telemetry.registry import LATENCY_BUCKETS_S, MetricsRegistry
from ditl_tpu.telemetry.serving import backlog_retry_after
from ditl_tpu.telemetry.slo import BurnRateMonitor, gateway_slo
from ditl_tpu.telemetry.tracing import (
    NULL_TRACER,
    Tracer,
    format_traceparent,
    parse_traceparent,
    resolve_request_id,
)
from ditl_tpu.utils.http11 import KeepAliveHandlerMixin
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["GatewayMetrics", "make_gateway", "main"]

PREFIX = "ditl_gateway"

# Loop ticks are sub-millisecond when healthy; the serving-latency
# buckets (5ms floor) would put every healthy tick in the first bucket
# and hide a 10x regression. A tick in the right tail means something
# blocked the loop (troubleshooting §35).
LOOP_TICK_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)


class _HedgeQueueTimeout(OSError):
    """A relay attempt expired in the hedge executor's queue before its
    upstream open could start — a GATEWAY-local backlog, not a replica
    failure. _relay_one retries it like any connection error but must NOT
    note_failure the replica: a request storm saturating the executor
    would otherwise bump healthy replicas past the supervisor's
    fail_threshold and restart them, amplifying the overload exactly when
    the gateway is the bottleneck."""


class GatewayMetrics:
    """Gateway-side telemetry bundle (telemetry/registry.py instruments;
    rendered by the gateway's /metrics). Per-replica and per-tenant
    counters are created lazily with the id sanitized into the metric NAME
    (the registry has no label support; each replica/tenant becomes its own
    family, which the classic text format is fine with)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._tenant_labels: set[str] = set()
        r = self.registry
        self.requests = r.counter(
            f"{PREFIX}_requests", "requests received by the gateway")
        self.completed = r.counter(
            f"{PREFIX}_requests_completed", "requests relayed to completion")
        self.retries = r.counter(
            f"{PREFIX}_retries",
            "proxy attempts retried on another replica (replica death/busy)")
        self.hedges = r.counter(
            f"{PREFIX}_hedges", "hedged duplicate requests fired")
        self.throttled = r.counter(
            f"{PREFIX}_throttled", "requests rejected by tenant admission")
        self.saturated = r.counter(
            f"{PREFIX}_fleet_saturated",
            "requests 429'd because every replica was saturated")
        self.no_replica = r.counter(
            f"{PREFIX}_no_replica", "requests failed with no live replica")
        self.stream_aborts = r.counter(
            f"{PREFIX}_stream_aborts",
            "streams cut mid-flight by a dying replica (not retryable)")
        self.replica_deaths = r.counter(
            f"{PREFIX}_replica_deaths",
            "replica died->drain->relaunch cycles the supervisor ran "
            "(the anomaly plane's death-rate input, ISSUE 10)")
        # Crash recovery (ISSUE 20): the --recover path's outcome
        # accounting. adopted + relaunched partition the non-parked,
        # non-quarantined roster of each recovery pass; a nonzero
        # relaunched count on a drill that expected pure adoption is the
        # stale-manifest signature (troubleshooting §38).
        self.recovery_runs = r.counter(
            f"{PREFIX}_recovery_runs",
            "gateway crash-recovery passes run (--recover startups that "
            "found a fleet manifest)")
        self.recovery_adopted = r.counter(
            f"{PREFIX}_recovery_adopted",
            "still-alive replica processes adopted by a recovering "
            "gateway (pid liveness + /health cross-check both passed; "
            "zero restarts paid)")
        self.recovery_relaunched = r.counter(
            f"{PREFIX}_recovery_relaunched",
            "manifest replicas a recovering gateway had to relaunch "
            "fresh (dead pid, recycled pid, or no /health answer on the "
            "recorded port)")
        # Restart amnesty accounting (ISSUE 20 satellite): tenants whose
        # token bucket restarted FULL because no persisted level covered
        # them — the pre-recovery behavior, now visible instead of a
        # silent rate-limit reset on every gateway bounce.
        self.admission_amnesty = r.counter(
            f"{PREFIX}_admission_amnesty",
            "rate-limited tenants whose token bucket restarted full "
            "after --recover because the manifest held no admission "
            "snapshot for them")
        self.affinity_hits = r.counter(
            f"{PREFIX}_affinity_hits",
            "requests routed to the same replica as the previous request "
            "with the same affinity key")
        self.affinity_misses = r.counter(
            f"{PREFIX}_affinity_misses",
            "requests whose affinity key landed on a different replica "
            "than last time")
        self.e2e = r.histogram(
            f"{PREFIX}_request_e2e_seconds",
            "gateway receive -> response relayed", LATENCY_BUCKETS_S)
        self.replicas_live = r.gauge(
            f"{PREFIX}_replicas_live", "replicas currently routable")
        self.replicas_draining = r.gauge(
            f"{PREFIX}_replicas_draining", "replicas currently draining")
        # Actuation plane (ISSUE 12): pool accounting next to liveness —
        # active = launched minus parked/quarantined (a crashed-but-
        # recovering replica is still active), so "why are only 2 of my 4
        # replicas serving" is answerable from one scrape.
        self.replicas_active = r.gauge(
            f"{PREFIX}_replicas_active",
            "replicas participating in serving (not parked by a "
            "scale-down, not quarantined)")
        self.replicas_quarantined = r.gauge(
            f"{PREFIX}_replicas_quarantined",
            "replicas quarantined by death-storm remediation")
        # KV handoff orchestration (ISSUE 13): one counter per cost-model
        # outcome so the "handoff-fallback storm" signature is scrapable
        # (troubleshooting §30). attempted = eligible requests the model
        # evaluated; shipped / declined are its two branches; fallback =
        # an accepted handoff whose leg failed (the request still serves
        # via plain relay + re-prefill — zero client-visible failures).
        self.handoff_attempted = r.counter(
            f"{PREFIX}_handoff_attempted",
            "requests evaluated by the KV-handoff transfer-cost model")
        self.handoff_shipped = r.counter(
            f"{PREFIX}_handoff_shipped",
            "prefill->decode KV handoffs shipped to the decode replica")
        self.handoff_declined = r.counter(
            f"{PREFIX}_handoff_declined",
            "handoffs the cost model declined (re-prefill estimated "
            "cheaper than the transfer)")
        self.handoff_fallback = r.counter(
            f"{PREFIX}_handoff_fallback",
            "accepted handoffs that failed mid-leg and fell back to plain "
            "relay (the decode replica re-prefills)")
        # Upstream connection pool (ISSUE 14): lifetime pool accounting as
        # stats-mirror gauges (the pool's counters are plain host ints;
        # render() mirrors them each scrape — the host_tier_spilled
        # idiom). hits/misses grade reuse, discards flag stale-socket
        # churn (troubleshooting §32), idle is the parked-socket gauge.
        self.pool_hits = r.gauge(
            f"{PREFIX}_pool_hits",
            "pooled upstream connections reused across relays/polls/"
            "probes (lifetime, stats mirror)")
        self.pool_misses = r.gauge(
            f"{PREFIX}_pool_misses",
            "upstream hops that had to open a fresh connection "
            "(lifetime, stats mirror)")
        self.pool_discards = r.gauge(
            f"{PREFIX}_pool_discards",
            "pooled upstream connections discarded (stale socket, age/"
            "idle cap, mid-request error, or fleet-mutation invalidation; "
            "lifetime, stats mirror)")
        self.pool_idle = r.gauge(
            f"{PREFIX}_pool_idle",
            "idle kept-alive upstream connections currently parked in "
            "the pool")
        # Event-loop data plane (ISSUE 17): the loop's own health family.
        # All zero on the threaded fallback. Tick time is PROCESSING time
        # per loop iteration (select return -> work drained), not the
        # select wait; the p95 gauge is maintained by the loop itself over
        # its recent tick window so a scrape never reads the histogram's
        # buckets cross-thread mid-update.
        self.loop_open_connections = r.gauge(
            f"{PREFIX}_loop_open_connections",
            "client connections currently held by the event-loop data "
            "plane (0 on the threaded fallback)")
        self.loop_open_sse_streams = r.gauge(
            f"{PREFIX}_loop_open_sse_streams",
            "SSE relays currently fanned through the event loop without "
            "a parked thread")
        self.loop_tick = r.histogram(
            f"{PREFIX}_loop_tick_seconds",
            "event-loop tick processing time (select return -> work "
            "drained; a stalled loop shows here first)",
            LOOP_TICK_BUCKETS_S)
        self.loop_tick_p95 = r.gauge(
            f"{PREFIX}_loop_tick_p95_s",
            "p95 loop-tick processing time over the loop's recent tick "
            "window (loop-maintained mirror; troubleshooting §35)")
        self.loop_ready_queue_depth = r.gauge(
            f"{PREFIX}_loop_ready_queue_depth",
            "file descriptors the last selector poll returned ready "
            "(sustained high depth = the loop is the bottleneck)")
        self.loop_accept_backlog_drops = r.counter(
            f"{PREFIX}_loop_accept_backlog_drops",
            "accepted client connections dropped at the "
            "gateway.evloop_max_connections cap")
        # Offload-pool saturation accounting (ISSUE 18): queue-wait plus
        # worker occupancy so "loop is fine, pool is starved" is
        # distinguishable from a blocked loop (troubleshooting §36).
        self.loop_offload_queue = r.histogram(
            f"{PREFIX}_loop_offload_queue_seconds",
            "handler offload queue wait (loop submit -> worker pickup; "
            "grows when the pool, not the loop, is the bottleneck)",
            LOOP_TICK_BUCKETS_S)
        self.loop_offload_busy = r.gauge(
            f"{PREFIX}_loop_offload_busy_workers",
            "offload-pool workers currently running a handler (pinned at "
            "pool size + queue wait growing = pool starvation)")
        self.loop_offload_workers = r.gauge(
            f"{PREFIX}_loop_offload_workers",
            "configured offload-pool size (gateway.evloop_offload_workers"
            "; denominator for occupancy)")

    # Each distinct tenant label becomes its own metric family; tenants
    # arrive as arbitrary unauthenticated bearer tokens, so beyond this
    # many distinct labels the long tail aggregates into one
    # `..._tenant_other_*` family instead of growing the registry (and
    # the /metrics exposition) without bound.
    MAX_TENANT_FAMILIES = 256

    def replica_counter(self, replica_id: str, kind: str):
        return self.registry.counter(
            f"{PREFIX}_replica_{sanitize_label(replica_id)}_{kind}",
            f"requests {kind} for replica {sanitize_label(replica_id)}")

    def class_counter(self, kind: str, slo_class: str | None):
        """Per-SLO-class routed/relayed/429 counters (ISSUE 9 satellite):
        ``ditl_gateway_<kind>_by_class_<class>`` — class steering is
        observable from /metrics without reading journals. Attribution is
        the class the request is SCHEDULED under: a tenant pin wins, else
        the client's ask; requests with neither land under ``default``
        (the engine schedules those as interactive). Bounded: 3 known
        classes + default."""
        label = sanitize_label(slo_class or "default")
        return self.registry.counter(
            f"{PREFIX}_{kind}_by_class_{label}",
            f"requests {kind} carrying SLO class {label}")

    def role_counter(self, role: str, kind: str):
        """Per-replica-role routed/spilled counters (ISSUE 9): the
        disaggregated fleet's steering decisions, aggregated by role
        rather than replica id. Bounded: 3 roles."""
        label = sanitize_label(role or "hybrid")
        return self.registry.counter(
            f"{PREFIX}_role_{label}_{kind}",
            f"requests {kind} on {label}-role replicas")

    def action_counter(self, kind: str, outcome: str):
        """Per action-kind/outcome counters (ISSUE 12):
        ``ditl_gateway_action_<kind>_<outcome>`` — how often the autoscale
        planner acted, refused, or failed, scrapeable without reading
        journals. Bounded: 4 kinds x 4 outcomes."""
        return self.registry.counter(
            f"{PREFIX}_action_{sanitize_label(kind)}_{sanitize_label(outcome)}",
            f"autoscale/remediation actions of kind {sanitize_label(kind)} "
            f"with outcome {sanitize_label(outcome)}")

    def tenant_counter(self, tenant: str, kind: str):
        label = sanitize_label(tenant)
        if label not in self._tenant_labels:
            if len(self._tenant_labels) >= self.MAX_TENANT_FAMILIES:
                label = "other"
            else:
                self._tenant_labels.add(label)
        return self.registry.counter(
            f"{PREFIX}_tenant_{label}_{kind}",
            f"requests {kind} for tenant {label}")

    def affinity_ratio(self) -> float | None:
        """Measured affinity hit-rate (hits / (hits + misses)); None before
        any repeated key. Policy-independent: computed from where requests
        actually LANDED, so round-robin and affinity are comparable on the
        same trace."""
        total = self.affinity_hits.value + self.affinity_misses.value
        if total == 0:
            return None
        return self.affinity_hits.value / total

    def render(self, fleet: Fleet | None = None) -> str:
        if fleet is not None:
            self.replicas_live.set(fleet.live_count())
            self.replicas_draining.set(fleet.draining_count())
            self.replicas_active.set(len(fleet.active_ids()))
            self.replicas_quarantined.set(len(fleet.quarantined_ids()))
            pool = fleet.pool.stats()
            self.pool_hits.set(pool["hits"])
            self.pool_misses.set(pool["misses"])
            self.pool_discards.set(pool["discards"])
            self.pool_idle.set(pool["idle"])
            views = fleet.views()
            self._set_cache_gauges(views)
            self._set_role_gauges(views)
            self._set_cold_start_gauges(views)
        return self.registry.render()

    def _set_cold_start_gauges(self, views) -> None:
        """Measured per-replica time-to-first-ready (ISSUE 12), from each
        replica's /health stamp: the number the scale-to-zero wake budget
        is derived from, exposed so an operator can see what Retry-After a
        cold fleet will promise. Absent until a replica reports one."""
        for v in views:
            if isinstance(v.cold_start_s, (int, float)):
                self.registry.gauge(
                    f"{PREFIX}_replica_{sanitize_label(v.id)}"
                    "_cold_start_seconds",
                    "measured time-to-first-ready the replica stamped on "
                    "/health (process start -> port bound) - the "
                    "scale-to-zero wake-budget input",
                ).set(round(v.cold_start_s, 3))

    def _set_cache_gauges(self, views) -> None:
        """Per-replica + token-weighted fleet prefix-cache hit ratios
        (ISSUE 8), sourced from each replica's last /health poll (no scrape
        fan-out) and rendered NEXT TO the routing-side affinity hit-rate so
        the router's claim (routed hit => KV reuse) is checkable from one
        exposition: affinity_ratio high while fleet_prefix_cache_hit_ratio
        is ~0 means the router is keying on something the engines cannot
        reuse (docs/troubleshooting.md §26). The lifetime ratio and the
        windowed recent ratio (ISSUE 9 — per-poll deltas, what the spill
        walk actually steers on) render side by side so a stale-sticky
        lifetime number is visible as such."""
        hit = miss = 0
        r_hit = r_miss = 0
        for v in views:
            rid = sanitize_label(v.id)
            ratio = v.cache_hit_ratio
            if ratio is not None:
                hit += v.cache_hit_tokens
                miss += v.cache_miss_tokens
                self.registry.gauge(
                    f"{PREFIX}_replica_{rid}_prefix_cache_hit_ratio",
                    f"measured engine prefix-cache hit ratio of replica "
                    f"{rid} (lifetime, from its last health poll)",
                ).set(round(ratio, 4))
            recent = v.recent_cache_hit_ratio
            if recent is not None:
                r_hit += v.recent_cache_hit_tokens
                r_miss += v.recent_cache_miss_tokens
                self.registry.gauge(
                    f"{PREFIX}_replica_{rid}_recent_prefix_cache_hit_ratio",
                    f"windowed (last few health polls) prefix-cache hit "
                    f"ratio of replica {rid} - the spill-steering input",
                ).set(round(recent, 4))
        if hit + miss:
            self.registry.gauge(
                f"{PREFIX}_fleet_prefix_cache_hit_ratio",
                "token-weighted fleet prefix-cache hit ratio - compare "
                "against the affinity hit-rate counters",
            ).set(round(hit / (hit + miss), 4))
        if r_hit + r_miss:
            self.registry.gauge(
                f"{PREFIX}_fleet_recent_prefix_cache_hit_ratio",
                "token-weighted fleet prefix-cache hit ratio over the "
                "recent health-poll window",
            ).set(round(r_hit / (r_hit + r_miss), 4))

    def _set_role_gauges(self, views) -> None:
        """Per-role fleet aggregation (ISSUE 9): live replica counts and
        worst-case (max) TTFT/TPOT p95 across each role's replicas, plus
        the role's peak slot pressure — the per-role latency view that
        makes 'which half of the disaggregated fleet is hurting' a single
        scrape (docs/troubleshooting.md §27)."""
        by_role: dict[str, list] = {}
        for v in views:
            by_role.setdefault(v.role or "hybrid", []).append(v)
        for role, vs in sorted(by_role.items()):
            label = sanitize_label(role)
            self.registry.gauge(
                f"{PREFIX}_role_{label}_replicas_live",
                f"live {label}-role replicas",
            ).set(sum(1 for v in vs if v.live))
            self.registry.gauge(
                f"{PREFIX}_role_{label}_slot_pressure",
                f"max active_slots/capacity across {label}-role replicas",
            ).set(round(max((v.slot_pressure for v in vs), default=0.0), 4))
            for key, name in (("ttft_p95_s", "ttft"),
                              ("tpot_p95_s", "tpot")):
                vals = [getattr(v, key) for v in vs
                        if isinstance(getattr(v, key), (int, float))]
                if vals:
                    self.registry.gauge(
                        f"{PREFIX}_role_{label}_{name}_p95_s",
                        f"worst per-replica {name} p95 across {label}-role "
                        "replicas (lifetime histograms, health-polled)",
                    ).set(round(max(vals), 6))

    def summary(self) -> dict:
        out = self.registry.summary()
        ratio = self.affinity_ratio()
        if ratio is not None:
            out[f"{PREFIX}_affinity_ratio"] = round(ratio, 4)
        return out


class GatewayHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, *args, **kwargs):
        # (timestamp, completed) samples for the fleet-level backlog-aware
        # Retry-After (same derivation the single server satellite uses).
        self._rate_samples: collections.deque = collections.deque(maxlen=64)
        # Persistent executors (ISSUE 14 satellite): hedged relays used to
        # build a fresh 2-worker ThreadPoolExecutor PER HEDGED REQUEST and
        # every /metrics//incidents fan-out built its own pool — thread
        # construction on the data plane's hot path. One hedge executor
        # and one fan-out executor per gateway, created here, shut down in
        # server_close's finally (the PR 11 thread-hygiene contract).
        # Hedge opens are short (connect + headers) but a primary must
        # never queue behind other requests' slow opens, so the hedge pool
        # is sized generously; fan-out probes are probe_timeout-bounded.
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="gw-hedge")
        self._fanout_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="gw-fanout")
        super().__init__(*args, **kwargs)

    def server_close(self):
        try:
            super().server_close()
        finally:
            self._hedge_pool.shutdown(wait=False, cancel_futures=True)
            self._fanout_pool.shutdown(wait=False, cancel_futures=True)


class _GatewayHandler(KeepAliveHandlerMixin, BaseHTTPRequestHandler):
    # Injected by make_gateway:
    fleet: Fleet = None
    router = None
    admission: TenantAdmission = None
    gw: GatewayMetrics = None
    gwcfg: GatewayConfig = None
    # key -> replica id that last served it (affinity hit-rate measurement)
    affinity_last: collections.OrderedDict = None  # guarded-by: affinity_lock
    affinity_lock: threading.Lock = None
    # Request tracing (ISSUE 6): the gateway roots (or continues) each
    # request's trace and stamps every relay attempt's span context on the
    # upstream request (W3C traceparent), so replica/engine spans nest
    # under the relay that carried them. Unarmed by default.
    tracer: Tracer = NULL_TRACER
    # Fleet-level SLO burn-rate monitor (telemetry/slo.py), served at /slo.
    slo: BurnRateMonitor = None
    # Incident plane (ISSUE 10): the gateway's own bundle manager (served
    # and aggregated with the replicas' at /incidents) and the routing-
    # decision flight ring (telemetry/flight.py). Both unarmed by default.
    incidents = None
    flight = None
    # Actuation plane (ISSUE 12): the autoscale actuator (serves /actions,
    # answers scale-to-zero demand with a measured wake budget) and the
    # traffic recorder (--save-trace). Both unarmed by default.
    actuator = None
    recorder = None
    # KV movement plane (ISSUE 13): kvtier (config.KVTierConfig) arms the
    # prefill->decode handoff orchestration on the relay leg; journal
    # (telemetry/journal.EventJournal) records the per-request cost-model
    # decision + both estimates (`kv.handoff.*` events). Unarmed by
    # default.
    kvtier = None
    journal = None
    # Usage metering (ISSUE 15): a telemetry/usage.UsageLedger recording
    # one gateway-edge row per admission-controlled request (tenant
    # digest, class, terminal outcome, e2e) — the edge half of the
    # attribution story (tenant throttles and fleet-level 429/503/504s
    # never reach an engine ledger). Unarmed by default.
    usage = None
    # Adapter publication coordinator (ISSUE 16): a
    # gateway/publish.AdapterPublisher driving fleet-wide
    # verify -> per-replica swap walks for /v1/adapters/{load,evict,
    # publish}. make_gateway always arms one (it needs only the fleet).
    publisher = None
    # Offline bulk-inference lane (ISSUE 19): a gateway/bulk.BulkJobManager
    # serving /v1/bulk/jobs — journaled crash-consistent jobs dispatching
    # per-prompt items through _route_and_relay pinned best_effort.
    # Unarmed by default (bulk.dir empty -> the routes 404).
    bulk = None

    def log_message(self, *args):
        logger.debug("gateway http: " + args[0], *args[1:])

    # -- plumbing -----------------------------------------------------------

    def _request_id(self) -> str:
        """Stable per-request id echoed on EVERY response — including
        429/503/504 and SSE relays — and forwarded upstream, so one id
        joins the client's logs, the gateway's spans, and the replica's
        (ISSUE 6 satellite). Reset per request in do_GET/do_POST (handler
        instances persist across keep-alive requests)."""
        rid = getattr(self, "_rid", None)
        if rid is None:
            rid = resolve_request_id(self.headers.get("X-Request-Id"))
            self._rid = rid
        return rid

    def _send_json(self, status: int, payload: dict,
                   retry_after: int | None = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Request-Id", self._request_id())
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _tenant(self) -> str:
        auth = self.headers.get("Authorization", "")
        if auth.lower().startswith("bearer "):
            return auth[7:].strip() or "anonymous"
        return "anonymous"

    def _sample_rate(self) -> None:
        self.server._rate_samples.append(
            (time.time(), self.gw.completed.value)
        )

    def _fleet_retry_after(self, floor: int = 1,
                           slo_class: str = "") -> int:
        """Backlog-aware Retry-After for fleet-level 429s: total backlog
        (queue + active across live replicas) over the gateway's recent
        completion rate — the same telemetry.serving.backlog_retry_after
        derivation the single server uses per replica.

        For ``best_effort`` callers on a bulk-armed gateway (ISSUE 19)
        the derivation switches inputs entirely: backlog = the bulk
        lane's pending work items, rate = the lane's own item-completion
        samples. A bulk submitter bounced off a deep offline backlog
        must come back when the BACKLOG has moved, not on the
        interactive service-rate clamp — the class hint also relaxes
        the clamp inside backlog_retry_after."""
        if slo_class == "best_effort" and self.bulk is not None:
            return backlog_retry_after(
                self.bulk.rate_samples, self.bulk.backlog(), floor=floor,
                slo_class=slo_class,
            )
        backlog = sum(
            v.queue_depth + v.active_slots + v.outstanding
            for v in self.fleet.views() if v.live
        )
        return backlog_retry_after(
            self.server._rate_samples, backlog, floor=floor,
            slo_class=slo_class,
        )

    # -- GET ----------------------------------------------------------------

    def do_GET(self):
        self._rid = None  # fresh id per request on keep-alive connections
        path, _, query = self.path.partition("?")
        path = path.rstrip("/") or "/"
        if path in ("/health", "/v1/health"):
            live = self.fleet.live_count()
            payload = {
                "status": "ok" if live else "no_live_replicas",
                "replicas_live": live,
                "replicas_draining": self.fleet.draining_count(),
                "replicas_total": len(self.fleet.ids),
            }
            # Loop-lag p95 from the evloop watchdog, absent != 0: only
            # reported when the watchdog is armed AND has observations
            # (same discipline as the replica role p95s).
            wd = getattr(self.server, "watchdog", None)
            lag = wd.lag_p95() if wd is not None else None
            if lag is not None:
                payload["loop_lag_p95_s"] = round(lag, 6)
            self._send_json(200 if live else 503, payload)
        elif path in ("/stats", "/v1/stats"):
            payload = {
                "router": getattr(self.router, "name", "unknown"),
                "replicas": {
                    v.id: {
                        "address": list(v.address),
                        "live": v.live,
                        "draining": v.draining,
                        "role": v.role,
                        "outstanding": v.outstanding,
                        "queue_depth": v.queue_depth,
                        "active_slots": v.active_slots,
                        "capacity": v.capacity,
                        "slot_pressure": round(v.slot_pressure, 4),
                        "prefix_cache_hit_ratio": v.cache_hit_ratio,
                        "recent_prefix_cache_hit_ratio":
                            v.recent_cache_hit_ratio,
                        "ttft_p95_s": v.ttft_p95_s,
                        "tpot_p95_s": v.tpot_p95_s,
                        "loop_lag_p95_s": v.loop_lag_p95_s,
                    }
                    for v in self.fleet.views()
                },
            }
            ratio = self.gw.affinity_ratio()
            if ratio is not None:
                payload["affinity_ratio"] = round(ratio, 4)
            if self.admission is not None:
                payload["tenants"] = self.admission.snapshot()
            self._send_json(200, payload)
        elif path == "/metrics":
            if self.slo is not None:
                # Refresh the ditl_slo_* gauges (same registry) so /metrics
                # carries the burn rates /slo renders; the scrape doubles
                # as the monitor's sample tick.
                self.slo.report()
            body = (self.gw.render(self.fleet)
                    + self._replica_memory_section()
                    + f"\n# TYPE {PREFIX}_up gauge\n{PREFIX}_up 1\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("X-Request-Id", self._request_id())
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path in ("/slo", "/v1/slo"):
            if self.slo is None:
                self._send_json(404, {"error": {"message":
                    "no SLO monitor configured"}})
            else:
                self._send_json(200, self.slo.report())
        elif path in ("/usage", "/v1/usage"):
            self._usage()
        elif path in ("/incidents", "/v1/incidents"):
            self._incidents()
        elif path in ("/actions", "/v1/actions"):
            # Actuation log (ISSUE 12): every planned/executed/refused/
            # failed action with its triggering signal snapshot and the
            # incident bundle it produced (the /actions-to-incident
            # cross-link, troubleshooting §30). 404 when the actuation
            # plane is unarmed — absent != "no actions taken".
            if self.actuator is None:
                self._send_json(404, {"error": {"message":
                    "no autoscale actuator configured"}})
            else:
                actions = self.actuator.recent()
                self._send_json(200, {
                    "count": len(actions),
                    "dry_run": bool(self.actuator.config.dry_run),
                    "wake_budget_s": round(
                        self.actuator.wake_budget_s(), 3),
                    "actions": actions,
                })
        elif path in ("/v1/models", "/models"):
            self._proxy_get("/v1/models")
        elif path in ("/v1/adapters", "/adapters"):
            self._adapters_get()
        elif path in ("/profile", "/v1/profile"):
            self._profile(query)
        elif path.startswith("/v1/bulk/jobs") or path.startswith("/bulk/jobs"):
            self._bulk_get(path, query)
        else:
            self._send_json(404, {"error": {"message": f"no route {self.path}"}})

    def _profile(self, query: str) -> None:
        """On-demand wall-clock profile (ISSUE 18): sample every thread
        for ``?seconds=N`` (clamped) and return flamegraph-ready
        collapsed stacks as text/plain. Stdlib sampler, no lock on the
        sample path — safe to hit on a loaded gateway."""
        from ditl_tpu.telemetry.prof import profile_for

        seconds = 2.0
        for part in query.split("&"):
            if part.startswith("seconds="):
                try:
                    seconds = float(part.split("=", 1)[1])
                except ValueError:
                    self._send_json(400, {"error": {
                        "message": "seconds must be a number"}})
                    return
        seconds = min(max(seconds, 0.1), 60.0)
        body = profile_for(seconds).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _incidents(self) -> None:
        """Fleet incident view (ISSUE 10): the gateway's own bundles plus
        every routable replica's /incidents listing, aggregated under one
        endpoint — "did anything fire anywhere" is one GET. Replicas
        without an armed incident plane answer 404 and are simply absent
        (absent != zero bundles); a slow/dead replica costs one skipped
        entry, never a wedged response."""
        from ditl_tpu.telemetry.incident import list_bundles

        own = (list_bundles(self.incidents.directory)
               if self.incidents is not None else [])
        replicas: dict[str, list] = {}

        def fetch(view):
            # Pooled probe (ISSUE 14): non-200 (404 = unarmed) raises
            # ValueError, read by the caller as "absent", exactly like the
            # old urlopen HTTPError.
            return self.fleet.pool.get_json(
                view.id, view.address, "/incidents",
                timeout=self.gwcfg.probe_timeout_s,
            )

        # /incidents is hit exactly when replicas are misbehaving, so N
        # slow replicas must cost ~probe_timeout_s total, not N x that.
        for view, data in self._fan_out_replicas(self.fleet.routable(),
                                                 fetch):
            if isinstance(data, dict) and data.get("incidents"):
                replicas[view.id] = data["incidents"]
        self._send_json(200, {
            "count": len(own) + sum(len(v) for v in replicas.values()),
            "gateway": own,
            "replicas": replicas,
        })

    def _adapters_get(self) -> None:
        """Fleet adapter view (ISSUE 16): every routable replica's
        /v1/adapters listing, fanned out concurrently with one shared
        deadline (the /incidents pattern). Replicas without an armed
        adapter plane answer 404 and are simply absent (absent != "zero
        adapters") — on a converged fleet every replica shows the same
        name->generation map; a mid-publication snapshot shows exactly
        which replicas have flipped."""
        def fetch(view):
            return self.fleet.pool.get_json(
                view.id, view.address, "/v1/adapters",
                timeout=self.gwcfg.probe_timeout_s,
            )

        replicas: dict[str, dict] = {}
        for view, data in self._fan_out_replicas(self.fleet.routable(),
                                                 fetch):
            if isinstance(data, dict) and "adapters" in data:
                replicas[view.id] = data
        self._send_json(200, {"replicas": replicas})

    def _usage(self) -> None:
        """Fleet usage view (ISSUE 15): every routable replica's /usage
        rollups fanned out concurrently (one shared deadline, the
        /incidents pattern) and merged into one per-tenant fleet rollup,
        plus the gateway's own admission counters — "what did tenant X
        consume, fleet-wide" is one GET. Replicas without an armed meter
        answer 404 and are simply absent (absent != zero usage)."""
        from ditl_tpu.telemetry.usage import merge_rollups

        def fetch(view):
            return self.fleet.pool.get_json(
                view.id, view.address, "/usage",
                timeout=self.gwcfg.probe_timeout_s,
            )

        replicas: dict[str, dict] = {}
        for view, data in self._fan_out_replicas(self.fleet.routable(),
                                                 fetch):
            if isinstance(data, dict) and isinstance(
                    data.get("tenants"), dict):
                replicas[view.id] = data["tenants"]
        payload = {
            "fleet": merge_rollups(list(replicas.values())),
            "replicas": replicas,
        }
        if self.admission is not None:
            # The gateway-edge view: admissions/throttles per tenant —
            # requests a throttle rejected never reach any replica meter.
            payload["gateway_tenants"] = self.admission.snapshot()
        self._send_json(200, payload)

    def _fan_out_replicas(self, views, fetch) -> list:
        """Concurrent per-replica ``fetch`` with ONE shared deadline
        (~probe_timeout_s for the whole fan-out): returns ``(view,
        result)`` pairs for the replicas that answered in time. A slow or
        dead replica costs one skipped entry, never a wedged response —
        stragglers are abandoned (queued-not-started futures cancelled,
        running ones die at their own socket timeouts). Runs on the
        gateway's persistent fan-out executor (ISSUE 14 satellite — no
        more per-scrape pool construction); shared by the /metrics memory
        section and /incidents."""
        out: list = []
        if not views:
            return out
        pool = self.server._fanout_pool
        futures = {pool.submit(fetch, v): v for v in views}
        done, not_done = wait(futures, timeout=self.gwcfg.probe_timeout_s)
        for f in not_done:
            f.cancel()
        for f in done:
            try:
                out.append((futures[f], f.result()))
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException, ValueError):
                continue
        return out

    def _replica_memory_section(self) -> str:
        """Fleet HBM view (ISSUE 7): each routable replica's
        ``ditl_memory_*`` gauges, re-namespaced per replica
        (``ditl_memory_<rid>_device0_bytes_in_use``) so the fleet's memory
        headroom is scrapable from ONE endpoint. Replicas are fetched
        CONCURRENTLY with one shared deadline (~probe_timeout_s for the
        whole section, not per replica — N slow replicas must not push the
        gateway scrape past Prometheus's own timeout); a slow or dead
        replica costs one skipped section, never a wedged scrape. CPU
        replicas contribute nothing (no ditl_memory_* lines to filter)."""
        def fetch(view):
            return self.fleet.pool.get_text(
                view.id, view.address, "/metrics",
                timeout=self.gwcfg.probe_timeout_s,
            )

        out: list[str] = []
        for view, text in self._fan_out_replicas(self.fleet.routable(),
                                                 fetch):
            rid = sanitize_label(view.id)
            for line in text.splitlines():
                # Matches both samples and their # TYPE/# HELP metadata
                # (the family name follows the directive keyword).
                if "ditl_memory_" in line.split("{", 1)[0]:
                    out.append(line.replace(
                        "ditl_memory_", f"ditl_memory_{rid}_"
                    ))
        return ("\n" + "\n".join(out)) if out else ""

    def _proxy_get(self, path: str) -> None:
        for view in self.fleet.routable():
            try:
                self._send_json(200, self.fleet.pool.get_json(
                    view.id, view.address, path,
                    timeout=self.gwcfg.probe_timeout_s,
                ))
                return
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException, ValueError):
                self.fleet.note_failure(view.id)
                continue
        self._send_json(503, {"error": {"message": "no live replica"}})

    # -- POST ---------------------------------------------------------------

    def do_POST(self):
        self._rid = None  # fresh id per request on keep-alive connections
        self._adapter_pin = None  # set per-request by _admit_and_route
        bulk_path = self.path.partition("?")[0].rstrip("/")
        if (bulk_path.startswith("/v1/bulk/jobs")
                or bulk_path.startswith("/bulk/jobs")):
            # Bulk routes parse their own body (a submit may be a JSONL
            # prompt upload, which the JSON-object gate below would 400).
            self._bulk_post(bulk_path)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) or b"{}"
            payload = json.loads(raw)
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            # A malformed Content-Length leaves the body unread; on a
            # kept-alive connection those bytes would desync the next
            # request — close after the error response. (Malformed JSON
            # reached here with the body fully read; closing anyway is
            # one wasted reconnect, not a correctness cost.)
            self.close_connection = True
            self._send_json(400, {"error": {"message": f"bad request: {e}"}})
            return
        path = self.path.rstrip("/")
        if path.endswith(("/chat/completions", "/completions", "/embeddings")):
            self.gw.requests.inc()
            # Root (or continue, if the client sent traceparent) this
            # request's trace: every relay attempt below becomes a child
            # span, and the replica continues the chain across the process
            # boundary.
            span = self.tracer.start_span(
                "gateway.request",
                parent=parse_traceparent(self.headers.get("traceparent")),
                request_id=self._request_id(),
                route=path,
            )
            try:
                self._admit_and_route(path, payload, raw, span=span)
            finally:
                det = getattr(self, "_evloop_detached", None)
                if det is None:
                    span.end()
                else:
                    # Evloop SSE detach (ISSUE 17): the stream outlives
                    # this handler invocation — the loop ends the root
                    # span at stream end, after the relay span.
                    det["root"] = span
        elif path.endswith(("/tokenize", "/detokenize")):
            # Metadata routes: cheap, not admission-controlled, and kept
            # OUT of the serving instruments (record=False) — a stream of
            # millisecond tokenize calls would otherwise inflate the
            # measured completion rate behind Retry-After and corrupt the
            # affinity hit-rate the router A/B records.
            self.gw.requests.inc()
            self._route_and_relay(path, payload, raw, record=False)
        elif path.endswith(("/adapters/load", "/adapters/evict",
                            "/adapters/publish")):
            # Adapter control plane (ISSUE 16): fleet-wide publication —
            # verify-at-edge, then a journaled per-replica walk. Not
            # admission-controlled (operator/trainer traffic, like the
            # actuation plane), and kept out of the serving instruments.
            self._adapter_admin(payload, path.rsplit("/", 1)[1])
        else:
            self._send_json(404, {"error": {"message": f"no route {self.path}"}})

    def _adapter_admin(self, payload: dict, op: str) -> None:
        if self.publisher is None:
            self._send_json(404, {"error": {"message":
                "no adapter publisher configured"}})
            return
        owner = str(payload.get("owner") or "")
        if not owner:
            # Default attribution: the caller's credential-safe label —
            # same identity the replicas' per-tenant ledgers bill under.
            owner = tenant_label(
                self._tenant(),
                self.admission.per_tenant
                if self.admission is not None else ())
        status, answer = self.publisher.run(
            op,
            str(payload.get("name") or ""),
            directory=str(payload.get("dir")
                          or payload.get("directory") or ""),
            owner=owner,
        )
        self._send_json(status, answer)

    # -- bulk lane (ISSUE 19) ------------------------------------------------

    def _bulk_label(self) -> str:
        """Credential-safe tenant label — the only identity the bulk lane
        ever persists (job files, journal rows, usage rows). Raw bearers
        stay in admission state, exactly the ISSUE 15 discipline."""
        return tenant_label(
            self._tenant(),
            self.admission.per_tenant if self.admission is not None else ())

    @staticmethod
    def _bulk_parts(path: str) -> list[str]:
        parts = [p for p in path.split("/") if p]
        if parts and parts[0] == "v1":
            parts = parts[1:]
        return parts

    def _bulk_get(self, path: str, query: str) -> None:
        if self.bulk is None:
            self._send_json(404, {"error": {"message":
                "bulk lane not configured (set bulk.dir)"}})
            return
        parts = self._bulk_parts(path)
        if parts == ["bulk", "jobs"]:
            jobs = self.bulk.jobs()
            self._send_json(200, {"count": len(jobs), "jobs": jobs})
        elif len(parts) == 3 and parts[:2] == ["bulk", "jobs"]:
            st = self.bulk.status(parts[2])
            if st is None:
                self._send_json(404, {"error": {"message":
                    f"no bulk job {parts[2]!r}"}})
            else:
                self._send_json(200, st)
        elif (len(parts) == 4 and parts[:2] == ["bulk", "jobs"]
                and parts[3] == "results"):
            self._bulk_results(parts[2], query)
        else:
            self._send_json(404, {"error": {"message":
                f"no route {self.path}"}})

    def _bulk_results(self, job_id: str, query: str) -> None:
        """Ordered results JSONL. Range-resumable: ``Range: bytes=N-``
        (or ``?offset=N``) answers 206 with the suffix — a client that
        died mid-download (or is polling a running job) resumes from its
        last byte, and the contiguous-prefix flush guarantees every byte
        it already holds is final."""
        if self.bulk.status(job_id) is None:
            self._send_json(404, {"error": {"message":
                f"no bulk job {job_id!r}"}})
            return
        try:
            with open(self.bulk.results_path(job_id), "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        start = 0
        m = re.match(r"^bytes=(\d+)-$", self.headers.get("Range") or "")
        if m:
            start = int(m.group(1))
        else:
            m = re.search(r"(?:^|&)offset=(\d+)", query or "")
            if m:
                start = int(m.group(1))
        start = min(start, len(data))
        body = data[start:]
        self.send_response(206 if start else 200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Accept-Ranges", "bytes")
        if start:
            self.send_header(
                "Content-Range",
                f"bytes {start}-{max(start, len(data) - 1)}/{len(data)}")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _bulk_post(self, path: str) -> None:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length > 0 else b""
        if self.bulk is None:
            self._send_json(404, {"error": {"message":
                "bulk lane not configured (set bulk.dir)"}})
            return
        parts = self._bulk_parts(path)
        if parts == ["bulk", "jobs"]:
            self._bulk_submit(raw)
        elif (len(parts) == 4 and parts[:2] == ["bulk", "jobs"]
                and parts[3] == "cancel"):
            if self.bulk.cancel(parts[2]):
                self._send_json(200, {"id": parts[2],
                                      "cancel_requested": True})
            else:
                self._send_json(404, {"error": {"message":
                    f"no bulk job {parts[2]!r}"}})
        else:
            self._send_json(404, {"error": {"message":
                f"no route {self.path}"}})

    def _bulk_submit(self, raw: bytes) -> None:
        """POST /v1/bulk/jobs: inline JSON (``{"prompts": [...], adapter,
        max_new, sampling}``) or an uploaded JSONL body (one
        ``{"prompt": ...}`` — or bare string — per line; params via
        ``?adapter=&max_new=`` query). Quota-gated per tenant with typed
        429s; the accepted job is durable before this returns 200."""
        query = self.path.partition("?")[2]
        try:
            prompts, params = self._bulk_parse_submit(raw, query)
        except ValueError as e:
            self.close_connection = True
            self._send_json(400, {"error": {"message": f"bad request: {e}"}})
            return
        label = self._bulk_label()
        if self.admission is not None:
            decision = self.admission.acquire_bulk(label, len(prompts))
            if not decision.ok:
                self.gw.class_counter("429", "best_effort").inc()
                self._send_json(
                    429,
                    {"error": {"message": decision.reason,
                               "type": "bulk_quota_exceeded"}},
                    retry_after=max(
                        1, int(decision.retry_after_s + 0.999),
                        self._fleet_retry_after(slo_class="best_effort")),
                )
                return
        try:
            st = self.bulk.submit(label, prompts, params)
        except ValueError as e:
            if self.admission is not None:
                self.admission.release_bulk(label, len(prompts))
            self._send_json(400, {"error": {"message": f"bad request: {e}"}})
            return
        self._send_json(200, st)

    @staticmethod
    def _bulk_parse_submit(raw: bytes, query: str) -> tuple[list, dict]:
        text = (raw or b"").decode("utf-8", "replace").strip()
        if not text:
            raise ValueError("empty bulk submit body")
        params: dict = {}
        if text.startswith("{"):
            try:
                payload = json.loads(text)
                if not isinstance(payload, dict):
                    raise ValueError
            except ValueError:
                payload = None
            if payload is not None and "prompts" in payload:
                prompts = payload.get("prompts")
                if not isinstance(prompts, list):
                    raise ValueError("prompts must be a list")
                for k in ("adapter", "max_new", "sampling"):
                    if k in payload:
                        params[k] = payload[k]
                return prompts, params
        # JSONL upload: one prompt per line ({"prompt": ...} or a bare
        # JSON string); per-job params ride the query string.
        prompts = []
        for n, line in enumerate(text.splitlines(), start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"bad JSONL at line {n}: {e}") from None
            if isinstance(rec, str):
                prompts.append(rec)
            elif isinstance(rec, dict) and isinstance(
                    rec.get("prompt"), str):
                prompts.append(rec["prompt"])
            else:
                raise ValueError(
                    f"JSONL line {n} must be a string or hold a "
                    "string 'prompt'")
        for m in re.finditer(r"(?:^|&)(adapter|max_new)=([^&]*)",
                             query or ""):
            k, v = m.group(1), m.group(2)
            params[k] = int(v) if k == "max_new" else v
        return prompts, params

    def _admit_and_route(self, path: str, payload: dict, raw: bytes,
                         span=None) -> None:
        m = self.gw
        tenant = self._tenant()
        # Credential-safe label (ISSUE 15): computed ONCE here and used
        # everywhere downstream — metrics, the traffic recorder, the
        # routing flight ring, the X-Tenant-Label relay header, and the
        # gateway usage ledger. The raw bearer keys admission state only.
        label = tenant_label(
            tenant,
            self.admission.per_tenant if self.admission is not None else ())
        # Reject-don't-drop for explicit client classes: a malformed
        # X-SLO-Class must 400 HERE, exactly as the replica would — the
        # relay layer only forwards KNOWN names (header-injection guard),
        # so silently stripping a typo'd class would serve the request at
        # the default priority with no error signal.
        cls_hdr = self.headers.get("X-SLO-Class")
        if cls_hdr is not None and cls_hdr not in SLO_CLASS_NAMES:
            self._send_json(400, {"error": {"message":
                f"unknown X-SLO-Class (one of {list(SLO_CLASS_NAMES)})"}})
            return
        pinned_class = None
        if self.admission is not None:
            # Raw Bearer token keys the admission state (per_tenant
            # overrides match on it); metrics get the credential-safe
            # label only (/metrics is unauthenticated).
            decision = self.admission.acquire(tenant)
            if not decision.ok:
                m.throttled.inc()
                m.tenant_counter(label, "throttled").inc()
                # Same attribution as routed/relayed/saturated: the class
                # the request would have been scheduled under (pin wins).
                m.class_counter(
                    "429",
                    decision.slo_class or self._client_class(payload),
                ).inc()
                if span is not None:
                    span.annotate(throttled=True)
                self._send_json(
                    429,
                    {"error": {"message": decision.reason,
                               "type": "rate_limit_error"}},
                    retry_after=max(1, min(30, math.ceil(
                        decision.retry_after_s))),
                )
                if self.usage is not None:
                    # A throttle is a terminal outcome only the gateway
                    # can bill — the request never reaches a replica.
                    self.usage.record(
                        tenant=label, outcome="429",
                        slo_class=(decision.slo_class
                                   or self._client_class(payload)
                                   or "default"),
                        prompt_tokens=prompt_token_estimate(payload),
                        throttled=True,
                    )
                return
            m.tenant_counter(label, "admitted").inc()
            pinned_class = decision.slo_class or None
            # Adapter pin (ISSUE 16): rides X-Adapter-Name on every relay
            # attempt of THIS request (stashed on the handler instance,
            # which serves one request at a time — the _rid pattern), and
            # OVERRIDES the payload's model field at the replica.
            self._adapter_pin = decision.adapter or None
        if self.recorder is not None:
            # Traffic recorder (ISSUE 12 satellite): one row per ADMITTED
            # request — throttled requests never reach here, so the saved
            # shape is the demand the fleet actually served, replayable
            # via bench.py --serve-trace-replay with preserved
            # inter-arrival times. Tenant rides as the credential-safe
            # digest, never the bearer token.
            self.recorder.note(
                tenant=label,
                slo_class=pinned_class or self._client_class(payload),
                prompt_tokens=prompt_token_estimate(payload),
                max_new=int(payload.get("max_tokens") or 0)
                if isinstance(payload.get("max_tokens"), (int, float))
                else 0,
                stream=bool(payload.get("stream")),
            )
        t0 = time.time()
        outcome = "error"
        try:
            outcome = self._route_and_relay(path, payload, raw, span=span,
                                            slo_class=pinned_class,
                                            tenant=label)
        finally:
            det = (getattr(self, "_evloop_detached", None)
                   if outcome == "detached" else None)
            if det is not None:
                # Evloop SSE detach (ISSUE 17): the request is still in
                # flight — it holds its admission slot and its e2e clock
                # until the loop sees the stream end. Everything below
                # runs then, via this closure, with the outcome the
                # CLIENT actually saw.
                def _finish(final_outcome: str) -> None:
                    if self.admission is not None:
                        self.admission.release(tenant)
                    m.e2e.observe(time.time() - t0)
                    if self.usage is not None:
                        self.usage.record(
                            tenant=label, outcome=final_outcome,
                            slo_class=(pinned_class
                                       or self._client_class(payload)
                                       or "default"),
                            prompt_tokens=prompt_token_estimate(payload),
                            stream=True,
                            e2e_s=round(time.time() - t0, 6),
                        )
                det["finish"] = _finish
            else:
                if self.admission is not None:
                    self.admission.release(tenant)
                m.e2e.observe(time.time() - t0)
                if self.usage is not None:
                    # One gateway-edge usage row per admitted request —
                    # the outcome the CLIENT saw (fleet 429/503/504s
                    # included), next to the engine-side rows the
                    # replicas ledger.
                    self.usage.record(
                        tenant=label, outcome=outcome,
                        slo_class=(pinned_class
                                   or self._client_class(payload)
                                   or "default"),
                        prompt_tokens=prompt_token_estimate(payload),
                        stream=bool(payload.get("stream")),
                        e2e_s=round(time.time() - t0, 6),
                    )

    def _client_class(self, payload: dict) -> str | None:
        """The SLO class the CLIENT asked for (validated header, else
        payload) — the metrics/steering view before any tenant pin."""
        cls = self.headers.get("X-SLO-Class")
        if cls in SLO_CLASS_NAMES:
            return cls
        cls = payload.get("slo_class")
        return cls if cls in SLO_CLASS_NAMES else None

    def _route_and_relay(self, path: str, payload: dict, raw: bytes,
                         record: bool = True, span=None,
                         slo_class: str | None = None,
                         tenant: str | None = None) -> str:
        """Route + relay one request; returns the terminal outcome the
        client saw (``200``/``429``/``503``/``504``/``cancel`` — the
        usage-ledger vocabulary; ``cancel`` = a stream aborted after
        bytes moved). ``tenant`` is the CREDENTIAL-SAFE label (never the
        bearer) — it rides the routing flight ring and the
        X-Tenant-Label header every relay stamps, which is how the
        replica's engine attributes its accounting (ISSUE 15)."""
        m, cfg = self.gw, self.gwcfg
        stream = bool(payload.get("stream"))
        key = affinity_key(payload, cfg.affinity_prefix_tokens)
        # The class the REPLICA will schedule under: the tenant pin wins
        # (it rides X-SLO-Class on every relay, overriding the payload),
        # else whatever the client asked for. This is also the routing
        # input for role steering on disaggregated fleets (ISSUE 9).
        eff_class = slo_class or self._client_class(payload)
        prompt_toks = prompt_token_estimate(payload) if cfg.role_routing \
            else 0
        # Deadline propagation (ISSUE 5): the effective budget is the
        # smaller of the client's `deadline_s` and the gateway's own
        # request_timeout_s; each relay attempt forwards the REMAINING
        # budget as X-Request-Deadline-S so the replica's engine evicts
        # work the gateway will have abandoned anyway (otherwise a retry
        # storm leaves dead generations burning slots fleet-wide).
        budget = cfg.request_timeout_s
        client_deadline = payload.get("deadline_s")
        has_client_deadline = (
            isinstance(client_deadline, (int, float)) and client_deadline > 0
        )
        if has_client_deadline:
            budget = min(budget, float(client_deadline))
        # Streams are the exception to "work the gateway will have
        # abandoned anyway": the gateway's socket timeout is per-read, so a
        # healthy stream longer than request_timeout_s is never abandoned
        # here — stamping the header would make the replica's engine evict
        # it and silently truncate the generation. Only an explicit client
        # deadline propagates into a stream; `budget` still bounds the
        # pre-first-byte attempt loop either way.
        propagate_deadline = has_client_deadline or not stream
        t_deadline0 = time.monotonic()
        timed_out = False
        tried: list[str] = []
        saw_busy = False
        busy_hint = 0
        for attempt in range(max(1, cfg.max_attempts)):
            remaining = budget - (time.monotonic() - t_deadline0)
            if remaining <= 0:
                timed_out = True
                break
            candidates = self.fleet.routable(exclude=tried)
            if not candidates:
                break
            # Role/class steering (ISSUE 9): restrict the candidate set by
            # the request's class before the policy picks. A no-op on
            # homogeneous fleets; on heterogeneous ones an empty preferred
            # set falls back to everything — no class is ever unroutable.
            if cfg.role_routing:
                candidates = role_candidates(
                    candidates, eff_class, prompt_toks,
                    cfg.long_prompt_tokens,
                )
            # route_info["spill"]: the affinity policy reports whether the
            # pick landed away from the key's (role-filtered) home — a
            # saturation spill, counted per role so the "all prefill-heavy
            # replicas saturated" signature is scrapable (troubleshooting
            # §27). Policies without homes never set it.
            route_info: dict = {}
            view = self.router.pick(key, candidates, slo_class=eff_class,
                                    prompt_tokens=prompt_toks,
                                    info=route_info)
            spilled = attempt == 0 and bool(route_info.get("spill"))
            if self.flight is not None:
                # Flight recorder (ISSUE 10): one routing-decision row per
                # relay attempt — which replica/role a request landed on,
                # under what class, and whether affinity spilled. Host
                # state only; dumped only into incident bundles.
                self.flight.ring(ROUTING_RING).record(
                    request=self._request_id(), attempt=attempt,
                    replica=view.id, role=view.role,
                    slo_class=eff_class or "default", spill=spilled,
                    stream=stream, candidates=len(candidates),
                    # Attribution (ISSUE 15): ring dumps inside incident
                    # bundles carry WHOSE requests landed where.
                    tenant=tenant or "anonymous",
                )
            if record:
                if attempt > 0:
                    m.retries.inc()
                    m.replica_counter(view.id, "retried").inc()
                m.replica_counter(view.id, "routed").inc()
                m.role_counter(view.role, "routed").inc()
                if spilled:
                    m.role_counter(view.role, "spilled").inc()
                if attempt == 0:
                    m.class_counter("routed", eff_class).inc()
            elif attempt > 0:
                m.retries.inc()
            if attempt == 0 and record and path.endswith("/completions"):
                # KV handoff (ISSUE 13): before relaying to the decode
                # replica the router just chose, maybe prefill the prompt
                # on a prefill_heavy replica and ship the paged KV over —
                # the decode replica's admission then prefix-matches the
                # shipped pages instead of re-prefilling. Best-effort by
                # construction: every failure path falls back to the plain
                # relay below (the replica re-prefills; the client never
                # sees a handoff failure).
                self._maybe_handoff(
                    view, payload, span=span,
                    deadline_left=remaining if propagate_deadline else None,
                )
            hedge_peers = (
                [v for v in candidates if v.id != view.id]
                if cfg.hedge_after_s > 0 and not stream else []
            )
            # The gateway's own in-flight count is the live half of the
            # load signal (least-outstanding, affinity spill, hedge-peer
            # choice, rolling_restart's drain-wait all read it); health-poll
            # queue depth alone is a full interval stale.
            # One relay span per attempt (retries are tagged, hedged
            # secondaries become SIBLING spans inside _hedged_open); the
            # attempt's span context rides the upstream request as
            # traceparent so the replica's spans nest under it.
            rspan = (
                self.tracer.start_span(
                    "gateway.relay", parent=span, replica=view.id,
                    attempt=attempt, retry=attempt > 0,
                    # Role-routing decision evidence (ISSUE 9): the trace
                    # shows WHERE each class landed and whether it spilled.
                    role=view.role, slo_class=eff_class or "default",
                    spill=spilled,
                )
                if span is not None else None
            )
            self.fleet.inc_outstanding(view.id)
            outcome, info = "error", None
            try:
                outcome, info = self._relay_one(
                    view, path, raw, stream, hedge_peers,
                    deadline_left=remaining if propagate_deadline else None,
                    span=rspan, root=span, slo_class=slo_class,
                    tenant=tenant,
                )
            finally:
                if outcome == "detached":
                    # Evloop SSE detach (ISSUE 17): the stream is still
                    # live — it stays outstanding (it IS load on the
                    # replica) and its relay span stays open; the loop
                    # runs both at stream end via the closure below.
                    pass
                else:
                    self.fleet.dec_outstanding(view.id)
                    if rspan is not None:
                        if outcome == "done" and info and info != view.id:
                            # A hedged peer served: THIS attempt lost —
                            # its span must not read as the one that
                            # answered (the winner's hedge span carries
                            # outcome="won").
                            rspan.end(outcome="lost", served_by=info)
                        else:
                            rspan.end(outcome=outcome)
            if outcome == "done":
                if record:
                    self._note_affinity(key, info or view.id)
                    m.completed.inc()
                    m.class_counter("relayed", eff_class).inc()
                    self._sample_rate()
                return "200"
            if outcome == "detached":
                # The loop owns both sockets now; the deferred half of
                # the "done"/"aborted" bookkeeping above runs when it
                # sees the stream end.
                det = self._evloop_detached
                served_id = info or view.id

                def _complete(ok: bool) -> None:
                    if ok:
                        if record:
                            self._note_affinity(key, served_id)
                            m.completed.inc()
                            m.class_counter("relayed", eff_class).inc()
                            self._sample_rate()
                    else:
                        # Bytes already relayed; nothing more the
                        # gateway can do (same terminal as "aborted").
                        m.stream_aborts.inc()
                    self.fleet.dec_outstanding(view.id)
                det["complete"] = _complete
                return "detached"
            if outcome == "aborted":
                # Bytes already relayed; nothing more the gateway can do.
                m.stream_aborts.inc()
                return "cancel"
            if outcome == "busy":
                saw_busy = True
                hint, busy_id = info
                busy_hint = max(busy_hint, hint)
                # Exclude the replica that actually SAID busy — under
                # hedging that can be the peer, not the primary (a merely
                # slow primary stays eligible for the next attempt).
                tried.append(busy_id)
            else:
                tried.append(view.id)
        if self.flight is not None:
            # Terminal failure row: the ring shows not just where requests
            # went but which ones the FLEET failed, and how.
            self.flight.ring(ROUTING_RING).record(
                request=self._request_id(),
                outcome=("timeout" if timed_out
                         else "saturated" if saw_busy else "no_replica"),
                slo_class=eff_class or "default",
                tenant=tenant or "anonymous",
            )
        if timed_out:
            self._send_json(504, {"error": {
                "message": "request deadline exhausted before any replica "
                           "answered",
                "type": "timeout_error"}})
            return "504"
        elif saw_busy:
            m.saturated.inc()
            if record:
                m.class_counter("429", eff_class).inc()
            self._send_json(
                429,
                {"error": {"message": "fleet saturated; retry later",
                           "type": "rate_limit_error"}},
                retry_after=self._fleet_retry_after(
                    floor=busy_hint, slo_class=eff_class or ""),
            )
            return "429"
        else:
            if self.actuator is not None:
                # Cold-start-aware admission (ISSUE 12): nothing routable
                # but a scale-down parked capacity we can wake — answer
                # 429 with the MEASURED wake budget as Retry-After (the
                # client's backoff lands after the replica is up) and let
                # the planner's wake action bring it back. A plain 503
                # would teach clients the fleet is broken when it is
                # merely asleep.
                retry = self.actuator.note_demand()
                if retry is not None:
                    self.gw.registry.counter(
                        f"{PREFIX}_cold_start_429",
                        "requests answered 429 with a wake-up Retry-After "
                        "while serving capacity was parked (scale-to-zero "
                        "admission)",
                    ).inc()
                    self._send_json(
                        429,
                        {"error": {"message":
                                   "fleet scaled to zero; waking a replica",
                                   "type": "rate_limit_error"}},
                        retry_after=retry,
                    )
                    return "429"
            m.no_replica.inc()
            self._send_json(503, {"error": {
                "message": "no live replica available"}})
            return "503"

    # -- KV handoff orchestration (ISSUE 13) ---------------------------------

    def _handoff_post(self, view, path: str, body: bytes, ctype: str,
                      timeout: float) -> bytes:
        """One bounded intra-host handoff hop over the upstream pool;
        non-200 raises (the caller falls back to plain relay)."""
        status, _, data = self.fleet.pool.request(
            view.id, view.address, "POST", path, body=body, headers={
                "Content-Type": ctype,
                "X-Request-Id": self._request_id(),
            }, timeout=timeout,
        )
        if status != 200:
            raise ValueError(f"{path} on {view.id} answered {status}")
        return data

    def _maybe_handoff(self, view, payload: dict, span=None,
                       deadline_left: float | None = None) -> None:
        """Prefill->decode KV handoff on the relay leg: when the chosen
        decode replica would have to prefill a long prompt, have a
        ``prefill_heavy`` replica prefill it instead, serialize the paged
        KV (infer/kv_transfer.py), and import it into the decode replica
        BEFORE the relay — DistServe/Splitwise disaggregation made real
        rather than routed-around.

        Gated by a measured transfer-cost model: estimated ship time
        (bytes / the decode replica's measured device_put bandwidth +
        fixed overhead) against estimated re-prefill time (tokens / its
        measured prefill tok/s), with configured floors before anything
        is measured. Re-prefill wins for short prompts and the model must
        say so — the decision AND both estimates are journaled per
        request (``kv.handoff.decision``). Chaos site ``kv.handoff``
        (error/delay) and any transport/HTTP failure — including a
        SIGKILL'd prefill replica mid-handoff — land in the fallback
        branch: counted, journaled, and the caller's plain relay proceeds
        with zero client-visible failures."""
        kt = self.kvtier
        if kt is None or not kt.handoff:
            return
        if not getattr(view, "kv_handoff", False) \
                or view.role == "prefill_heavy":
            return  # a prefill_heavy target prefills locally by design
        # The request's deadline budget BOUNDS the handoff, it is never
        # spent past it: with under a second left there is no room for
        # two hops plus a prefill — relay immediately (the deadline
        # contract promised a 504 in seconds, not a 120 s stall behind a
        # wedged prefill replica), and below each leg's socket timeout is
        # capped at the remaining budget.
        if deadline_left is not None and deadline_left < 1.0:
            return
        prompt = payload.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            return  # chat/messages tokenization is replica-side; skip
        sources = handoff_sources(self.fleet.routable(), view.id)
        if not sources:
            return
        m = self.gw
        # Model-token estimate, not a raw word count: the floors and the
        # cost formulas are denominated in model tokens, and a whitespace
        # count undercounts subword/byte tokenizers several-fold (a long
        # code prompt would never clear the min-tokens floor). chars /
        # est_chars_per_token is the tokenizer-free approximation; the
        # word count stays as a lower bound.
        tokens = max(prompt_token_estimate(payload),
                     int(len(prompt) / kt.est_chars_per_token))
        m.handoff_attempted.inc()
        bpt = view.kv_bytes_per_token
        if not bpt:
            bpt = next(
                (v.kv_bytes_per_token for v in sources
                 if v.kv_bytes_per_token), 0.0,
            )
        bw = (view.kv_put_mbps or kt.put_bw_floor_mbps) * 1e6
        tps = view.prefill_tok_per_s or kt.prefill_tps_floor
        est_transfer_s = kt.handoff_overhead_s + tokens * (bpt or 0.0) / bw
        est_prefill_s = tokens / tps
        ship = (tokens >= kt.handoff_min_prompt_tokens
                and est_transfer_s < est_prefill_s)
        source = min(sources, key=lambda v: v.outstanding + v.queue_depth)
        if self.journal is not None:
            self.journal.event(
                "kv.handoff.decision",
                request=self._request_id(),
                decision="ship" if ship else "decline",
                prompt_tokens=tokens,
                est_transfer_s=round(est_transfer_s, 6),
                est_prefill_s=round(est_prefill_s, 6),
                decode_replica=view.id, prefill_replica=source.id,
            )
        if not ship:
            m.handoff_declined.inc()
            return
        t_start = time.monotonic()

        def leg_timeout() -> float:
            t = kt.handoff_timeout_s
            if deadline_left is not None:
                t = min(t, max(
                    0.001, deadline_left - (time.monotonic() - t_start)
                ))
            return t

        try:
            # Chaos seam: `error` = a lost handoff leg, `delay` = a slow
            # one; both end in the fallback branch below, exactly like a
            # replica dying mid-handoff does.
            maybe_inject("kv.handoff")
            blob = self._handoff_post(
                source, "/internal/prefill",
                json.dumps({"prompt": prompt}).encode(),
                "application/json", leg_timeout(),
            )
            self._handoff_post(
                view, "/internal/kv_handoff", blob,
                "application/octet-stream", leg_timeout(),
            )
        except (InjectedFault, OSError, http.client.HTTPException,
                ValueError) as e:
            m.handoff_fallback.inc()
            if self.journal is not None:
                self.journal.event(
                    "kv.handoff.fallback", request=self._request_id(),
                    error=str(e)[:200],
                    decode_replica=view.id, prefill_replica=source.id,
                )
            if span is not None:
                span.annotate(handoff="fallback")
            return
        m.handoff_shipped.inc()
        if self.journal is not None:
            self.journal.event(
                "kv.handoff.shipped", request=self._request_id(),
                bytes=len(blob), prompt_tokens=tokens,
                decode_replica=view.id, prefill_replica=source.id,
            )
        if span is not None:
            span.annotate(handoff="shipped")

    # -- relaying -----------------------------------------------------------

    def _open(self, view, path: str, raw: bytes,
              deadline_left: float | None = None, trace=None,
              slo_class: str | None = None, tenant: str | None = None):
        """One upstream request; returns (conn, resp) or raises OSError/
        HTTPException on connection-level failure (retryable — no bytes
        have been relayed to the client yet). ``deadline_left`` (seconds)
        bounds the socket AND is forwarded as X-Request-Deadline-S so the
        replica's engine gives up when the gateway will. ``trace`` (this
        attempt's relay span) is forwarded as the W3C traceparent, and the
        request id always rides X-Request-Id — the replica's logs/spans
        join the client's on either."""
        timeout = self.gwcfg.request_timeout_s
        headers = {
            "Content-Type": "application/json",
            "Authorization": self.headers.get("Authorization", ""),
            "X-Request-Id": self._request_id(),
        }
        # SLO class (ISSUE 8): a tenant pin from admission wins; otherwise
        # the client's own header is relayed. The header OVERRIDES the
        # payload at the replica, which is exactly what makes the pin
        # enforceable. Forwarded only when it names a known class — the
        # header-injection guard; malformed client values were already
        # 400'd in _admit_and_route before any relay.
        cls = slo_class or self.headers.get("X-SLO-Class")
        if cls in SLO_CLASS_NAMES:
            headers["X-SLO-Class"] = cls
        # Adapter pin (ISSUE 16): same precedence shape as the SLO class —
        # a tenant pin from admission wins, else the client's own header
        # is relayed. The header OVERRIDES the payload's model field at
        # the replica; an evicted/unknown name 404s there with a reason
        # (reject-don't-drop), so no validation is needed at this hop.
        adapter = getattr(self, "_adapter_pin", None) \
            or self.headers.get("X-Adapter-Name")
        if adapter:
            headers["X-Adapter-Name"] = adapter
        if tenant:
            # Tenant relay header (ISSUE 15): the admission-layer label
            # (digest or configured name — NEVER the raw bearer), so the
            # replica's engine attributes tokens/pages/device time to the
            # same identity the gateway throttles and meters under.
            headers["X-Tenant-Label"] = sanitize_label(tenant)
        if trace is not None:
            headers["traceparent"] = format_traceparent(trace.context)
        if deadline_left is not None:
            timeout = min(timeout, max(0.001, deadline_left))
            headers["X-Request-Deadline-S"] = f"{max(0.001, deadline_left):.3f}"
        # Pooled upstream hop (ISSUE 14): a kept-alive connection when one
        # is parked for this replica, else a fresh connect — exactly the
        # pre-pool behavior. A mid-request failure discards the connection
        # (closed + counted) and raises into the caller's existing retry
        # path; full-read-before-relay keeps that idempotent-safe.
        conn = self.fleet.pool.checkout(view.id, view.address, timeout)
        try:
            conn.request("POST", path, body=raw, headers=headers)
            return conn, conn.getresponse()
        except BaseException:
            self.fleet.pool.discard(conn)
            raise

    def _relay_one(self, view, path, raw, stream, hedge_peers,
                   deadline_left: float | None = None, span=None, root=None,
                   slo_class: str | None = None, tenant: str | None = None):
        """Proxy one attempt. Returns (outcome, info):
        ``("done", served_replica_id)`` — response relayed;
        ``("retry", None)`` — connection-level failure, safe to fail over;
        ``("busy", (retry_after, busy_replica_id))`` — a replica said
        429/503 (spill; under hedging the busy answer can come from the
        peer rather than the primary);
        ``("aborted", None)`` — died mid-stream after bytes were relayed.
        ``span`` is this attempt's relay span (its context rides upstream);
        ``root`` is the request span hedged secondaries chain under as
        SIBLINGS of this attempt."""
        # Chaos seam: `error` = an upstream connection failure before any
        # byte moved (exercises idempotent-safe failover), `delay` = a slow
        # relay (hedging drills), `kill` = losing the gateway process.
        fault = maybe_inject("gateway.relay", handles=("error",))
        if fault is not None and fault.action == "error":
            if span is not None:
                span.annotate(injected_fault=True)
            self.fleet.note_failure(view.id)
            return ("retry", None)
        served = view.id
        try:
            if hedge_peers:
                conn, resp, served = self._hedged_open(
                    view, hedge_peers, path, raw, deadline_left,
                    span=span, root=root, slo_class=slo_class,
                    tenant=tenant,
                )
            else:
                conn, resp = self._open(view, path, raw, deadline_left,
                                        trace=span, slo_class=slo_class,
                                        tenant=tenant)
        except (OSError, http.client.HTTPException) as e:
            if not isinstance(e, _HedgeQueueTimeout):
                # A queue timeout is gateway-local backlog; blaming the
                # replica would feed the supervisor's fail_threshold.
                self.fleet.note_failure(view.id)
            return ("retry", None)
        # The winning connection belongs to whichever replica SERVED (under
        # hedging that can be the peer); check it back into the pool only
        # when its response was fully drained and the upstream didn't ask
        # to close — everything else (SSE relays, torn reads) is a counted
        # discard (ISSUE 14).
        reusable = False
        try:
            if resp.status in (429, 503):
                try:
                    hint = int(resp.getheader("Retry-After") or 1)
                except ValueError:
                    hint = 1
                resp.read()
                reusable = True
                return ("busy", (hint, served))
            ctype = resp.getheader("Content-Type", "application/json")
            if stream and ctype.startswith("text/event-stream"):
                # SSE responses are close-delimited (the replica sends
                # Connection: close by design); never pooled.
                out = self._relay_stream(view, resp, ctype)
                if out == "detached":
                    # Evloop data plane (ISSUE 17): the loop takes the
                    # upstream socket — the finally below must NOT
                    # discard the live connection; the loop discards it
                    # (counted, as on the threaded path) at stream end.
                    self._evloop_detached.update(
                        conn=conn, served=served, rspan=span, handler=self,
                    )
                    conn = None
                return (out, served)
            try:
                data = resp.read()
            except (OSError, http.client.HTTPException):
                # Full response never arrived: nothing relayed, retryable.
                self.fleet.note_failure(view.id)
                return ("retry", None)
            reusable = True
            self.send_response(resp.status)
            self.send_header("Content-Type", ctype)
            self.send_header("X-Request-Id", self._request_id())
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return ("done", served)
        finally:
            if conn is None:
                pass  # detached: the event loop owns the socket now
            elif reusable:
                self.fleet.pool.checkin(served, conn, response=resp)
            else:
                self.fleet.pool.discard(conn)

    def _relay_stream(self, view, resp, ctype) -> str:
        """SSE pass-through: relay chunks as they arrive (read1 returns
        whatever the socket holds, preserving incremental delivery). The
        FIRST upstream chunk is read before any header goes to the client,
        so a replica dying at stream start is still retryable — once our
        200 is out, a death can only abort."""
        try:
            first = resp.read1(65536)
        except (OSError, http.client.HTTPException):
            self.fleet.note_failure(view.id)
            return "retry"
        self.send_response(resp.status)
        self.send_header("Content-Type", ctype)
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Cache-Control", "no-cache")
        # The relayed SSE body is close-delimited (no Content-Length), so
        # the client connection cannot be kept alive — same opt-out the
        # replica's own SSE responses make (ISSUE 14).
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            chunk = first
            while chunk:
                self.wfile.write(chunk)
                self.wfile.flush()
                chunk = resp.read1(65536)
            return "done"
        except (OSError, http.client.HTTPException):
            self.fleet.note_failure(view.id)
            logger.warning("replica %s died mid-stream", view.id)
            return "aborted"

    def _hedged_open(self, view, peers, path, raw, deadline_left=None,
                     span=None, root=None, slo_class=None, tenant=None):
        """Tail-latency hedging (non-streaming only): if the primary has
        not answered within ``hedge_after_s``, fire the same request at the
        least-loaded peer and take whichever responds first. The loser's
        connection is abandoned (its replica finishes the wasted work —
        the standard hedging trade; a propagated deadline caps even that
        waste). Completions are idempotent from the client's perspective,
        so duplicates are safe. A fired hedge gets its OWN relay span as a
        SIBLING of the primary attempt's (both children of ``root``) — the
        trace shows two overlapping relays and which one won. Runs on the
        gateway's persistent hedge executor (ISSUE 14 satellite): no more
        2-worker pool construction per hedged relay."""
        pool = self.server._hedge_pool
        hspan = None
        try:
            t0 = time.monotonic()
            primary = pool.submit(self._open, view, path, raw, deadline_left,
                                  span, slo_class, tenant)
            done, _ = wait([primary], timeout=self.gwcfg.hedge_after_s)
            if done:
                conn, resp = primary.result()  # may raise: caller retries
                return conn, resp, view.id
            if not primary.running() and not primary.done():
                # Executor saturated: the primary never STARTED, so the
                # elapsed hedge_after_s measured queue depth, not a slow
                # replica — firing a secondary would queue behind the same
                # backlog and double the load exactly when workers are
                # short (and count a hedge that never was). Wait the
                # primary out instead, BOUNDED by the request's remaining
                # deadline (else the gateway's own timeout): a queued
                # future has no socket timeout protecting it yet, and a
                # deadline_s=5 request must not sit tens of seconds in an
                # executor queue before its first connect.
                left = (
                    deadline_left - (time.monotonic() - t0)
                    if deadline_left is not None
                    else self.gwcfg.request_timeout_s
                )
                try:
                    conn, resp = primary.result(
                        timeout=max(0.001, left))
                except FutureTimeoutError:
                    # Give up on this attempt; if the open starts later
                    # anyway, its connection is abandoned through the
                    # pool's accounting. Raise the caller's retryable
                    # error class.
                    primary.cancel()
                    primary.add_done_callback(
                        self._abandoned_conn_closer())
                    raise _HedgeQueueTimeout(
                        "hedge executor saturated; relay attempt timed "
                        "out before its upstream open could start"
                    ) from None
                return conn, resp, view.id
            peer = min(peers, key=lambda v: v.outstanding + v.queue_depth)
            self.gw.hedges.inc()
            self.gw.replica_counter(peer.id, "hedged").inc()
            if root is not None:
                hspan = self.tracer.start_span(
                    "gateway.relay", parent=root, replica=peer.id,
                    hedge=True,
                )
            # The secondary starts hedge_after_s (at least) into the budget:
            # re-derive its remaining deadline, or its replica keeps the
            # hedged generation alive past the moment the gateway gives up.
            secondary_left = (
                deadline_left - (time.monotonic() - t0)
                if deadline_left is not None else None
            )
            secondary = pool.submit(self._open, peer, path, raw,
                                    secondary_left, hspan, slo_class, tenant)
            futures = {primary: view.id, secondary: peer.id}
            last_exc: BaseException | None = None
            pending = set(futures)
            while pending:
                done, pending = wait(pending, return_when=FIRST_COMPLETED)
                for f in done:
                    try:
                        conn, resp = f.result()
                    except BaseException as e:
                        last_exc = e
                        continue
                    # Abandon every loser: the still-pending future AND any
                    # that completed in the same wake-up (both can land in
                    # `done` at once — its connection must close too, not
                    # leak an FD per hedge). Losers go through the pool's
                    # discard so the churn counter stays honest (a loser
                    # was counted at checkout; its close must be counted
                    # too, troubleshooting §32 reads the ratio).
                    abandon = self._abandoned_conn_closer()
                    for other in done | pending:
                        if other is not f:
                            other.add_done_callback(abandon)
                    if hspan is not None:
                        hspan.end(outcome=(
                            "won" if futures[f] == peer.id else "lost"
                        ))
                    return conn, resp, futures[f]
            if hspan is not None:
                hspan.end(outcome="error")
            raise last_exc  # both failed
        finally:
            if hspan is not None:
                hspan.end()  # no-op when already ended with an outcome

    def _abandoned_conn_closer(self):
        """Done-callback that discards a hedge loser's connection through
        the pool (mid-flight — never reusable, always counted)."""
        pool = self.fleet.pool

        def _closer(future) -> None:
            try:
                conn, _resp = future.result()
            except BaseException:
                return  # the losing open failed; _open already discarded
            pool.discard(conn)

        return _closer

    def _note_affinity(self, key, replica_id: str) -> None:
        if key is None:
            return
        with self.affinity_lock:
            prev = self.affinity_last.get(key)
            if prev is not None:
                if prev == replica_id:
                    self.gw.affinity_hits.inc()
                else:
                    self.gw.affinity_misses.inc()
            self.affinity_last[key] = replica_id
            self.affinity_last.move_to_end(key)
            while len(self.affinity_last) > 4096:
                self.affinity_last.popitem(last=False)


class _EvloopGatewayHandler(_GatewayHandler):
    """The handler the event-loop data plane (gateway/evloop.py, ISSUE 17)
    runs on its offload workers: identical control plane, one override —
    an SSE relay reads its FIRST upstream chunk here (preserving the
    retry-on-dead-start contract), then DETACHES instead of looping: the
    event loop takes both raw sockets and fans chunks through without
    this worker parked for the stream's lifetime. ``_evloop_detached``
    carries the deferred terminal state (span ends, admission release,
    usage row, pool discard) the loop runs at stream end."""

    # Per-request detach state. The loop builds one handler instance per
    # request (gateway/evloop.py _run_handler), so instance state here is
    # exactly as private as _rid/_adapter_pin on the threaded path.
    _evloop_detached: dict | None = None

    def _relay_stream(self, view, resp, ctype) -> str:
        # First chunk on the worker, blocking — a replica dying at stream
        # start stays retryable, exactly like the threaded path. This
        # read also drains http.client's internal BufferedReader (8 KiB,
        # < the 64 KiB ask), so after detach the raw socket is the only
        # byte source left (evloop.py re-checks for residue anyway).
        try:
            first = resp.read1(65536)
        except (OSError, http.client.HTTPException):
            self.fleet.note_failure(view.id)
            return "retry"
        self.send_response(resp.status)
        self.send_header("Content-Type", ctype)
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Cache-Control", "no-cache")
        # Close-delimited, as on the threaded path (ISSUE 14).
        self.send_header("Connection", "close")
        self.end_headers()
        if not first:
            # Upstream closed with an empty body: headers-only relay,
            # terminal here (threaded parity) — nothing to detach.
            return "done"
        self.wfile.write(first)
        self.close_connection = True
        self._evloop_detached = {"view": view, "resp": resp}
        return "detached"


def make_gateway(
    fleet: Fleet,
    *,
    config: GatewayConfig | None = None,
    router=None,
    admission: TenantAdmission | None = None,
    metrics: GatewayMetrics | None = None,
    host: str | None = None,
    port: int | None = None,
    tracer: Tracer | None = None,
    slo: BurnRateMonitor | None = None,
    telemetry=None,
    incidents=None,
    flight=None,
    actuator=None,
    recorder=None,
    kvtier=None,
    journal=None,
    usage=None,
    bulk=None,
    recover_manifest=None,
):
    """Build (not start) the gateway server over ``fleet`` — tests drive it
    on a thread, ``main`` drives it with ``serve_forever``. ``router``
    defaults to the config's policy; ``admission`` defaults to the config's
    tenant budgets (None when the config sets no limits — requests are then
    admitted unconditionally). ``tracer`` (telemetry/tracing.py) arms
    request tracing; ``slo`` defaults to a fleet-level burn-rate monitor
    built from ``telemetry`` (config.TelemetryConfig) or its defaults;
    ``incidents`` (telemetry/incident.IncidentManager) arms the
    /incidents aggregation endpoint and ``flight``
    (telemetry/flight.FlightRecorder) the per-request routing ring
    (ISSUE 10) — both unarmed by default. ``actuator``
    (gateway.autoscale.Actuator) arms the /actions endpoint and the
    scale-to-zero wake admission; ``recorder``
    (gateway.autoscale.TrafficRecorder) appends one JSONL row per
    admitted request (ISSUE 12) — both unarmed by default. ``kvtier``
    (config.KVTierConfig with ``handoff=True``) arms the prefill->decode
    KV handoff orchestration (ISSUE 13); ``journal``
    (telemetry/journal.EventJournal) records its per-request cost-model
    decisions. ``usage`` (telemetry/usage.UsageLedger) arms the
    gateway-edge usage ledger: one row per admission-controlled request
    with the tenant digest, class, and terminal outcome (ISSUE 15) —
    unarmed by default. ``bulk`` (gateway.bulk.BulkJobManager) arms the
    /v1/bulk/jobs endpoints (ISSUE 19): make_gateway binds the manager's
    dispatch path to this gateway's relay (pinned ``best_effort``, stable
    per-item request ids so retries ride the idempotent-safe relay) plus
    an idle-fleet probe for the backlog-stall detector, and calls
    ``start()`` so incomplete jobs resume before the first request
    lands. ``config.data_plane`` picks the transport
    (ISSUE 17): the selectors event loop (gateway/evloop.py, the
    default) or the legacy thread-per-connection ``GatewayHTTPServer`` —
    both expose the same serve_forever/shutdown/server_close/
    server_address surface, so callers never branch.
    ``recover_manifest`` (a dict from recovery.load_manifest, ISSUE 20)
    marks this gateway a --recover incarnation: admission token buckets
    re-warm from the manifest's persisted levels (amnesty counted when
    absent) and adapter generations reconcile against each replica's
    live GET /v1/adapters — both BEFORE the bulk manager resumes, so
    resumed jobs meet re-warmed budgets."""
    config = config or GatewayConfig()
    # Upstream keep-alive pool caps (ISSUE 14): the fleet owns the pool
    # (health polls and fleet-mutation invalidation need it gateway or
    # not); the gateway applies its config's knobs here.
    # pool_max_idle_per_replica=0 disables pooling — every upstream hop
    # connects fresh, the microbench's A/B leg.
    fleet.pool.configure(
        max_idle_per_replica=config.pool_max_idle_per_replica,
        max_age_s=config.pool_max_age_s,
    )
    if router is None:
        router = make_policy(config.router)
    # Bulk quotas (ISSUE 19) live in the SAME admission object as the
    # interactive budgets — one fairness layer, one per-tenant state map,
    # one snapshot at /stats. A bulk-armed gateway therefore always has
    # admission, even when the config sets no interactive limits.
    bulk_cfg = bulk.config if bulk is not None else None
    if admission is None and (
        config.tenant_rate > 0 or config.tenant_max_concurrent > 0
        or config.tenant_slo_class or bulk_cfg is not None
    ):
        admission = TenantAdmission(
            rate=config.tenant_rate, burst=config.tenant_burst,
            max_concurrent=config.tenant_max_concurrent,
            slo_class=config.tenant_slo_class,
            bulk_max_jobs=(bulk_cfg.max_jobs_per_tenant
                           if bulk_cfg is not None else 0),
            bulk_max_queued_items=(bulk_cfg.max_queued_items_per_tenant
                                   if bulk_cfg is not None else 0),
        )
    if bulk is not None and bulk.admission is None:
        # The manager releases a job's quota footprint at terminal state
        # and re-registers resumed jobs — it needs the live object.
        bulk.admission = admission
    if fleet.manifest is not None and admission is not None:
        # Crash-recovery manifest (ISSUE 20): admission bucket levels
        # ride every manifest record from here on (keyed on tenant
        # labels inside admission.bucket_snapshot — raw bearers never
        # reach the file). Re-record immediately: a crash between here
        # and the next fleet mutation / 2s supervisor refresh must find
        # an admission section (empty != absent), not the pre-wiring
        # snapshot.
        fleet.manifest.admission = admission
        fleet.manifest.record()
    gw_metrics = metrics if metrics is not None else GatewayMetrics()
    if slo is None:
        kw = telemetry.gateway_slo_kwargs() if telemetry is not None else {}
        slo = gateway_slo(gw_metrics, **kw)
    # Adapter publication coordinator (ISSUE 16): always armed — it needs
    # only the fleet; replicas without an adapter plane answer its hops
    # with 404s, which the walk reports per-replica instead of hiding.
    from ditl_tpu.gateway.publish import AdapterPublisher
    publisher = AdapterPublisher(
        fleet, journal=journal, registry=gw_metrics.registry,
        timeout_s=config.request_timeout_s, manifest=fleet.manifest,
    )
    if recover_manifest is not None:
        from ditl_tpu.gateway.recovery import reconcile_adapters

        if admission is not None:
            # Restart amnesty fix (ISSUE 20 satellite): armed before the
            # bulk manager resumes below, so even the first tenants back
            # (resumed bulk jobs re-registering quota) re-warm instead
            # of silently restarting full.
            admission.rewarm(
                recover_manifest.get("admission") or {},
                on_amnesty=gw_metrics.admission_amnesty.inc,
            )
        reconcile_adapters(fleet, recover_manifest, publisher,
                           journal=journal,
                           timeout_s=config.recovery_adopt_timeout_s)
    base = (_EvloopGatewayHandler if config.data_plane == "evloop"
            else _GatewayHandler)
    handler = type(
        "BoundGatewayHandler",
        (base,),
        {
            "fleet": fleet,
            "router": router,
            "admission": admission,
            "gw": gw_metrics,
            "gwcfg": config,
            "affinity_last": collections.OrderedDict(),
            "affinity_lock": threading.Lock(),
            "tracer": tracer if tracer is not None else NULL_TRACER,
            "slo": slo,
            "incidents": incidents,
            "flight": flight,
            "actuator": actuator,
            "recorder": recorder,
            "kvtier": kvtier,
            "journal": journal,
            "usage": usage,
            "publisher": publisher,
            "bulk": bulk,
        },
    )
    address = (host if host is not None else config.host,
               port if port is not None else config.port)
    if config.data_plane == "evloop":
        # Event-loop data plane (ISSUE 17): same bound handler (run on
        # offload workers), same 4-method server surface
        # (serve_forever/shutdown/server_close/server_address).
        from ditl_tpu.gateway.evloop import EventLoopGateway
        server = _bind_with_retry(
            lambda: EventLoopGateway(address, handler, config=config,
                                     metrics=gw_metrics),
            config)
        # Stall-attribution plane (ISSUE 18): when armed, the watchdog
        # converts heartbeat age into ditl_loop_lag_seconds and, on a
        # stall, burst-samples the loop thread into a convicting stack
        # fed to the anomaly->incident path. Disarmed by default
        # (loop_stall_threshold_s == 0): zero extra threads.
        if telemetry is not None and telemetry.loop_stall_threshold_s > 0:
            from ditl_tpu.telemetry.anomaly import AnomalyPlane
            from ditl_tpu.telemetry.prof import LoopWatchdog
            server.watchdog = LoopWatchdog(
                server.heartbeat,
                registry=gw_metrics.registry,
                plane=AnomalyPlane(incidents=incidents, journal=journal),
                journal=journal,
                source="gateway",
                **telemetry.watchdog_kwargs(),
            )
        if telemetry is not None and telemetry.prof_hz > 0:
            from ditl_tpu.telemetry.prof import SamplingProfiler
            server.profiler = SamplingProfiler(
                hz=telemetry.prof_hz,
                max_stacks=telemetry.prof_max_stacks,
                registry=gw_metrics.registry,
            )
            server.profiler.start()
    else:
        server = _bind_with_retry(
            lambda: GatewayHTTPServer(address, handler), config)
    if bulk is not None:
        _bind_bulk(bulk, server, handler, fleet)
    return server


def _bind_with_retry(build, config):
    """Construct a data-plane server, retrying a bounded number of
    EADDRINUSE bind failures (ISSUE 20 fast-restart satellite): a
    recovering gateway reclaims its predecessor's FIXED port while
    kernel TIME_WAIT entries from severed connections linger. Both
    planes set SO_REUSEADDR on their listeners (which clears ordinary
    TIME_WAIT) and tear down cleanly on a failed construction, so
    re-invoking ``build`` is always safe. Any other OSError — and
    EADDRINUSE past the budget — propagates unchanged."""
    import errno

    attempts = max(0, int(config.recovery_bind_retries))
    for remaining in range(attempts, -1, -1):
        try:
            return build()
        except OSError as e:
            if e.errno != errno.EADDRINUSE or remaining == 0:
                raise
            logger.warning(
                "gateway bind EADDRINUSE; retrying in %.1fs "
                "(%d attempts left)",
                config.recovery_bind_wait_s, remaining)
            time.sleep(config.recovery_bind_wait_s)
    raise AssertionError("unreachable")


def _bind_bulk(bulk, server, handler_cls, fleet) -> None:
    """Wire a BulkJobManager (ISSUE 19) to THIS gateway: its dispatch
    path becomes a pseudo-handler run of ``_route_and_relay`` — the
    evloop offload idiom, so bulk items traverse the IDENTICAL routing/
    retry/hedging/KV-handoff/usage machinery a socket request would,
    pinned ``best_effort`` with a stable per-item request id (replica-
    death retries ride the idempotent-safe relay). Also binds the
    idle-fleet probe the backlog-stall detector needs, then starts the
    manager (resuming any incomplete journaled jobs)."""
    import io

    def dispatch(item: dict) -> dict:
        h = handler_cls.__new__(handler_cls)
        h.server = server
        h.client_address = ("bulk", 0)
        h.connection = None
        h.request = None
        h.rfile = io.BytesIO(b"")
        h.wfile = io.BytesIO()
        h.close_connection = True
        h.requestline = "POST /v1/completions HTTP/1.1"
        h.request_version = "HTTP/1.1"
        h.command = "POST"
        h.path = "/v1/completions"
        h.headers = {}
        # Stable id: the SAME item re-dispatched (outer retry, or resume
        # after a kill) carries the same X-Request-Id — the join key
        # across gateway spans, replica logs, and the bulk journal.
        h._rid = str(item.get("rid") or "")
        h._adapter_pin = item.get("adapter") or None
        payload = {
            "prompt": item.get("prompt") or "",
            "max_tokens": int(item.get("max_new") or 0),
            "stream": False,
            **dict(item.get("sampling") or {}),
        }
        if not payload["max_tokens"]:
            del payload["max_tokens"]
        raw = json.dumps(payload).encode()
        try:
            outcome = h._route_and_relay(
                "/v1/completions", payload, raw, record=True,
                slo_class="best_effort",
                tenant=item.get("tenant") or "anonymous",
            )
        except Exception:  # noqa: BLE001 - a relay bug reads as transient
            logger.exception("bulk: pseudo-handler relay failed")
            return {"outcome": "error"}
        resp = h.wfile.getvalue()
        head, _, body = resp.partition(b"\r\n\r\n")
        out: dict = {"outcome": str(outcome), "text": "",
                     "completion_tokens": 0}
        if outcome == "200":
            try:
                ans = json.loads(body)
                choice = (ans.get("choices") or [{}])[0]
                out["text"] = str(choice.get("text") or "")
                out["completion_tokens"] = int(
                    (ans.get("usage") or {}).get("completion_tokens") or 0)
            except (ValueError, AttributeError, IndexError, TypeError):
                out["outcome"] = "error"
        elif outcome == "429":
            m = re.search(rb"(?im)^Retry-After:\s*(\d+)", head)
            if m:
                out["retry_after_s"] = float(m.group(1))
        return out

    def idle_fn() -> bool:
        views = [v for v in fleet.views() if v.live]
        return bool(views) and all(
            v.active_slots == 0 and v.queue_depth == 0
            and v.outstanding == 0 for v in views)

    bulk.bind(dispatch, idle_fn=idle_fn)
    bulk.start()


def main(argv: list[str] | None = None) -> int:
    """``python -m ditl_tpu.launch gateway``: spawn N subprocess replicas
    of ``infer/server.py`` and front them with one gateway endpoint."""
    import argparse
    import signal
    import sys

    from ditl_tpu.config import Config, parse_overrides
    from ditl_tpu.gateway.replica import (
        SubprocessReplica, gateway_journal_path,
    )
    from ditl_tpu.telemetry.journal import EventJournal

    parser = argparse.ArgumentParser(prog="ditl_tpu.launch gateway")
    parser.add_argument("--preset", default=None,
                        help="model preset for every replica")
    parser.add_argument("--tokenizer", default="byte")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--engine", choices=("lockstep", "continuous"),
                        default="continuous")
    parser.add_argument("--slots", type=int, default=8,
                        help="decode slots per replica (continuous engine); "
                        "the BASE value role knobs scale (gateway/roles.py)")
    parser.add_argument("--max-queue", type=int, default=32,
                        help="per-replica admission queue cap (replica "
                        "429s beyond it; the gateway spills/429s in turn)")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        help="base chunked-prefill size per replica "
                        "(continuous engine; 0 = whole-prompt) — "
                        "role-scaled for heterogeneous fleets")
    parser.add_argument("--token-budget", type=int, default=0,
                        help="base per-tick token budget per replica "
                        "(continuous engine; 0 = unbudgeted) — role-scaled "
                        "for heterogeneous fleets")
    parser.add_argument("--pages", type=int, default=0,
                        help="base KV page-pool size per replica (paged "
                        "cache mode; 0 = engine default) — role-scaled "
                        "for heterogeneous fleets")
    parser.add_argument("--replica-arg", action="append", default=[],
                        metavar="ARG",
                        help="extra argument passed through to every "
                        "ditl_tpu.infer.server replica (repeatable), e.g. "
                        "--replica-arg=--cache-mode --replica-arg=paged")
    parser.add_argument("--trace-dir", default="",
                        help="arm end-to-end request tracing (ISSUE 6): "
                        "the gateway AND every replica journal their spans "
                        "into this directory; merge + export with "
                        "python -m ditl_tpu.telemetry.trace_export --dir "
                        "DIR")
    parser.add_argument("--incident-dir", default="",
                        help="arm the anomaly/incident plane fleet-wide "
                        "(ISSUE 10): the gateway watches replica deaths "
                        "and spill/relay-error storms, each replica "
                        "watches its own engine (deadline/429 storms, "
                        "latency jumps), and all bundles aggregate at the "
                        "gateway's /incidents (each process writes its own "
                        "subdirectory)")
    parser.add_argument("--save-trace", default="", metavar="PATH",
                        help="traffic recorder (ISSUE 12): append one "
                        "JSONL row per admitted request (arrival offset, "
                        "tenant digest, class, prompt/max_new token "
                        "estimates) — the shape bench.py "
                        "--serve-trace-replay replays")
    parser.add_argument("--recover", default="", metavar="DIR",
                        help="crash recovery (ISSUE 20): adopt the fleet a "
                        "SIGKILLed gateway left behind from DIR's "
                        "gateway-manifest.json — still-alive replicas are "
                        "adopted (zero restarts), parked/quarantined state "
                        "is restored, planner cooldowns replay from the "
                        "journal tail, admission buckets re-warm, adapter "
                        "generations reconcile, and journaled bulk jobs "
                        "resume. DIR doubles as gateway.journal_dir when "
                        "that is unset. A missing manifest cold-starts "
                        "with a warning")
    parser.add_argument("overrides", nargs="*",
                        help="config overrides like gateway.router=affinity "
                        "gateway.replicas=4 telemetry.slo_ttft_s=0.5 "
                        "autoscale.enabled=true")
    args = parser.parse_args(argv)

    full_config = parse_overrides(
        Config(),
        [o for o in args.overrides
         if o.startswith(("gateway.", "telemetry.", "autoscale.",
                          "kvtier.", "usage.", "bulk."))],
    )
    config = full_config.gateway
    telemetry_cfg = full_config.telemetry
    autoscale_cfg = full_config.autoscale
    kvtier_cfg = full_config.kvtier
    usage_cfg = full_config.usage
    bulk_cfg = full_config.bulk

    from ditl_tpu.gateway.roles import parse_roles, role_knobs

    roles = parse_roles(config.replica_roles, config.replicas)

    def make_build_argv(replica_id: str, role: str):
        # One closure per replica: the role's engine knobs (roles.py) are
        # derived from the BASE --slots/--prefill-chunk/--token-budget so a
        # heterogeneous fleet launches from one command line.
        knobs = role_knobs(role, n_slots=args.slots,
                           prefill_chunk=args.prefill_chunk,
                           token_budget=args.token_budget)

        def build_argv(port: int):
            cmd = [sys.executable, "-m", "ditl_tpu.infer.server",
                   "--host", "127.0.0.1", "--port", str(port),
                   "--tokenizer", args.tokenizer,
                   "--engine", args.engine,
                   "--role", role]
            if args.engine == "continuous":
                cmd += ["--slots", str(knobs["n_slots"]),
                        "--max-queue", str(args.max_queue)]
                if knobs["prefill_chunk"]:
                    cmd += ["--prefill-chunk", str(knobs["prefill_chunk"])]
                if knobs["token_budget"]:
                    cmd += ["--token-budget", str(knobs["token_budget"])]
                if args.pages:
                    # --pages is sized for the BASE slot count: scale it by
                    # the role's slot ratio first (a decode_heavy replica
                    # running 2x the slots needs 2x the pool just to keep
                    # per-slot headroom), THEN by the role's extra depth
                    # (pages_scale) — the same slot-derived-then-scaled
                    # sizing bench.py uses.
                    scaled = (args.pages * knobs["n_slots"]
                              / max(1, args.slots) * knobs["pages_scale"])
                    cmd += ["--pages", str(max(2, int(scaled)))]
            if args.engine == "continuous" and kvtier_cfg.host_tier_mb:
                # Requires paged replicas (--replica-arg=--cache-mode
                # --replica-arg=paged); a mismatch fails the replica
                # launch loudly rather than silently serving tierless.
                cmd += ["--host-tier-mb", str(kvtier_cfg.host_tier_mb),
                        "--spill-max-pages-per-tick",
                        str(kvtier_cfg.spill_max_pages_per_tick)]
            if args.engine == "continuous" and kvtier_cfg.handoff:
                cmd += ["--kv-handoff"]
            if args.preset:
                cmd += ["--preset", args.preset]
            if args.checkpoint_dir:
                cmd += ["--checkpoint-dir", args.checkpoint_dir]
            if args.trace_dir:
                # Each replica journals its own spans (events-server-<pid>)
                # into the shared directory; trace_export merges by
                # trace_id.
                cmd += ["--trace-dir", args.trace_dir]
            if args.incident_dir:
                # Per-replica bundle subdirectory: managers never contend
                # on bundle names, and the gateway's /incidents aggregation
                # reads each replica's listing over HTTP anyway.
                import os as _os

                cmd += ["--incident-dir",
                        _os.path.join(args.incident_dir, replica_id)]
            if usage_cfg.ledger_dir:
                # Per-replica ledger subdirectory (ISSUE 15): each process
                # appends its own usage-*.jsonl; the aggregator CLI reads
                # any of them, the gateway's /usage fan-out reads the live
                # meters over HTTP.
                import os as _os

                cmd += ["--usage-dir",
                        _os.path.join(usage_cfg.ledger_dir, replica_id)]
            if not usage_cfg.metering:
                cmd += ["--no-usage-metering"]
            for field_name in ("max_tenant_families", "conviction_share",
                               "conviction_min_tokens"):
                cmd += ["--usage-override",
                        f"{field_name}={getattr(usage_cfg, field_name)}"]
            return cmd + list(args.replica_arg)

        return build_argv

    # The recovery state directory doubles as the journal directory: the
    # manifest, the action journal tail, and the crash/recovery events
    # must all live where the NEXT incarnation's --recover will look.
    journal_dir = config.journal_dir or args.recover
    journal = None
    if journal_dir:
        journal = EventJournal(
            gateway_journal_path(journal_dir), source="gateway",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        )
    tracer = None
    if args.trace_dir:
        import os as _os

        tracer = Tracer(EventJournal(
            _os.path.join(args.trace_dir, "events-gateway-trace.jsonl"),
            source="gateway",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        ))
    handles = [
        SubprocessReplica(f"r{i}", make_build_argv(f"r{i}", roles[i]),
                          role=roles[i])
        for i in range(config.replicas)
    ]
    fleet = Fleet(handles)
    # Crash-recovery manifest (ISSUE 20): armed whenever a journal
    # directory exists — crash consistency costs one small atomic JSON
    # write per fleet mutation. The PRIOR incarnation's manifest (if
    # --recover) is loaded before this incarnation's first record can
    # replace it.
    prior_manifest = None
    if journal_dir:
        from ditl_tpu.gateway.recovery import FleetManifest, load_manifest
        from ditl_tpu.gateway.recovery import manifest_path as _mpath

        if args.recover:
            prior_manifest = load_manifest(args.recover)
            if prior_manifest is None:
                logger.warning(
                    "--recover %s: no fleet manifest found; cold-starting",
                    args.recover)
        fleet.manifest = FleetManifest(_mpath(journal_dir))
    # Gateway-side anomaly/incident plane (ISSUE 10): replica death-rate +
    # spill/relay-error storms + fleet SLO burn alerts, bundling the
    # routing flight ring, gateway metrics, and the journal tail. The
    # metrics bundle exists regardless (the supervisor's replica_deaths
    # counter must be honest on unarmed gateways too); only the
    # detectors/bundles gate on --incident-dir.
    gw_metrics = GatewayMetrics()
    flight = incidents = slo = gw_anomaly = plane = None
    if args.incident_dir:
        import os as _os

        from ditl_tpu.telemetry import (
            AnomalyPlane, FlightRecorder, GatewayDetector,
            GatewayAnomalyMonitor, IncidentManager,
        )

        flight = FlightRecorder(telemetry_cfg.flight_ring_size)
        plane_journal = journal if journal is not None else (
            tracer.journal if tracer is not None else None
        )
        incidents = IncidentManager(
            _os.path.join(args.incident_dir, "gateway"),
            flight=flight,
            metrics_render=gw_metrics.registry.render,
            journal_dir=journal_dir or args.trace_dir,
            registry=gw_metrics.registry,
            source="gateway",
            **telemetry_cfg.incident_kwargs(),
        )
        plane = AnomalyPlane(incidents=incidents, journal=plane_journal)
        slo = gateway_slo(
            gw_metrics, **telemetry_cfg.gateway_slo_kwargs(),
            journal=plane_journal, on_alert=plane.on_slo_alert,
        )
        gw_anomaly = GatewayAnomalyMonitor(
            plane, gw_metrics,
            GatewayDetector(
                storm_threshold=telemetry_cfg.anomaly_storm_threshold),
            slo=slo, flight=flight,
        )
    recorder = None
    if args.save_trace:
        from ditl_tpu.gateway.autoscale import TrafficRecorder

        recorder = TrafficRecorder(args.save_trace)
    usage_ledger = None
    if usage_cfg.ledger_dir:
        from ditl_tpu.telemetry.usage import UsageLedger, usage_ledger_path

        usage_ledger = UsageLedger(
            usage_ledger_path(usage_cfg.ledger_dir, "gateway"),
            source="gateway",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        )
    bulk_manager = None
    if bulk_cfg.dir:
        # Offline bulk lane (ISSUE 19): the manager is built here (durable
        # state + journal) and wired to the relay inside make_gateway,
        # which also resumes any jobs a previous incarnation left
        # incomplete.
        from ditl_tpu.gateway.bulk import BulkJobManager

        bulk_manager = BulkJobManager(
            bulk_cfg.dir, bulk_cfg,
            registry=gw_metrics.registry,
            flight=flight, plane=plane, usage=usage_ledger,
            source="gateway",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        )
    supervisor = None
    server = None
    # One finally covers startup too: a replica that never turns healthy
    # (bad --preset, broken checkpoint) raises out of start_all, and the
    # other N-1 subprocess replicas must not be left orphaned holding
    # ports and devices.
    try:
        if prior_manifest is not None:
            # Adopt-or-relaunch BEFORE start_all: adopted replicas are
            # already alive and parked/quarantined replicas are restored
            # down-on-purpose, so start_all only launches what genuinely
            # needs launching.
            from ditl_tpu.gateway.recovery import recover_fleet

            recover_fleet(
                fleet, prior_manifest, journal=journal,
                metrics=gw_metrics,
                probe_timeout_s=config.recovery_adopt_timeout_s,
            )
            fleet.manifest.seed_adapters(prior_manifest.get("adapters"))
        logger.info("starting %d replica(s)...", config.replicas)
        fleet.start_all(wait_healthy_s=config.restart_timeout_s)
        supervisor = FleetSupervisor(
            fleet,
            interval_s=config.health_interval_s,
            fail_threshold=config.fail_threshold,
            probe_timeout_s=config.probe_timeout_s,
            restart_timeout_s=config.restart_timeout_s,
            journal=journal,
            anomaly=gw_anomaly,
            metrics=gw_metrics,
        )
        actuator = None
        if autoscale_cfg.enabled:
            # Actuation plane (ISSUE 12): planner + actuator riding the
            # supervisor's poll loop, sharing its fleet-mutation lock,
            # journal, and — when --incident-dir armed one — the SAME
            # anomaly plane the detectors feed, so action bundles and
            # organic bundles land in one tally and one directory.
            from ditl_tpu.gateway.autoscale import Actuator

            actuator = Actuator(
                fleet, supervisor, autoscale_cfg,
                journal=journal, tracer=tracer, metrics=gw_metrics,
                flight=flight, plane=plane, slo=slo, bulk=bulk_manager,
            )
            supervisor.autoscaler = actuator
            if prior_manifest is not None and journal_dir:
                # Cooldown replay (ISSUE 20): re-stamp the planner's
                # scale/remediation recency from the action.executed
                # tail so the recovered gateway does not immediately
                # re-plan inside a window the old incarnation opened.
                from ditl_tpu.gateway.recovery import replay_action_tail

                replay_action_tail(journal_dir, actuator.planner,
                                   journal=journal)
        supervisor.start()
        server = make_gateway(fleet, config=config, tracer=tracer,
                              telemetry=telemetry_cfg, metrics=gw_metrics,
                              slo=slo, incidents=incidents, flight=flight,
                              actuator=actuator, recorder=recorder,
                              kvtier=kvtier_cfg if kvtier_cfg.handoff
                              else None,
                              journal=journal, usage=usage_ledger,
                              bulk=bulk_manager,
                              recover_manifest=prior_manifest)
        stopping = threading.Event()

        def _shutdown(signum, frame):
            if not stopping.is_set():
                stopping.set()
                threading.Thread(target=server.shutdown, daemon=True).start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _shutdown)
            except ValueError:
                pass
        logger.info(
            "gateway serving %d replica(s) on %s:%d (router=%s)",
            config.replicas, *server.server_address[:2], config.router,
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
    finally:
        if supervisor is not None:
            supervisor.stop()
        if server is not None:
            server.server_close()
        fleet.stop_all(drain=True, timeout=config.drain_timeout_s)
        if bulk_manager is not None:
            # In-flight items are abandoned WITHOUT terminal rows; jobs
            # stay "running" on disk — the next gateway resumes them.
            bulk_manager.close()
        if recorder is not None:
            recorder.close()
        if usage_ledger is not None:
            usage_ledger.close()
        if journal is not None:
            journal.close()
        if tracer is not None and tracer.journal is not None:
            tracer.journal.close()
    return 0


if __name__ == "__main__":
    import sys

    from ditl_tpu.utils.logging import setup_logging

    setup_logging()
    sys.exit(main())
