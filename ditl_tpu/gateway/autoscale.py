"""Actuation plane: demand-driven autoscaling + detector-triggered
remediation (ISSUE 12 tentpole).

Every observability layer before this one *watches* — metrics, traces,
flight rings, anomaly detectors, incident bundles all terminate at a
human. This module closes the loop from signals to actions: an
:class:`ActionPlanner` turns the signals the fleet already produces
(``ReplicaView.slot_pressure``, queue depth, windowed
``recent_cache_hit_ratio``, health-polled TPOT p95s, SLO burn state,
supervisor death notes) into typed :class:`Action` records, and an
:class:`Actuator` executes them through the existing FleetSupervisor
primitives (drain / stop / start / await-healthy) under the one
fleet-mutation lock that crash recovery and rolling restarts already
hold — a scale event can no longer race a relaunch.

The observability spine is the point, not a side effect. Every action —
planned, executed, refused, failed, or dry-run — is:

- **journaled** as ``action.*`` events carrying the triggering signal
  snapshot inline (``events-gateway.jsonl``; the flapping-guard drill pins
  the causal order ``action.signal -> action.planned -> action.executed``);
- **flight-recorded** into the ACTION ring (telemetry/flight.py), so an
  incident bundle dumps the last few hundred actions next to the routing
  decisions they reshaped;
- **span-traced** as ``gateway.action`` on the existing trace layer;
- **counted** per action-kind/outcome on the gateway's /metrics
  (``ditl_gateway_action_<kind>_<outcome>_total``);
- **listable** at the gateway's ``/actions`` endpoint (bounded in-memory
  log, each entry cross-linked to its incident bundle when one fired);
- **incident-bundled** for executed remediation and failed actions via the
  PR 10 IncidentManager — a bad remediation leaves the same forensic trail
  as an organic failure, chaos attribution included.

Action taxonomy:

- ``scale_up`` / ``scale_down`` — demand scaling between
  ``autoscale.min_replicas`` and the launched pool, with hysteresis
  (asymmetric: fast up, slow down) and a post-execute cooldown so an
  oscillating load cannot oscillate the fleet. Scale-down parks the
  replica (``deactivated``): drained, stopped, excluded from routing and
  from supervisor recovery; the affinity ring's consistent hashing
  guarantees only the parked replica's keys remap (router.py). Scale-to-
  zero is the same action below the floor, armed separately, and demand
  arriving against an empty fleet answers 429 with a wake-up budget
  derived from the MEASURED replica cold start (time-to-first-ready
  stamped on /health) while a wake is planned.
- ``drain`` — TPOT-storm remediation: the live replica whose health-polled
  TPOT p95 stands ``tpot_storm_factor`` x above its peers' median (and
  above the absolute ``tpot_storm_min_s`` floor) is drained, restarted,
  and re-admitted — the targeted version of a rolling-restart leg.
- ``quarantine`` — death-storm remediation: a replica that died
  ``quarantine_deaths`` times inside ``quarantine_window_s`` is stopped
  and excluded from supervision, breaking the crash loop the supervisor's
  relaunch budget would otherwise bleed out on.

Also here (ISSUE 12 satellites): the :class:`TrafficRecorder` the gateway
arms with ``--save-trace`` (one JSONL row per admitted request — arrival
offset, tenant digest, class, prompt/max_new token estimates) and
:func:`load_trace`, the reader ``bench.py --serve-trace-replay`` drives;
and :class:`ReplicaSecondsSampler`, the replica-seconds integral the
autoscaler A/B is graded on.

Stdlib-only and jax-free like the rest of the gateway package.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import statistics
import threading
import time
from typing import Callable

from ditl_tpu.chaos import maybe_inject
from ditl_tpu.telemetry.anomaly import Anomaly
from ditl_tpu.telemetry.flight import ACTION_RING
from ditl_tpu.telemetry.tracing import NULL_TRACER
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "ACTION_KINDS",
    "Action",
    "ActionPlanner",
    "Actuator",
    "FleetSignals",
    "ReplicaSecondsSampler",
    "TrafficRecorder",
    "load_trace",
]

ACTION_KINDS = ("scale_up", "scale_down", "drain", "quarantine")
# Remediation kinds bundle on EXECUTE (a remediation is incident-worthy by
# definition); every kind bundles on FAILED.
REMEDIATION_KINDS = frozenset({"drain", "quarantine"})


@dataclasses.dataclass(frozen=True)
class Action:
    """One typed fleet action. ``signal`` is the triggering signal
    snapshot (host scalars only — journaled and bundled verbatim);
    ``allow_zero`` marks the scale paths exempt from the min_replicas
    floor (idle scale-to-zero) or from hysteresis/cooldown (wake)."""

    kind: str
    target: str
    reason: str
    signal: dict = dataclasses.field(default_factory=dict)
    ts: float = dataclasses.field(default_factory=time.time)
    allow_zero: bool = False


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """The planner's input: one coherent read of the signals the fleet
    already produces, taken once per supervision pass."""

    now: float
    views: tuple  # live, routable ReplicaViews (the pressure denominators)
    active: tuple  # replica ids participating in serving (may be down)
    parked: tuple  # scale-down-parked ids (the scale-up pool)
    quarantined: tuple
    pressure: float  # mean active_slots/capacity across live views
    queue_per_replica: float  # mean queued+outstanding per live view
    slo_alerting: bool = False
    cold_start_s: float = 0.0  # wake budget input (measured, or default)
    # Pending bulk-lane work items (ISSUE 19): a standing demand signal
    # the instantaneous pressure/queue reads cannot see — bulk dispatches
    # best_effort and is preempted first, so a busy fleet shows ZERO bulk
    # in its queues while hours of work wait in the lane.
    bulk_backlog: int = 0

    def snapshot(self) -> dict:
        """The journal/bundle form: small, flat-ish, host scalars only."""
        return {
            "pressure": round(self.pressure, 4),
            "queue_per_replica": round(self.queue_per_replica, 4),
            "live": len(self.views),
            "active": len(self.active),
            "parked": len(self.parked),
            "quarantined": len(self.quarantined),
            "slo_alerting": self.slo_alerting,
            "cold_start_s": round(self.cold_start_s, 3),
            "bulk_backlog": self.bulk_backlog,
            "tpot_p95_s": {
                v.id: round(v.tpot_p95_s, 6) for v in self.views
                if isinstance(v.tpot_p95_s, (int, float))
            },
        }


class ActionPlanner:
    """Signals -> typed actions, under hysteresis and cooldown guards.

    Pure host logic: ``plan()`` is called once per supervision pass with a
    fresh :class:`FleetSignals`; the planner keeps only the small state a
    control loop needs (streak counters, cooldown stamps, per-replica
    death windows). The ACTUATOR reports back via :meth:`note_executed` —
    cooldowns key on actions that actually happened, never on plans, so a
    refused plan cannot silently burn the window (the flapping-guard
    drill pins the journal order ``signal -> planned -> executed``).

    ``on_signal(name, snapshot)`` fires once when a hysteresis episode
    BEGINS (a pressure signal first crosses its threshold) — the causal
    head of the journal chain."""

    def __init__(self, config, *,
                 on_signal: Callable[[str, dict], None] | None = None):
        self.config = config
        self.on_signal = on_signal
        self._up_streak = 0
        self._down_streak = 0
        self._idle_since: float | None = None
        self._last_scale = float("-inf")
        self._remedy_last: dict[str, float] = {}
        # Death notes arrive on per-replica recovery threads and demand
        # notes on gateway request threads, while plan() iterates on the
        # supervisor thread — the cross-thread inputs take this lock (the
        # rest of the planner state is supervisor-thread-only).
        self._lock = threading.Lock()
        self._deaths: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._wake_pending = False  # guarded-by: _lock

    # -- inputs from the supervisor/gateway ---------------------------------

    def note_death(self, replica_id: str, now: float | None = None) -> None:
        """One supervisor death note — the quarantine window's input.
        Called from recovery threads; never blocks on fleet state."""
        with self._lock:
            d = self._deaths.setdefault(
                replica_id, collections.deque(maxlen=64)
            )
            d.append(time.time() if now is None else now)

    def note_demand(self) -> None:
        """Demand arrived while nothing was routable: plan a wake on the
        next pass, bypassing hysteresis AND cooldown (answering demand
        must not wait out a scale-down's cooldown)."""
        with self._lock:
            self._wake_pending = True

    def note_executed(self, action: Action, now: float | None = None,
                      dry_run: bool = False) -> None:
        """The actuator executed ``action`` (or dry-ran it): stamp the
        cooldowns — dry-run must preview the real cadence, one action per
        window. Detection STATE is only consumed by real execution: a
        dry-run quarantine leaves the death history intact, so flipping
        dry_run off does not restart the crash-loop breaker's count from
        zero."""
        now = time.time() if now is None else now
        if action.kind in ("scale_up", "scale_down"):
            self._last_scale = now
            self._up_streak = self._down_streak = 0
            self._idle_since = None
        if action.kind in REMEDIATION_KINDS:
            self._remedy_last[action.target] = now
            if action.kind == "quarantine" and not dry_run:
                with self._lock:
                    self._deaths.pop(action.target, None)

    def note_replayed(self, kind: str, target: str, ts: float) -> None:
        """Journal-tail replay after a gateway crash (ISSUE 20): restamp
        the cooldown a previous incarnation's executed action started,
        WITHOUT re-executing anything. Only recency is rebuilt — streaks,
        death windows and wake state are detection state that the new
        incarnation re-observes live; a recovered planner that forgot
        its cooldowns would immediately re-plan an action whose window
        had not expired when the old gateway died. Stamps keep the max
        (the tail may replay out of order across rotated segments)."""
        if kind in ("scale_up", "scale_down"):
            self._last_scale = max(self._last_scale, ts)
        if kind in REMEDIATION_KINDS and target:
            prior = self._remedy_last.get(target, float("-inf"))
            self._remedy_last[target] = max(prior, ts)

    # -- planning -----------------------------------------------------------

    def _signal(self, name: str, signals: FleetSignals) -> None:
        if self.on_signal is not None:
            try:
                self.on_signal(name, signals.snapshot())
            except Exception:  # noqa: BLE001 - observer must not break plan
                logger.exception("autoscale: on_signal hook failed")

    def plan(self, signals: FleetSignals) -> list[Action]:
        cfg = self.config
        now = signals.now
        out: list[Action] = []
        out.extend(self._plan_quarantine(signals))
        out.extend(self._plan_drain(signals))
        # Wake (scale-to-zero admission): demand against an empty fleet
        # bypasses hysteresis and cooldown — the 429 the gateway answered
        # promised capacity within the wake budget.
        with self._lock:
            wake, self._wake_pending = self._wake_pending, False
        if wake:
            if not signals.views and signals.parked:
                self._signal("wake", signals)
                out.append(Action(
                    "scale_up", sorted(signals.parked)[0],
                    "wake: demand while scaled to zero",
                    signals.snapshot(), now, allow_zero=True,
                ))
                return out
        if not signals.views:
            # Nothing live to read pressure from (crash storm or scaled to
            # zero): demand scaling needs a denominator; remediation above
            # already did its work.
            self._up_streak = self._down_streak = 0
            self._idle_since = None
            return out
        cooled = now - self._last_scale >= cfg.cooldown_s
        # Bulk-lane coupling (armed only when bulk_scale_up_backlog > 0):
        # a deep offline backlog is demand even when every queue reads
        # empty — bulk is preempted first, so it never shows up there.
        bulk_coupled = cfg.bulk_scale_up_backlog > 0
        bulk_hot = (bulk_coupled
                    and signals.bulk_backlog >= cfg.bulk_scale_up_backlog)
        bulk_pending = bulk_coupled and signals.bulk_backlog > 0
        # -- scale up -------------------------------------------------------
        hot = (signals.pressure >= cfg.scale_up_pressure
               or signals.queue_per_replica >= cfg.scale_up_queue
               or bulk_hot)
        if hot:
            if self._up_streak == 0:
                self._signal("pressure_high", signals)
            self._up_streak += 1
        else:
            self._up_streak = 0
        if (hot and self._up_streak >= cfg.up_hysteresis_polls
                and signals.parked and cooled):
            out.append(Action(
                "scale_up", sorted(signals.parked)[0],
                f"pressure {signals.pressure:.2f} / queue "
                f"{signals.queue_per_replica:.2f} / bulk backlog "
                f"{signals.bulk_backlog} over "
                f"{self._up_streak} poll(s)",
                signals.snapshot(), now,
            ))
            return out
        # -- scale down -----------------------------------------------------
        # A pending bulk backlog vetoes parking: the lane exists to soak
        # exactly the capacity a scale-down would remove. Drain the
        # backlog first; THEN the fleet may shrink.
        idle = (signals.pressure <= cfg.scale_down_pressure
                and signals.queue_per_replica == 0
                and not bulk_pending)
        all_idle = signals.pressure == 0 and signals.queue_per_replica == 0 \
            and not bulk_pending \
            and all(v.outstanding == 0 for v in signals.views)
        if idle:
            if self._down_streak == 0:
                self._signal("pressure_low", signals)
            self._down_streak += 1
        else:
            self._down_streak = 0
        self._idle_since = (
            (self._idle_since or now) if all_idle else None
        )
        if not idle or signals.slo_alerting or not cooled:
            # A burning SLO pins the fleet size no matter how quiet the
            # instantaneous pressure looks.
            return out
        n_active = len(signals.active)
        floor = cfg.min_replicas
        # The floor binds on LIVE capacity, not the active roster: an
        # active-but-dead replica (mid-recovery, or given up on) serves
        # nothing, so parking a live one while dead peers pad the count
        # would take the fleet below its real floor.
        if self._down_streak >= cfg.hysteresis_polls and n_active > floor \
                and len(signals.views) > floor:
            out.append(Action(
                "scale_down", self._down_target(signals),
                f"pressure {signals.pressure:.2f} idle over "
                f"{self._down_streak} poll(s)",
                signals.snapshot(), now,
            ))
        elif (cfg.scale_to_zero and n_active > 0
              and self._idle_since is not None
              and now - self._idle_since >= cfg.idle_to_zero_s):
            out.append(Action(
                "scale_down", self._down_target(signals),
                f"idle {now - self._idle_since:.1f}s: scale to zero",
                signals.snapshot(), now, allow_zero=True,
            ))
        return out

    @staticmethod
    def _down_target(signals: FleetSignals) -> str:
        """Park the LEAST valuable replica: lowest windowed prefix-cache
        hit ratio first (its cache is the cheapest to lose — only its own
        ring keys remap), highest id among ties (low ids stay stable)."""
        return max(
            signals.views,
            key=lambda v: (-(round(v.recent_cache_hit_ratio or 0.0, 4)),
                           v.id),
        ).id

    def _plan_drain(self, signals: FleetSignals) -> list[Action]:
        """TPOT-storm remediation: one live replica far above its peers'
        median is the culprit (an even fleet-wide slowdown is load, not a
        culprit — nothing to drain)."""
        cfg = self.config
        rated = [v for v in signals.views
                 if isinstance(v.tpot_p95_s, (int, float))]
        if len(rated) < 2:
            return []
        worst = max(rated, key=lambda v: v.tpot_p95_s)
        peers = [v.tpot_p95_s for v in rated if v.id != worst.id]
        bar = max(cfg.tpot_storm_min_s,
                  cfg.tpot_storm_factor * statistics.median(peers))
        if worst.tpot_p95_s <= bar:
            return []
        last = self._remedy_last.get(worst.id, float("-inf"))
        if signals.now - last < cfg.remedy_cooldown_s:
            return []
        self._signal("tpot_storm", signals)
        return [Action(
            "drain", worst.id,
            f"tpot p95 {worst.tpot_p95_s:.3f}s > {bar:.3f}s "
            f"(peers' median x {cfg.tpot_storm_factor:g})",
            signals.snapshot(), signals.now,
        )]

    def _plan_quarantine(self, signals: FleetSignals) -> list[Action]:
        cfg = self.config
        out: list[Action] = []
        with self._lock:
            # Snapshot: recovery threads append death notes concurrently.
            deaths_by_rid = {rid: list(d)
                             for rid, d in self._deaths.items()}
        for rid, deaths in deaths_by_rid.items():
            if rid in signals.quarantined:
                continue
            recent = [t for t in deaths
                      if signals.now - t <= cfg.quarantine_window_s]
            if len(recent) < cfg.quarantine_deaths:
                continue
            last = self._remedy_last.get(rid, float("-inf"))
            if signals.now - last < cfg.remedy_cooldown_s:
                continue
            self._signal("death_storm", signals)
            out.append(Action(
                "quarantine", rid,
                f"{len(recent)} death(s) in {cfg.quarantine_window_s:g}s",
                signals.snapshot(), signals.now,
            ))
        return out


class Actuator:
    """Executes planned actions through FleetSupervisor primitives, under
    the supervisor's fleet-mutation lock, with the full observability
    spine (journal / flight ring / span / counters / incident bundle) on
    every outcome. ``dry_run`` plans-but-logs: the action journals and
    counts as planned, then records outcome ``dry_run`` without touching
    the fleet."""

    def __init__(
        self,
        fleet,
        supervisor,
        config,
        *,
        planner: ActionPlanner | None = None,
        journal=None,
        tracer=None,
        metrics=None,
        flight=None,
        plane=None,
        slo=None,
        bulk=None,
    ):
        """``journal``: EventJournal for ``action.*`` events; ``metrics``:
        GatewayMetrics (per-kind/outcome counters); ``flight``:
        FlightRecorder (ACTION ring); ``plane``: AnomalyPlane — executed
        remediation and failed actions become incident bundles through it;
        ``slo``: BurnRateMonitor whose ``any_alerting()`` pins the fleet
        size while burning; ``bulk``: BulkJobManager whose ``backlog()``
        feeds the bulk demand signal (ISSUE 19) — None reads as zero."""
        self.fleet = fleet
        self.supervisor = supervisor
        self.config = config
        self.planner = planner if planner is not None else ActionPlanner(
            config, on_signal=self._on_signal
        )
        if planner is not None and planner.on_signal is None:
            planner.on_signal = self._on_signal
        self.journal = journal
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics
        self.flight = flight
        self.plane = plane
        self.slo = slo
        self.bulk = bulk
        # THE fleet-mutation lock — the same Lock object the supervisor's
        # crash recovery and rolling restarts hold (replica.py); sharing
        # the object is what serializes a scale event against a relaunch.
        self.fleet_lock = supervisor.fleet_lock
        self._executing = ""  # guarded-by: fleet_lock
        self._log_lock = threading.Lock()
        self._log: collections.deque = collections.deque(
            maxlen=max(1, getattr(config, "action_log", 256))
        )  # guarded-by: _log_lock
        # Written by signals() on the supervisor thread, read by gateway
        # request threads (/actions wake budget, note_demand).
        self._cold_lock = threading.Lock()
        self._cold_starts: dict[str, float] = {}  # guarded-by: _cold_lock

    # -- signal plumbing ----------------------------------------------------

    def _on_signal(self, name: str, snapshot: dict) -> None:
        """Hysteresis-episode head: the causal anchor the planned/executed
        events chain after in the journal."""
        self._journal_event("action.signal", signal_name=name,
                            signal=snapshot)
        if self.flight is not None:
            self.flight.ring(ACTION_RING).record(
                event="signal", signal_name=name, **snapshot
            )

    def _journal_event(self, event: str, **attrs) -> None:
        if self.journal is not None:
            try:
                self.journal.event(event, **attrs)
            except Exception:  # noqa: BLE001 - journal loss must not stop us
                logger.exception("autoscale: journal write failed")

    def note_death(self, replica_id: str) -> None:
        """Supervisor death-branch hook (never raises)."""
        try:
            self.planner.note_death(replica_id)
        except Exception:  # noqa: BLE001 - never break replica recovery
            logger.exception("autoscale: death note failed")

    def note_demand(self) -> int | None:
        """The gateway found nothing routable: if the fleet is genuinely
        asleep (NO routable replica anywhere, parked capacity available),
        request a wake and return the Retry-After the 429 should carry
        (the measured wake-up budget); None otherwise — a request that
        merely exhausted its retries against live-but-erroring replicas
        must get the fast 503, not a wake promise the planner (which
        wakes only an empty fleet) would silently drop."""
        try:
            if self.fleet.routable() or not self.fleet.parked_ids():
                return None
            self.planner.note_demand()
            return max(1, int(self.wake_budget_s() + 0.999))
        except Exception:  # noqa: BLE001 - admission must not crash
            logger.exception("autoscale: demand note failed")
            return None

    def wake_budget_s(self) -> float:
        """``wake_budget_factor`` x the largest MEASURED cold start any
        replica ever reported on /health (compile cache included);
        ``default_cold_start_s`` only bootstraps a fleet that has never
        reported one."""
        with self._cold_lock:
            measured = max(self._cold_starts.values(), default=0.0)
        base = measured if measured > 0 else self.config.default_cold_start_s
        return self.config.wake_budget_factor * base

    # -- the control loop ---------------------------------------------------

    def signals(self, now: float | None = None) -> FleetSignals:
        now = time.time() if now is None else now
        views = self.fleet.routable()
        with self._cold_lock:
            for v in views:
                if isinstance(v.cold_start_s, (int, float)):
                    self._cold_starts[v.id] = float(v.cold_start_s)
        n = len(views)
        pressure = (
            sum(v.slot_pressure for v in views) / n if n else 0.0
        )
        queue = (
            sum(v.queue_depth + v.outstanding for v in views) / n
            if n else 0.0
        )
        alerting = False
        if self.slo is not None:
            try:
                alerting = bool(self.slo.any_alerting())
            except Exception:  # noqa: BLE001 - a broken monitor reads calm
                alerting = False
        bulk_backlog = 0
        if self.bulk is not None:
            try:
                bulk_backlog = int(self.bulk.backlog())
            except Exception:  # noqa: BLE001 - a broken lane reads empty
                bulk_backlog = 0
        return FleetSignals(
            now=now,
            views=tuple(views),
            active=tuple(self.fleet.active_ids()),
            parked=tuple(self.fleet.parked_ids()),
            quarantined=tuple(self.fleet.quarantined_ids()),
            pressure=pressure,
            queue_per_replica=queue,
            slo_alerting=alerting,
            cold_start_s=self.wake_budget_s() / self.config.wake_budget_factor,
            bulk_backlog=bulk_backlog,
        )

    def poll(self) -> list[dict]:
        """One planner pass + actuation; rides the supervisor loop. Never
        raises — the supervisor thread it rides IS the fleet's crash
        recovery, and a broken actuation pass must not take that down.
        Returns the log entries this pass produced (tests)."""
        try:
            actions = self.planner.plan(self.signals())
            return [self.apply(a) for a in actions]
        except Exception:  # noqa: BLE001 - never break the health loop
            logger.exception("autoscale: actuation pass failed")
            return []

    # -- actuation ----------------------------------------------------------

    def apply(self, action: Action) -> dict:
        """Execute one action with the full observability spine. Returns
        the /actions log entry."""
        m = self.metrics
        dry = bool(self.config.dry_run)
        self._journal_event("action.planned", kind=action.kind,
                            target=action.target, reason=action.reason,
                            dry_run=dry, signal=action.signal)
        if self.flight is not None:
            self.flight.ring(ACTION_RING).record(
                event="planned", kind=action.kind, target=action.target,
                reason=action.reason, dry_run=dry,
            )
        if m is not None:
            m.action_counter(action.kind, "planned").inc()
        span = self.tracer.start_span(
            "gateway.action", kind=action.kind, target=action.target,
            reason=action.reason, dry_run=dry,
        )
        outcome, detail = "refused", ""
        try:
            if dry:
                outcome = "dry_run"
            else:
                # BOUNDED wait for the fleet-mutation lock: apply() runs
                # on the supervisor's run-loop thread, and a recovery leg
                # can hold the lock up to restart_timeout_s — blocking
                # here unboundedly would stall health probing of the
                # whole rest of the fleet behind one wedged relaunch. A
                # timed-out action refuses (cooldown un-stamped), so the
                # planner simply re-plans it on a later pass.
                lock_wait = max(5.0, 2 * self.config.drain_wait_s)
                if not self.fleet_lock.acquire(timeout=lock_wait):
                    detail = (f"fleet-mutation lock busy after "
                              f"{lock_wait:.0f}s (recovery or rolling "
                              "restart in progress); will replan")
                else:
                    try:
                        outcome, detail = self._apply_holding_locked(action)
                    finally:
                        self.fleet_lock.release()
        except Exception as e:  # noqa: BLE001 - incl. InjectedFault
            outcome, detail = "failed", f"{type(e).__name__}: {e}"
            logger.exception("autoscale: %s %s failed",
                             action.kind, action.target)
        if outcome in ("executed", "dry_run"):
            # Dry-run stamps the cooldowns too: plan-but-log must PREVIEW
            # the real cadence (one action per cooldown window), not
            # re-plan the identical action every supervisor pass — the
            # fleet state a real execute would change cannot change here,
            # so the cooldown is the only thing bounding repetition.
            self.planner.note_executed(action, dry_run=(outcome == "dry_run"))
        if outcome != "dry_run":
            self._journal_event(f"action.{outcome}", kind=action.kind,
                                target=action.target, detail=detail,
                                signal=action.signal)
        if self.flight is not None:
            self.flight.ring(ACTION_RING).record(
                event=outcome, kind=action.kind, target=action.target,
                detail=detail,
            )
        if m is not None:
            m.action_counter(action.kind, outcome).inc()
        try:
            # The span write lands in the journal file; a full disk must
            # cost the trace record, never the action log entry below (or
            # the supervisor thread this runs on).
            span.end(outcome=outcome)
        except Exception:  # noqa: BLE001 - observability loss only
            logger.exception("autoscale: action span write failed")
        incident = None
        if self.plane is not None and (
            outcome == "failed"
            or (outcome == "executed" and action.kind in REMEDIATION_KINDS)
        ):
            # Remediation leaves the same forensic trail as the failure it
            # chased: ring dumps (incl. the ACTION ring), metrics, journal
            # tail, trace slice, chaos attribution — one bundle.
            incident = self.plane.trigger(Anomaly(
                f"action.{action.kind}",
                severity="warning",
                detail={"fingerprint_key": action.target,
                        "target": action.target,
                        "outcome": outcome,
                        "reason": action.reason,
                        "action_detail": detail,
                        "signal": action.signal},
            ))
        entry = {
            "ts": action.ts,
            "kind": action.kind,
            "target": action.target,
            "reason": action.reason,
            "outcome": outcome,
            "detail": detail,
            "dry_run": dry,
            "signal": action.signal,
            "incident": incident,
        }
        with self._log_lock:
            self._log.append(entry)
        return entry

    def recent(self) -> list[dict]:
        """The bounded action log, oldest first (the /actions body)."""
        with self._log_lock:
            return list(self._log)

    # -- executors (caller holds fleet_lock) --------------------------------

    def _apply_holding_locked(self, action: Action) -> tuple[str, str]:
        """The under-lock half of :meth:`apply`; caller holds (and
        releases) ``fleet_lock`` via the timed acquire above."""
        self._executing = f"{action.kind}:{action.target}"
        try:
            # Chaos seam (ISSUE 12 satellite): inside the lock on purpose
            # — a delay here WIDENS the window a racing kill/rolling-
            # restart must serialize against; error = a failed actuation.
            maybe_inject("supervisor.action")
            return self._execute_locked(action)
        finally:
            self._executing = ""

    def _execute_locked(self, action: Action) -> tuple[str, str]:
        if action.kind == "scale_up":
            return self._scale_up_locked(action)
        if action.kind == "scale_down":
            return self._scale_down_locked(action)
        if action.kind == "drain":
            return self._drain_locked(action)
        if action.kind == "quarantine":
            return self._quarantine_locked(action)
        return "refused", f"unknown action kind {action.kind!r}"

    def _scale_up_locked(self, action: Action) -> tuple[str, str]:
        # Re-validate under the lock: the world may have moved since the
        # plan (another actor already woke it, an operator removed it).
        parked = self.fleet.parked_ids()
        rid = action.target if action.target in parked else (
            sorted(parked)[0] if parked else ""
        )
        if not rid:
            return "refused", "no parked replica to activate"
        st = self.fleet._state(rid)
        self.fleet.set_deactivated(rid, False)
        st.handle.start()
        if self.supervisor._await_healthy(rid):
            st.fails = 0
            self.fleet.mark_draining(rid, False)
            return "executed", f"activated {rid}"
        # Revert: a replica that cannot come up must not sit half-active
        # soaking supervisor recovery attempts against a broken image.
        st.handle.stop(drain=False, timeout=0.0)
        st.live = False
        self.fleet.set_deactivated(rid, True)
        return "failed", f"{rid} did not become healthy"

    def _scale_down_locked(self, action: Action) -> tuple[str, str]:
        rid = action.target
        active = self.fleet.active_ids()
        if rid not in active:
            return "refused", f"{rid} is not active"
        floor = 0 if action.allow_zero else self.config.min_replicas
        if len(active) - 1 < floor:
            return "refused", (
                f"would leave {len(active) - 1} active < floor {floor}"
            )
        # The floor binds on LIVE capacity too: active-but-dead replicas
        # (mid-recovery or given up on) pad the roster without serving,
        # and parking a live one behind that padding would leave fewer
        # than `floor` replicas actually answering requests.
        live = [r for r in active if self.fleet._state(r).live]
        if rid in live and len(live) - 1 < floor:
            return "refused", (
                f"would leave {len(live) - 1} live < floor {floor}"
            )
        st = self.fleet._state(rid)
        # Park FIRST: routing stops, the supervisor's poll skips it, and a
        # concurrent death of this very replica resolves to "down on
        # purpose" instead of a relaunch (the scale-down-racing-kill
        # drill).
        self.fleet.set_deactivated(rid, True)
        self.fleet.mark_draining(rid, True)
        self.supervisor.drain_stop_locked(rid, st, self.config.drain_wait_s)
        self.fleet.mark_draining(rid, False)
        return "executed", f"parked {rid}"

    def _drain_locked(self, action: Action) -> tuple[str, str]:
        rid = action.target
        if rid not in self.fleet.active_ids():
            return "refused", f"{rid} is not active"
        st = self.fleet._state(rid)
        self.fleet.mark_draining(rid, True)
        self.supervisor.drain_stop_locked(rid, st, self.config.drain_wait_s)
        st.handle.start()
        if self.supervisor._await_healthy(rid):
            st.fails = 0
            self.fleet.mark_draining(rid, False)
            return "executed", f"drained and restarted {rid}"
        # Leave it draining-and-dead: it is NOT parked, so the supervisor's
        # ordinary recovery keeps trying after the lock releases — but
        # ONLY if the failure count reads dead. Pin it to the threshold
        # (the _recover_cycle_locked rule): a replica that turns healthy
        # just after our await timed out would otherwise probe fails=0,
        # live=True with draining stuck True — permanently unroutable.
        st.fails = max(st.fails, self.supervisor.fail_threshold)
        return "failed", f"{rid} did not come back after drain"

    def _quarantine_locked(self, action: Action) -> tuple[str, str]:
        rid = action.target
        st = self.fleet._state(rid)
        if st.quarantined:
            return "refused", f"{rid} already quarantined"
        self.fleet.set_quarantined(rid, True)
        self.fleet.mark_draining(rid, True)
        # Hard stop: a crash-looping replica has nothing worth draining.
        st.handle.stop(drain=False, timeout=0.0)
        st.live = False
        self.fleet.mark_draining(rid, False)
        return "executed", f"quarantined {rid}"


class ReplicaSecondsSampler:
    """Integral of live replica count over wall time — the resource-cost
    number the autoscaler A/B is graded on (``bench.py
    --serve-trace-replay`` embeds it; perf_compare gates it downward).
    Sampling, not transition-tracking: the supervisor mutates liveness
    from several threads and a 50 ms Riemann sum is honest enough for
    runs measured in seconds-to-hours."""

    def __init__(self, fleet, interval_s: float = 0.05):
        self.fleet = fleet
        self.interval_s = interval_s
        self._total = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ReplicaSecondsSampler":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="replica-seconds"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        last = time.monotonic()
        while not self._stop.wait(self.interval_s):
            now = time.monotonic()
            self._total += self.fleet.live_count() * (now - last)
            last = now

    def stop(self) -> float:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return self.total

    @property
    def total(self) -> float:
        return self._total


class TrafficRecorder:
    """``--save-trace``: one JSONL row per ADMITTED request — arrival
    offset from the first admitted request, tenant digest (the
    credential-safe label, never the bearer token), SLO class, and the
    gateway's tokenizer-free prompt/max_new estimates. The shape
    ``bench.py --serve-trace-replay`` replays with preserved inter-arrival
    times. Line-buffered appends: a killed gateway loses at most the row
    it never wrote (the journal contract)."""

    def __init__(self, path: str):
        if not path:
            raise ValueError("TrafficRecorder needs a path")
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()
        self._t0: float | None = None  # guarded-by: _lock
        self.rows = 0

    def note(self, *, tenant: str = "", slo_class: str | None = None,
             prompt_tokens: int = 0, max_new: int = 0,
             stream: bool = False, now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            row = {
                "t": round(now - self._t0, 4),
                "tenant": tenant,
                "slo_class": slo_class,
                "prompt_tokens": int(prompt_tokens),
                "max_new": int(max_new),
                "stream": bool(stream),
            }
            try:
                self._f.write(json.dumps(row, sort_keys=True) + "\n")
                self.rows += 1
            except OSError:
                logger.exception("traffic recorder: write failed")

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


def load_trace(path: str) -> list[dict]:
    """Read a recorded traffic trace, oldest first. Corrupt lines (the
    torn tail a kill leaves) are skipped, never an error; offsets are
    re-zeroed to the first row so replays always start at t=0."""
    rows: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if not isinstance(row, dict):
                continue
            t = row.get("t")
            if not isinstance(t, (int, float)) or t < 0:
                continue
            rows.append(row)
    rows.sort(key=lambda r: r["t"])
    if rows:
        t0 = rows[0]["t"]
        for r in rows:
            r["t"] = round(r["t"] - t0, 4)
    return rows
