"""GPipe-style pipeline parallelism over the ``stage`` mesh axis.

The reference has no model and therefore no pipeline parallelism (SURVEY.md §2
parallelism checklist: "TP, PP, SP, EP ... all absent"); this module is part
of the TPU build's full parallelism menu. Design, TPU-first:

- The stacked layer parameters (leading ``layers`` dim, models/llama.py) are
  sharded over ``stage``: each stage holds ``L / n_stages`` contiguous layers,
  fully materialized (GPipe memory layout — pipeline replaces FSDP as the
  weight-sharding strategy; see ``PIPELINE_RULES``).
- The batch is split into ``n_microbatches`` microbatches that flow through
  the stages. Every device runs the same compiled program (SPMD): a
  ``lax.scan`` over ``n_microbatches + n_stages - 1`` ticks, where each tick
  applies the stage's local layers (an inner ``lax.scan``) and rotates
  activations to the next stage with ``lax.ppermute`` — XLA lowers the
  neighbor permute to ICI/DCN sends, exactly like the ring-attention rotation
  (ops/ring_attention.py).
- Bubble fraction is the GPipe ``(n_stages-1)/(n_ticks)``; garbage flows
  through the bubble slots and is never read (stage 0 overwrites its inbox
  with the next microbatch; the last stage only records ticks that carry a
  finished microbatch).
- The whole schedule is differentiable (scan + ppermute + where), so the same
  code path serves training; the backward pass is the reverse pipeline XLA
  derives from the forward scan.

Composability: ``stage`` composes with the batch axes (``data``, ``fsdp`` —
the latter acting as plain data parallelism here, since ``PIPELINE_RULES``
un-shards parameters). It does not compose with ``tensor``/``sequence``/
``expert`` inside the pipelined region — those require GSPMD propagation,
which ``shard_map`` regions deliberately bypass; ``pipeline_apply`` validates
this at trace time.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ditl_tpu.parallel.sharding import DEFAULT_RULES
from ditl_tpu.utils.compat import shard_map

__all__ = ["PIPELINE_RULES", "pipeline_rules", "pipeline_apply"]


def pipeline_rules(base: dict[str, Any] | None = None) -> dict[str, Any]:
    """Sharding rules for pipelined runs: layers -> stage, weights otherwise
    replicated (each stage holds its layers whole), batch axes untouched."""
    rules = dict(base if base is not None else DEFAULT_RULES)
    rules.update(
        layers="stage",
        embed=None,
        heads=None,
        kv_heads=None,
        mlp=None,
        vocab=None,
        expert=None,
        seq=None,
        act_heads=None,
        act_kv_heads=None,
        act_mlp=None,
        act_vocab=None,
    )
    return rules


PIPELINE_RULES = pipeline_rules()


def _batch_axes(rules: dict[str, Any]) -> Any:
    return rules.get("batch", ("data", "fsdp"))


def pipeline_apply(
    layer_fn: Callable[[jax.Array, Any, Any], tuple[jax.Array, jax.Array]],
    stacked_params: Any,
    x: jax.Array,  # (B, S, D) global activations entering the first layer
    extras: Any,  # pytree of (B, ...) arrays consumed by every layer (positions, segment_ids)
    *,
    mesh: jax.sharding.Mesh,
    rules: dict[str, Any] | None = None,
    n_microbatches: int | None = None,
    axis_name: str = "stage",
) -> tuple[jax.Array, jax.Array]:
    """Run ``x`` through all layers, pipelined over the ``stage`` mesh axis.

    ``layer_fn(x_mb, one_layer_params, extras_mb) -> (x_mb, aux_scalar)``
    applies a single decoder layer to one microbatch. ``stacked_params`` is
    the layer pytree with the leading ``layers`` dim (stage-sharded by the
    caller's train-state shardings). Returns the final activations (B, S, D)
    and the mean-over-microbatches of the summed per-layer aux scalars —
    matching the non-pipelined ``lax.scan``'s ``sum(aux)`` semantics.
    """
    rules = rules if rules is not None else PIPELINE_RULES
    n_stages = mesh.shape[axis_name]
    for ax in ("tensor", "sequence", "expert"):
        if ax in mesh.shape and mesh.shape[ax] > 1:
            raise ValueError(
                f"pipeline parallelism does not compose with mesh axis "
                f"{ax!r} > 1 (got {mesh.shape[ax]}) inside the pipelined region"
            )
    b = x.shape[0]
    m = n_microbatches or n_stages
    if b % m:
        raise ValueError(f"batch {b} not divisible by n_microbatches {m}")
    batch_ax = _batch_axes(rules)
    batch_ax = batch_ax if isinstance(batch_ax, tuple) else (batch_ax,)
    dp = 1
    for ax in batch_ax:
        dp *= mesh.shape.get(ax, 1)
    if (b // m) % dp:
        raise ValueError(
            f"microbatch size {b // m} (batch {b} / {m} microbatches) must be "
            f"divisible by the data-parallel size {dp}"
        )
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(
            f"num_layers {n_layers} not divisible by {n_stages} pipeline stages"
        )

    def split(a):
        return a.reshape((m, b // m) + a.shape[1:])

    x_mb = split(x)
    extras_mb = jax.tree.map(split, extras)

    batch = _batch_axes(rules)
    x_spec = P(None, batch, *([None] * (x.ndim - 1)))
    param_specs = jax.tree.map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stacked_params
    )
    extras_specs = jax.tree.map(
        lambda e: P(None, batch, *([None] * (e.ndim - 2))), extras_mb
    )

    stage_prog = functools.partial(
        _stage_program,
        layer_fn,
        axis_name=axis_name,
        n_stages=n_stages,
        m=m,
        batch_axes=tuple(ax for ax in batch_ax if mesh.shape.get(ax, 1) > 1),
    )
    out_mb, aux = shard_map(
        stage_prog,
        mesh=mesh,
        in_specs=(param_specs, x_spec, extras_specs),
        out_specs=(x_spec, P()),
        check_vma=False,
    )(stacked_params, x_mb, extras_mb)
    return out_mb.reshape((b,) + x.shape[1:]), aux


def _stage_program(
    layer_fn, local_params, x_st, extras_st, *, axis_name, n_stages, m, batch_axes
):
    """The per-stage SPMD program: GPipe tick loop over the microbatch queue."""
    s_idx = jax.lax.axis_index(axis_name)
    n_ticks = m + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, out, aux_sum = carry
        # Stage 0 pulls the next microbatch from its queue; other stages use
        # the activation rotated in from the previous stage.
        inj = jax.lax.dynamic_index_in_dim(
            x_st, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        buf = jnp.where(s_idx == 0, inj, buf)
        # This tick, stage s works on microbatch t - s (bubble ticks work on
        # garbage that is masked out below and never emitted).
        my_mb = t - s_idx
        ex = jax.tree.map(
            lambda e: jax.lax.dynamic_index_in_dim(
                e, jnp.clip(my_mb, 0, m - 1), 0, keepdims=False
            ),
            extras_st,
        )

        def one_layer(h, lp):
            return layer_fn(h, lp, ex)

        buf, aux = jax.lax.scan(one_layer, buf, local_params)
        valid = (my_mb >= 0) & (my_mb < m)
        aux_sum = aux_sum + jnp.where(valid, jnp.sum(aux), 0.0)

        # The last stage records finished microbatches before the rotation.
        out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
        write = (s_idx == n_stages - 1) & (t >= n_stages - 1)
        out = jnp.where(
            write, jax.lax.dynamic_update_index_in_dim(out, buf, out_idx, 0), out
        )
        buf = jax.lax.ppermute(buf, axis_name, perm)
        return (buf, out, aux_sum), None

    buf0 = jnp.zeros_like(x_st[0])
    out0 = jnp.zeros_like(x_st)
    (_, out, aux_sum), _ = jax.lax.scan(
        tick, (buf0, out0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks)
    )
    # Results live on the last stage only; broadcast them to every stage so
    # the loss head (outside the shard_map) sees stage-replicated activations.
    out = jnp.where(s_idx == n_stages - 1, out, jnp.zeros_like(out))
    out = jax.lax.psum(out, axis_name)
    # Each stage summed aux over its own layers; psum completes the layer sum,
    # /m converts the sum over microbatches into the batch-level aux. The aux
    # is declared replicated (out_specs P()), so it must also be reduced over
    # the data axes — each data shard computed aux on its own batch slice.
    aux = jax.lax.psum(aux_sum, axis_name) / m
    if batch_axes:
        aux = jax.lax.pmean(aux, batch_axes)
    return out, aux
