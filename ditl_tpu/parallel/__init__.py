from ditl_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    logical_to_spec,
    named_sharding_tree,
    spec_tree,
)
