"""GSPMD sharding rules: logical axes -> mesh axes -> PartitionSpecs.

The reference's single parallelism strategy is data parallelism by sampler
sharding (ref ``src/distributed_inference.py:58-59``); it has no weight,
activation, sequence, or expert sharding (SURVEY.md §2 checklist). Here all of
them are expressed through one mechanism — every parameter and activation
declares *logical* axes (``"embed"``, ``"heads"``, ``"batch"``...), and a rule
table maps logical axes onto mesh axes. Changing parallelism strategy
(DP -> FSDP -> TP/SP -> MoE) is a rule/mesh change, not a model rewrite —
SURVEY.md §7 'hard part (b)'.

Rules (MaxText-style conventions):
- ``batch``   -> ``("data", "fsdp")``: both axes split the batch; FSDP is data
  parallelism with sharded parameters/optimizer state.
- ``embed``   -> ``fsdp``: ZeRO-3-style parameter sharding along the embedding
  dim; XLA all-gathers weights per layer and reduce-scatters grads.
- ``heads`` / ``mlp`` / ``vocab`` -> ``tensor``: Megatron-style intra-layer
  tensor parallelism (all-reduce on the row-parallel matmul output).
- ``seq``     -> ``sequence``: context parallelism for long sequences (ring
  attention partner axis).
- ``expert``  -> ``expert``: MoE expert parallelism (all-to-all dispatch).
- ``layers``  -> ``None``: the scanned layer dim is never sharded (pipeline
  parallelism would shard it; see parallel/pipeline.py).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "DEFAULT_RULES", "logical_to_spec", "spec_tree", "named_sharding_tree",
    "mesh_axes_size", "seq_shards", "pallas_batch_shards",
    "pallas_bwd_effective",
]


def mesh_axes_size(mesh, axes) -> int:
    """Product of mesh-axis sizes for a rules value (str, tuple, or None)."""
    if axes is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def seq_shards(mesh, rules=None) -> int:
    """Shard count of the KV-cache context dim ("cache_seq" rule) on this
    mesh — 1 means context is unsharded. One definition for the engines
    and the attention routing, so they can never disagree."""
    if mesh is None:
        return 1
    r = rules if rules is not None else DEFAULT_RULES
    return mesh_axes_size(mesh, r.get("cache_seq"))


def pallas_batch_shards(mesh, rules, batch: int) -> int | None:
    """Shard count over the batch axes for shard_map'ing a Pallas op whose
    weights stay replicated, or None when this mesh cannot host it:
    sequence-sharded activations, batch not divisible by the batch axes,
    or TENSOR parallelism in use — TP shards the very weights the wrapper
    would replicate, so running the kernel would silently de-shard TP's
    compute (tensor-x redundant FLOPs) while looking like a kernel A/B.
    (FSDP-sharded weights are fine: FSDP all-gathers weights per use
    anyway, so replication inside the island matches its cost model.)
    ONE definition shared by the backward-kernel seams (ops/mlp.py,
    ops/projection.py) and bench.py's ``bwd_impl`` record, so the dispatch
    and its attribution can never drift apart."""
    if mesh is None:
        return 1
    r = rules if rules is not None else DEFAULT_RULES
    if mesh_axes_size(mesh, r.get("seq")) > 1:
        return None
    if max(mesh_axes_size(mesh, r.get("heads")),
           mesh_axes_size(mesh, r.get("mlp"))) > 1:
        return None
    dp = mesh_axes_size(mesh, r.get("batch"))
    return None if batch % dp else dp


def pallas_bwd_effective(bwd_impl: str, batch: int, seq: int, d: int, f: int,
                         blocks, mesh, rules, supports_fn) -> str:
    """The backward implementation a Pallas-seamed op will ACTUALLY run —
    the mesh gate above plus the op's own shape predicate on the per-shard
    token count. Shared by ops/mlp.py and ops/projection.py (and through
    them bench.py's ``bwd_impl`` field) so the two seams cannot diverge."""
    if bwd_impl != "pallas":
        return bwd_impl
    shard = pallas_batch_shards(mesh, rules, batch)
    if shard is None:
        return "xla"
    return "pallas" if supports_fn(
        (batch // shard) * seq, d, f, tuple(blocks or ())
    ) else "xla"

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
DEFAULT_RULES: dict[str, Any] = {
    # parameter axes
    "embed": "fsdp",
    "heads": "tensor",
    "kv_heads": "tensor",
    "mlp": "tensor",
    "vocab": "tensor",
    "expert": "expert",
    "head_dim": None,
    "layers": None,
    "norm": None,
    "lora_rank": None,
    # activation axes (distinct from parameter axes: an activation's embed dim
    # is NOT fsdp-sharded — fsdp shards weights and splits batch)
    "batch": ("data", "fsdp"),
    "seq": "sequence",
    "act_embed": None,
    "act_heads": "tensor",
    "act_kv_heads": "tensor",
    "act_mlp": "tensor",
    "act_vocab": "tensor",
    # KV-cache CONTEXT dim for sequence-sharded serving: the contiguous
    # cache's token axis splits over the sequence mesh axis and decode
    # attention merges per-shard partial softmax over ICI
    # (ops/attention._seq_sharded_decode) — context capacity scales with
    # the mesh instead of one chip's HBM.
    "cache_seq": "sequence",
}


def logical_to_spec(
    logical_axes: Sequence[str | None], rules: dict[str, Any] | None = None
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    rules = rules if rules is not None else DEFAULT_RULES
    spec = []
    for ax in logical_axes:
        if ax is None:
            spec.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"no sharding rule for logical axis {ax!r}")
            spec.append(rules[ax])
    return P(*spec)


def is_axes_leaf(x: Any) -> bool:
    """A logical-axes leaf is a *plain* tuple of axis names / None. Namedtuples
    (optax states) and tuples holding subtrees (optax.chain state) are pytree
    containers, not leaves."""
    return type(x) is tuple and all(e is None or isinstance(e, str) for e in x)


def spec_tree(logical_tree: Any, rules: dict[str, Any] | None = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules), logical_tree, is_leaf=is_axes_leaf
    )


def named_sharding_tree(mesh, logical_tree: Any, rules: dict[str, Any] | None = None):
    """Pytree of NamedShardings for ``jax.jit``'s in/out_shardings or
    ``jax.device_put``."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=is_axes_leaf,
    )
