"""Remote-LLM client (L4): OpenAI-compatible chat completions.

Parity surface: ``get_model_response(prompt)`` wrapping
``litellm.completion(CONFIG['MODEL_NAME'], messages)`` with api_base +
``OPENAI_API_KEY`` configuration (ref ``src/distributed_inference.py:34-41,
53-54``). Contract preserved exactly: **total function** — it never raises;
any failure returns the sentinel string. Improvements the reference only
documents (ref ``docs/troubleshooting.md:42-51`` tells the *user* to
"implement exponential backoff"):

- exponential backoff with jitter on 429/5xx/connection errors, honoring
  ``Retry-After``;
- bounded-concurrency batch path (``complete_many``) so API eval does not
  serialize per example like the reference's hot loop (ref ``:69``), and
  the TPU step is never blocked behind HTTP;
- injectable transport — the test seam SURVEY.md §4 identifies as the
  reference's one good testing idea (mock via function injection), kept.

Implemented on stdlib ``urllib`` (no litellm/httpx dependency; the image has
no egress anyway) against the ``/chat/completions`` wire format LiteLLM's
proxy and every OpenAI-compatible server speak.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

from ditl_tpu.chaos import maybe_inject
from ditl_tpu.config import APIConfig
from ditl_tpu.telemetry.registry import MetricsRegistry
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ERROR_SENTINEL = "Error: Unable to get model response"

__all__ = ["ERROR_SENTINEL", "ClientMetrics", "LLMClient",
           "client_metrics", "get_model_response"]

Transport = Callable[[str, dict, bytes, float], tuple[int, dict, bytes]]


class ClientMetrics:
    """Remote-LLM client telemetry (telemetry/registry.py instruments):
    how often the retry machinery engages and how it ends. Module-level
    singleton (``client_metrics``) shared by every LLMClient in the
    process — the eval loop constructs clients per call, and per-instance
    registries would scatter the counts."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self.requests = r.counter(
            "ditl_client_requests", "remote-LLM logical calls started")
        self.retries = r.counter(
            "ditl_client_retries", "HTTP attempts retried (429/5xx/conn)")
        self.retry_exhausted = r.counter(
            "ditl_client_retry_exhausted",
            "calls that failed after exhausting max_retries")
        self.deadline_exhausted = r.counter(
            "ditl_client_deadline_exhausted",
            "calls aborted by the total_timeout_s wall-clock bound")


client_metrics = ClientMetrics()


class HTTPStatusError(Exception):
    def __init__(self, status: int, headers: dict, body: bytes):
        super().__init__(f"HTTP {status}")
        self.status = status
        self.headers = headers
        self.body = body


def _urllib_transport(url: str, headers: dict, body: bytes, timeout: float):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


class LLMClient:
    def __init__(self, config: APIConfig | None = None, transport: Transport | None = None):
        self.config = config or APIConfig()
        self.transport = transport or _urllib_transport

    # -- low level ----------------------------------------------------------

    def _request_once(self, payload: dict, endpoint: str = "/chat/completions",
                      timeout_s: float | None = None) -> dict:
        cfg = self.config
        # Chaos seam: `error` becomes an OSError — a transport-level
        # failure that exercises the REAL retry/backoff/deadline path
        # (an InjectedFault would bypass the retryable-exception filter).
        fault = maybe_inject("client.request", handles=("error",))
        if fault is not None and fault.action == "error":
            raise OSError("chaos: injected client transport failure")
        url = cfg.api_base.rstrip("/") + endpoint
        headers = {
            "Content-Type": "application/json",
            "Authorization": f"Bearer {cfg.api_key()}",
        }
        body = json.dumps(payload).encode("utf-8")
        status, resp_headers, resp_body = self.transport(
            url, headers, body,
            cfg.timeout_s if timeout_s is None else timeout_s,
        )
        if status != 200:
            raise HTTPStatusError(status, resp_headers, resp_body)
        return json.loads(resp_body)

    def _request_with_retries(self, payload: dict, endpoint: str = "/chat/completions") -> dict:
        """Retry loop with exponential backoff, bounded two ways: attempt
        count (``max_retries``) and — the ISSUE 5 satellite — total wall
        clock (``total_timeout_s``): per-attempt timeouts are clamped to
        the remaining budget and backoff never sleeps past the deadline,
        so one dead endpoint can no longer stall a caller for
        ``max_retries x (timeout_s + backoff_max_s)``."""
        cfg = self.config
        deadline = (
            time.monotonic() + cfg.total_timeout_s
            if cfg.total_timeout_s > 0 else None
        )
        client_metrics.requests.inc()
        last_exc: Exception | None = None

        def _remaining() -> float | None:
            return None if deadline is None else deadline - time.monotonic()

        for attempt in range(cfg.max_retries + 1):
            timeout_s = cfg.timeout_s
            remaining = _remaining()
            if remaining is not None:
                if remaining <= 0:
                    client_metrics.deadline_exhausted.inc()
                    raise TimeoutError(
                        f"total_timeout_s={cfg.total_timeout_s}s exhausted "
                        f"after {attempt} attempt(s)"
                    ) from last_exc
                timeout_s = min(timeout_s, remaining)
            try:
                return self._request_once(payload, endpoint, timeout_s)
            except HTTPStatusError as e:
                last_exc = e
                retryable = e.status == 429 or e.status >= 500
                if not retryable or attempt == cfg.max_retries:
                    if retryable:
                        client_metrics.retry_exhausted.inc()
                    raise
                delay = self._backoff_delay(attempt, e.headers)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                last_exc = e
                if attempt == cfg.max_retries:
                    client_metrics.retry_exhausted.inc()
                    raise
                delay = self._backoff_delay(attempt, {})
            remaining = _remaining()
            if remaining is not None and delay >= remaining:
                # The backoff alone would overshoot the deadline: stop now
                # rather than sleep into a guaranteed failure.
                client_metrics.deadline_exhausted.inc()
                raise TimeoutError(
                    f"total_timeout_s={cfg.total_timeout_s}s exhausted "
                    f"after {attempt + 1} attempt(s) (backoff {delay:.2f}s "
                    "would overshoot)"
                ) from last_exc
            client_metrics.retries.inc()
            logger.warning(
                "API request failed (%s), retry %d/%d in %.2fs",
                last_exc,
                attempt + 1,
                cfg.max_retries,
                delay,
            )
            time.sleep(delay)
        raise last_exc  # unreachable

    def _backoff_delay(self, attempt: int, headers: dict) -> float:
        retry_after = headers.get("Retry-After") or headers.get("retry-after")
        if retry_after:
            try:
                return min(float(retry_after), self.config.backoff_max_s)
            except ValueError:
                pass
        base = self.config.backoff_base_s * (2**attempt)
        return min(base, self.config.backoff_max_s) * (0.5 + random.random() / 2)

    # -- public surface -----------------------------------------------------

    def complete(self, prompt: str, system: str | None = None) -> str:
        """Single-turn completion. Total function: returns ``ERROR_SENTINEL``
        on any failure (parity with ref ``:39-41``)."""
        messages = []
        if system:
            messages.append({"role": "system", "content": system})
        messages.append({"role": "user", "content": prompt})
        payload = {"model": self.config.model_name, "messages": messages}
        try:
            response = self._request_with_retries(payload)
            return response["choices"][0]["message"]["content"]
        except Exception as e:
            logger.error("Error getting model response: %s", e)
            return ERROR_SENTINEL

    def embed(self, texts: str | Sequence[str]) -> list[list[float]] | None:
        """Embeddings from the endpoint's ``/embeddings`` route (this
        framework's own server serves it; any OpenAI-compatible endpoint
        works). Total function like ``complete``: ``None`` on any failure,
        never raises."""
        payload = {
            "model": self.config.model_name,
            "input": texts if isinstance(texts, str) else list(texts),
        }
        try:
            resp = self._request_with_retries(payload, endpoint="/embeddings")
            data = sorted(resp["data"], key=lambda d: d["index"])
            return [d["embedding"] for d in data]
        except Exception as e:
            logger.error("Error getting embeddings: %s", e)
            return None

    def complete_many(self, prompts: Sequence[str], system: str | None = None) -> list[str]:
        """Bounded-concurrency fan-out; order-preserving; each element total."""
        if not prompts:
            return []
        workers = max(1, min(self.config.max_concurrency, len(prompts)))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            return list(pool.map(lambda p: self.complete(p, system), prompts))


def get_model_response(prompt: str, config: APIConfig | None = None) -> str:
    """Drop-in functional parity with the reference's module-level
    ``get_model_response(prompt) -> str`` (ref ``src/distributed_inference.py:34``)."""
    return LLMClient(config).complete(prompt)
