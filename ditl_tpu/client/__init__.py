from ditl_tpu.client.llm import ERROR_SENTINEL, LLMClient, get_model_response  # noqa: F401
from ditl_tpu.client.eval_loop import run_api_eval  # noqa: F401
