"""Process-0 API evaluation loop (L4/L5).

The reference's hot loop sends every example to the remote LLM serially and
logs prompt/response/label on rank 0 (ref ``src/distributed_inference.py:64-76``).
Here the API eval is an explicitly separate, process-0-only, *concurrent* side
loop (BASELINE.json north star: 'the LiteLLM client path stays intact for
API-side eval') that never blocks the device train step: the trainer calls it
between steps with a handful of samples.
"""

from __future__ import annotations

from ditl_tpu.client.llm import ERROR_SENTINEL, LLMClient
from ditl_tpu.runtime.distributed import is_coordinator
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["run_api_eval"]

_SYSTEM = (
    "You are a sentiment classifier. Reply with exactly one word: "
    "'positive' or 'negative'."
)


def run_api_eval(
    client: LLMClient,
    texts: list[str],
    labels: list[int],
    max_samples: int = 8,
    log_chars: int = 100,
) -> dict:
    """Send up to ``max_samples`` texts to the remote model; log and score.

    Runs only on process 0 (every other process returns immediately) — the
    structural form of the reference's ``if rank == 0`` gate (ref ``:71``).
    Returns {'n', 'n_errors', 'accuracy'} (accuracy over non-error replies).
    """
    if not is_coordinator():
        return {"n": 0, "n_errors": 0, "accuracy": 0.0}
    texts = texts[:max_samples]
    labels = labels[:max_samples]
    responses = client.complete_many(texts, system=_SYSTEM)
    n_errors = 0
    n_correct = 0
    n_scored = 0
    for text, label, response in zip(texts, labels, responses):
        logger.info("Prompt: %s...", text[:log_chars])
        logger.info("Response: %s...", response[:log_chars])
        logger.info("Actual label: %d", label)
        if response == ERROR_SENTINEL:
            n_errors += 1
            continue
        lowered = response.lower()
        predicted = 1 if "positive" in lowered else 0 if "negative" in lowered else None
        if predicted is not None:
            n_scored += 1
            n_correct += int(predicted == label)
    accuracy = n_correct / n_scored if n_scored else 0.0
    logger.info(
        "api eval: %d samples, %d errors, accuracy %.3f", len(texts), n_errors, accuracy
    )
    return {"n": len(texts), "n_errors": n_errors, "accuracy": accuracy}
