"""Single launcher for all hosts (L6).

The reference needs one hand-edited Bash script per node, differing only in
``--node_rank`` (ref ``scripts/run_node0.sh:13`` vs ``run_node1.sh:13``), plus
NCCL env tuning. On TPU VMs every host runs *the same command* —
``jax.distributed.initialize`` discovers rank/world topology from the TPU
metadata — so the launcher collapses to one CLI (BASELINE.json north star:
'run_node0.sh + run_node1.sh collapse into a single TPU-VM launcher'):

    python -m ditl_tpu.launch --preset tiny-llama mesh.fsdp=8 train.total_steps=50

CPU simulation of an N-device pod (SURVEY.md §4's repaired test strategy):

    python -m ditl_tpu.launch --simulate 8 data.synthetic=true
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from ditl_tpu.config import Config, parse_overrides
from ditl_tpu.models.presets import get_preset


def build_config(argv: list[str] | None = None) -> Config:
    parser = argparse.ArgumentParser(
        prog="ditl_tpu.launch",
        description="TPU-native distributed fine-tuning launcher (one command, every host)",
    )
    parser.add_argument("--preset", default=None, help="model preset name")
    parser.add_argument(
        "--simulate", type=int, default=0, help="simulate N CPU devices (no TPU needed)"
    )
    parser.add_argument(
        "--distributed", action="store_true", help="multi-host: call jax.distributed.initialize"
    )
    parser.add_argument("--coordinator", default=None, help="host:port of process 0")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--print-config", action="store_true")
    parser.add_argument(
        "--supervise", action="store_true",
        help="run training in a child PROCESS and restart it (up to "
        "train.max_restarts) on any death — including SIGKILL/host-crash "
        "class failures the in-process supervisor cannot catch; each "
        "restart resumes from the latest Orbax checkpoint",
    )
    parser.add_argument(
        "overrides", nargs="*", help="config overrides like train.total_steps=50"
    )
    args = parser.parse_args(argv)

    config = Config()
    if args.preset:
        config = dataclasses.replace(config, model=get_preset(args.preset))
    # `model.name=<preset>` in overrides swaps in the preset shapes FIRST, so
    # later model.* overrides layer on top of it rather than being silently
    # ignored or applied to the tiny default shapes.
    from ditl_tpu.models.presets import PRESETS

    for item in args.overrides:
        if item.startswith("model.name=") and item.split("=", 1)[1] in PRESETS:
            config = dataclasses.replace(
                config, model=get_preset(item.split("=", 1)[1])
            )
    config = dataclasses.replace(
        config,
        runtime=dataclasses.replace(
            config.runtime,
            simulate_devices=args.simulate,
            distributed=args.distributed,
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        ),
    )
    config = parse_overrides(config, args.overrides)
    if args.print_config:
        print(config.to_json())
        sys.exit(0)
    return config


def run_supervised(config: Config) -> dict:
    """Restart supervisor — the analog of torchrun's elastic ``--max_restarts``
    (which the reference launches through but never configures, ref
    ``scripts/run_node0.sh:10``, SURVEY.md §5 'failure detection'). On an
    unhandled training exception, re-enters ``train()`` up to
    ``train.max_restarts`` times; each retry resumes from the latest Orbax
    checkpoint (``init_runtime`` is idempotent, so re-entry is in-process).
    Recovery requires somewhere to recover FROM: without ``checkpoint_dir`` +
    ``resume`` the exception propagates immediately."""
    import logging

    from ditl_tpu.train.trainer import train

    restarts = 0
    while True:
        try:
            summary = train(config)
            summary["restarts"] = restarts
            return summary
        except Exception:
            if (
                restarts >= config.train.max_restarts
                or not config.train.checkpoint_dir
                or not config.train.resume
            ):
                raise
            restarts += 1
            logging.getLogger(__name__).exception(
                "training failed; restart %d/%d from latest checkpoint",
                restarts,
                config.train.max_restarts,
            )


def run_process_supervised(argv: list[str]) -> int:
    """Process-level restart supervisor: spawn the launcher as a child
    process and restart it when it dies abnormally — the recovery story for
    SIGKILL/OOM/host-crash failures that never reach a Python except block
    (``run_supervised`` handles only in-process exceptions). Resumption
    correctness comes from the same Orbax checkpoint + data-iterator
    position the in-process path uses."""
    import logging
    import subprocess

    logger = logging.getLogger(__name__)
    child_argv = [a for a in argv if a != "--supervise"]
    config = build_config(child_argv)
    can_resume = bool(config.train.checkpoint_dir and config.train.resume)
    restarts = 0
    while True:
        rc = subprocess.call(
            [sys.executable, "-m", "ditl_tpu.launch", *child_argv]
        )
        if rc == 0:
            return 0
        if restarts >= config.train.max_restarts or not can_resume:
            logger.error(
                "training process exited rc=%d; giving up (%d restarts used, "
                "resume %s)", rc, restarts, "on" if can_resume else "off",
            )
            return rc
        restarts += 1
        logger.error(
            "training process exited rc=%d; restart %d/%d from latest "
            "checkpoint", rc, restarts, config.train.max_restarts,
        )


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if "--supervise" in argv:
        return run_process_supervised(argv)
    config = build_config(argv)
    try:
        summary = run_supervised(config)
    except Exception:
        import logging

        logging.getLogger(__name__).exception("training failed")
        return 1
    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
