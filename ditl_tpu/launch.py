"""Single launcher for all hosts (L6).

The reference needs one hand-edited Bash script per node, differing only in
``--node_rank`` (ref ``scripts/run_node0.sh:13`` vs ``run_node1.sh:13``), plus
NCCL env tuning. On TPU VMs every host runs *the same command* —
``jax.distributed.initialize`` discovers rank/world topology from the TPU
metadata — so the launcher collapses to one CLI (BASELINE.json north star:
'run_node0.sh + run_node1.sh collapse into a single TPU-VM launcher'):

    python -m ditl_tpu.launch --preset tiny-llama mesh.fsdp=8 train.total_steps=50

CPU simulation of an N-device pod (SURVEY.md §4's repaired test strategy):

    python -m ditl_tpu.launch --simulate 8 data.synthetic=true

The persistent XLA compilation cache is on by default
(``runtime.compile_cache_dir``, wired through ``init_runtime``): restarts,
elastic relaunches, and repeat runs of an unchanged config skip the
multi-minute first compile. ``runtime.compile_cache_dir=`` disables it;
docs/troubleshooting.md §20 covers staleness.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from ditl_tpu.config import Config, parse_overrides
from ditl_tpu.models.presets import get_preset


def build_config(argv: list[str] | None = None) -> Config:
    parser = argparse.ArgumentParser(
        prog="ditl_tpu.launch",
        description="TPU-native distributed fine-tuning launcher (one command, every host)",
        # No prefix abbreviation: every host (and the pod controller's
        # rendezvous-clash guard) must see the same literal flag tokens —
        # an abbreviated --coord would bypass the --pod ownership check.
        allow_abbrev=False,
    )
    parser.add_argument("--preset", default=None, help="model preset name")
    parser.add_argument(
        "--simulate", type=int, default=0, help="simulate N CPU devices (no TPU needed)"
    )
    parser.add_argument(
        "--distributed", action="store_true", help="multi-host: call jax.distributed.initialize"
    )
    parser.add_argument("--coordinator", default=None, help="host:port of process 0")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--print-config", action="store_true")
    parser.add_argument(
        "--supervise", action="store_true",
        help="run training in a child PROCESS and restart it (up to "
        "train.max_restarts) on any death — including SIGKILL/host-crash "
        "class failures the in-process supervisor cannot catch; each "
        "restart resumes from the latest Orbax checkpoint",
    )
    parser.add_argument(
        "--pod", type=int, default=0,
        help="with --supervise: run an elastic POD of N distributed worker "
        "processes on this host (runtime/elastic.py) — any worker death "
        "tears down the survivors and relaunches the whole pod on a fresh "
        "coordinator port, resuming from the multi-host Orbax checkpoint",
    )
    parser.add_argument(
        "overrides", nargs="*", help="config overrides like train.total_steps=50"
    )
    args = parser.parse_args(argv)
    if args.pod and not args.supervise:
        parser.error("--pod requires --supervise (the elastic pod controller)")

    config = Config()
    if args.preset:
        config = dataclasses.replace(config, model=get_preset(args.preset))
    # `model.name=<preset>` in overrides swaps in the preset shapes FIRST, so
    # later model.* overrides layer on top of it rather than being silently
    # ignored or applied to the tiny default shapes.
    from ditl_tpu.models.presets import PRESETS

    for item in args.overrides:
        if item.startswith("model.name=") and item.split("=", 1)[1] in PRESETS:
            config = dataclasses.replace(
                config, model=get_preset(item.split("=", 1)[1])
            )
    config = dataclasses.replace(
        config,
        runtime=dataclasses.replace(
            config.runtime,
            simulate_devices=args.simulate,
            distributed=args.distributed,
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        ),
    )
    config = parse_overrides(config, args.overrides)
    if args.print_config:
        print(config.to_json())
        sys.exit(0)
    return config


def run_supervised(config: Config) -> dict:
    """Restart supervisor — the analog of torchrun's elastic ``--max_restarts``
    (which the reference launches through but never configures, ref
    ``scripts/run_node0.sh:10``, SURVEY.md §5 'failure detection'). On an
    unhandled training exception, re-enters ``train()`` up to
    ``train.max_restarts`` times; each retry resumes from the latest Orbax
    checkpoint (``init_runtime`` is idempotent, so re-entry is in-process).
    Recovery requires somewhere to recover FROM: without ``checkpoint_dir`` +
    ``resume`` the exception propagates immediately."""
    import logging

    from ditl_tpu.train.trainer import train

    restarts = 0
    while True:
        try:
            summary = train(config)
            summary["restarts"] = restarts
            return summary
        except Exception:
            if (
                config.runtime.distributed
                or restarts >= config.train.max_restarts
                or not config.train.checkpoint_dir
                or not config.train.resume
            ):
                # Distributed: NEVER retry solo — re-entering train() while
                # the peers sit mid-collective at a later step desyncs the
                # pod into a permanent wedge. Die loudly instead; pod-level
                # recovery (the controller relaunching ALL workers,
                # runtime/elastic.py) is the only sound restart.
                raise
            restarts += 1
            logging.getLogger(__name__).exception(
                "training failed; restart %d/%d from latest checkpoint",
                restarts,
                config.train.max_restarts,
            )


def _strip_supervisor_args(argv: list[str]) -> list[str]:
    """Remove --supervise and --pod N/--pod=N from an argv: workers must not
    recursively supervise."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--supervise" or a.startswith("--pod="):
            continue
        if a == "--pod":
            skip = True
            continue
        out.append(a)
    return out


def run_process_supervised(argv: list[str], num_workers: int = 1) -> int:
    """Process-level restart supervisor over the elastic pod controller
    (runtime/elastic.py) — the recovery story for SIGKILL/OOM/host-crash
    failures that never reach a Python except block (``run_supervised``
    handles only in-process exceptions).

    ``num_workers == 1`` (plain ``--supervise``) runs one non-distributed
    child and restarts it on abnormal death. ``num_workers > 1``
    (``--supervise --pod N``) runs N distributed workers rendezvousing on a
    controller-owned coordinator port; ANY worker death tears down the
    survivors (wedged in collectives with a dead peer) and relaunches the
    whole pod on a fresh port. Resumption correctness comes from the same
    multi-host Orbax checkpoint + data-iterator position in both modes."""
    import logging

    from ditl_tpu.runtime.elastic import PodController

    logger = logging.getLogger(__name__)
    child_argv = _strip_supervisor_args(argv)
    if num_workers > 1:
        # Reject-don't-drop: the controller OWNS rendezvous in pod mode — it
        # assigns a fresh coordinator port per generation and a distinct
        # process id per worker. User-supplied rendezvous flags would
        # argparse-last-win over the controller's (duplicate process ids,
        # fixed ports across relaunches), so refuse them loudly.
        owned = ("--distributed", "--coordinator", "--num-processes",
                 "--process-id",
                 # ...and the override spellings of the same fields, which
                 # parse_overrides applies AFTER the flag-derived config.
                 "runtime.distributed", "runtime.coordinator_address",
                 "runtime.num_processes", "runtime.process_id")
        clashes = [
            a for a in child_argv
            if a in owned or any(a.startswith(f"{o}=") for o in owned)
        ]
        if clashes:
            raise SystemExit(
                "ditl_tpu.launch: error: --pod manages rendezvous itself; "
                f"remove {' '.join(sorted(set(clashes)))}"
            )
    config = build_config(child_argv)
    can_resume = bool(config.train.checkpoint_dir and config.train.resume)
    if num_workers == 1 and config.runtime.distributed:
        # A single supervised child that is one member of a LARGER pod must
        # never be solo-restarted: relaunching it against peers sitting
        # mid-collective at a later step wedges the whole pod (the same
        # desync run_supervised's in-process guard forbids). Let the failure
        # propagate; pod-level recovery (--pod on one host, or an external
        # controller restarting EVERY host) is the only sound restart.
        can_resume = False

    def build_argv(proc_id: int, nproc: int, port: int, attempt: int):
        worker = [sys.executable, "-m", "ditl_tpu.launch"]
        if nproc > 1:
            worker += [
                "--distributed", "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", str(nproc), "--process-id", str(proc_id),
            ]
        return worker + child_argv

    def on_restart(failure_rc, restarts, max_restarts):
        logger.error(
            "training process exited rc=%d; restart %d/%d from latest "
            "checkpoint", failure_rc, restarts, max_restarts,
        )

    controller = PodController(
        num_workers,
        build_argv,
        max_pod_restarts=config.train.max_restarts if can_resume else 0,
        heartbeat_dir=config.train.heartbeat_dir,
        heartbeat_timeout_s=config.train.heartbeat_timeout_s,
        # Slow-not-dead escalation (ISSUE 5): heartbeat STEP lag vs. the
        # pod median, journaled `pod.straggler`, optionally relaunching.
        straggler_lag_steps=config.train.straggler_lag_steps,
        straggler_relaunch=config.train.straggler_relaunch,
        # The trainer emits heartbeats under its jax.process_index(): the
        # worker slot for a controller-owned pod, but the configured (or,
        # when rank is autodetected, unknowable — None = wildcard) process
        # id for a single supervised member of a larger pod.
        heartbeat_ids=(
            None if num_workers > 1
            else [config.runtime.process_id if config.runtime.distributed else 0]
        ),
        # State transitions on stderr for debuggability (the child's summary
        # JSON owns stdout).
        log=lambda msg: print(msg, file=sys.stderr, flush=True),
        on_restart=on_restart,
        # The workers journal under the same dir (train.telemetry_dir), so
        # the controller's end-of-run merge yields one ordered pod timeline.
        journal_dir=config.train.telemetry_dir,
        # Size control (ISSUE 6 satellite): telemetry.journal_max_mb caps
        # every per-process journal via segment rotation.
        journal_max_bytes=config.telemetry.journal_max_bytes(),
        # Anomaly/incident plane (ISSUE 10): worker deaths, heartbeat
        # stalls, and straggler escalations assemble liveness-ring bundles
        # under a controller-owned subdirectory (the workers' trainer-side
        # managers write their own).
        incident_dir=(
            os.path.join(config.telemetry.incident_dir, "controller")
            if config.telemetry.incident_dir else ""
        ),
        incident_kwargs=config.telemetry.incident_kwargs(),
    )
    result = controller.run()
    if not result.ok:
        rc = result.returncode
        logger.error(
            "training process exited rc=%d; giving up (%d restarts used, "
            "resume %s)", rc, result.restarts, "on" if can_resume else "off",
        )
        return rc
    return 0


def _pod_size(argv: list[str]) -> int:
    """Parse --pod N / --pod=N without argparse (main must decide the
    supervisor mode before any config parsing)."""
    for i, a in enumerate(argv):
        value = None
        if a == "--pod":
            if i + 1 >= len(argv):
                raise SystemExit(
                    "ditl_tpu.launch: error: --pod expects a worker count"
                )
            value = argv[i + 1]
        elif a.startswith("--pod="):
            value = a.split("=", 1)[1]
        if value is not None:
            try:
                n = int(value)
            except ValueError:
                n = -1
            if n < 0:
                raise SystemExit(
                    f"ditl_tpu.launch: error: --pod expects a worker count "
                    f">= 0, got {value!r}"
                )
            # 0 is the documented default: "no pod" — plain single-child
            # supervision, so templated `--pod $N` invocations degrade
            # gracefully.
            return n
    return 0


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "gateway":
        # Serving-gateway subcommand (ditl_tpu/gateway/, ISSUE 4): spawn N
        # subprocess replicas of infer/server.py and front them with one
        # OpenAI-compatible endpoint. Deliberately dispatched before the
        # training argparse — the gateway has its own CLI surface.
        from ditl_tpu.gateway.gateway import main as gateway_main
        from ditl_tpu.utils.logging import setup_logging

        setup_logging()
        return gateway_main(argv[1:])
    if "--supervise" in argv:
        return run_process_supervised(argv, max(1, _pod_size(argv)))
    config = build_config(argv)
    try:
        summary = run_supervised(config)
    except Exception:
        import logging

        logging.getLogger(__name__).exception("training failed")
        return 1
    # Only the coordinator answers on stdout — in a pod every worker runs
    # this identical program and N copies of the summary would interleave.
    from ditl_tpu.runtime.distributed import is_coordinator

    if is_coordinator():
        print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
