"""Source annotations the static passes (ditl_tpu/analysis/) key on.
Stdlib-only and zero-cost at runtime — these exist so invariants live
NEXT TO the code they bind, where a reviewer (and the analyzer) can see
them, instead of in a test file three directories away.

Deliberately OUTSIDE the analysis package: hot-path modules (the engine,
the flight recorder, the metrics logger) import the marker, and pulling
it from ``ditl_tpu.analysis`` would execute the whole analyzer framework
(rule registration and all) in every serving/training process just to
obtain a no-op decorator.

``@hot_path``
    Marks a function as device-sync-free by contract: the scheduler tick
    loop, flight-ring record paths, and the metrics record methods — the
    places where one stray ``jax.device_get`` / ``.block_until_ready()`` /
    ``float(device_array)`` stalls the pipeline for every request (the
    exact class of bug the PR 3 flush fix and the PR 10 five-device_get
    pin were fighting). The ``blocking-transfer`` rule flags blocking
    spellings inside any function carrying this decorator; a genuinely
    host-side cast gets a reasoned pragma, never an unmark.

``@event_loop``
    Marks a function as running ON the gateway's single-threaded
    selectors loop (ISSUE 17): one blocking call there stalls every open
    connection and stream at once, not just one request. The
    ``event-loop-hygiene`` rule flags blocking spellings inside any
    function carrying this decorator — ``sleep``, ``.sendall(``,
    ``.join(``, and lock waits without a ``# guarded-by:`` witness.
    Plain ``.recv(`` is deliberately NOT flagged: loop-owned sockets are
    non-blocking by construction (``setblocking(False)`` at accept/
    detach), so recv returns immediately; the flagged spellings block
    (or raise mid-write, for sendall) regardless of socket mode.

``# guarded-by: <lock>`` (trailing comment on the attribute's defining
    assignment)
    Declares that an attribute may only be read or written inside a
    ``with self.<lock>:`` block of the same class. The ``lock-discipline``
    rule enforces it lexically; methods named ``*_locked`` are exempt by
    convention (they document that the CALLER holds the lock — the same
    contract the suffix already communicates to a human reader).
"""

from __future__ import annotations

__all__ = ["event_loop", "hot_path"]


def hot_path(fn):
    """No-op marker decorator: the decorated function promises to never
    block on a device transfer. Enforced statically by the
    ``blocking-transfer`` rule (ditl_tpu/analysis/rules_hotpath.py); the
    attribute below is for runtime introspection and tests."""
    fn.__ditl_hot_path__ = True
    return fn


def event_loop(fn):
    """No-op marker decorator: the decorated function runs on the
    gateway's selectors event loop and promises never to block it.
    Enforced statically by the ``event-loop-hygiene`` rule
    (ditl_tpu/analysis/rules_evloop.py); the attribute below is for
    runtime introspection and tests."""
    fn.__ditl_event_loop__ = True
    return fn
