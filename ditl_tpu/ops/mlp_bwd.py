"""Pallas fused-backward kernels for the fused-gate|up SwiGLU MLP block.

The r5 custom-VJP null (BASELINE.md, experiments/bwd_levers.py) proved the
~40 ms MLP backward residual is XLA's in-step *schedule*, not the einsum
spelling: re-emitting the same contractions by hand changed nothing, because
XLA still owned tiling and interleaving. This module takes the next step the
r5 verdict named — take the backward out of XLA's hands entirely, the same
move ops/flash_attention.py made for attention — by emitting the whole block
backward as a tightly-scheduled PAIR of Pallas (Mosaic) kernels:

- **Pass 1** (grid ``(F/bf, N/bn)``, token dim sequential-innermost): per
  (f, n) tile, compute ``dinner = g @ w_down^T``, recompute the elementwise
  SwiGLU pieces from the stored ``gate``/``up`` residuals (the "dots"-policy
  choice — no extra HBM residuals), emit ``dgate``/``dup`` tiles, and
  accumulate ``d_w_down = inner^T @ g`` in a VMEM f32 scratch written out on
  the last token tile. ``g`` is read once per f-block; the elementwise
  recompute and BOTH consumers of ``dinner`` live in one kernel instance,
  so nothing is ever re-materialized through HBM.
- **Pass 2** (grid ``(D/bd, N/bn)``): per (d, n) tile, ``dh = dgu @ w_gu^T``
  (full 2F contracted in-step) and ``d_w_gu = h^T @ dgu`` accumulated in
  VMEM, sharing the ``dgu`` tile between both products.

Between the passes, ``dgu = concat(dgate, dup)`` is one XLA concat — the
same (N, 2F) intermediate XLA's own backward materializes.

Tiling targets v5e's ~16M scoped VMEM at the pinned 1b3 bench shapes
(D=2048, F=5632, N=8192): pass 1 at (bn=256, bf=512) holds ~10.5 MB; pass 2
at (bn=256, bd=128) holds ~14.6 MB (the (bd, 2F) f32 accumulator dominates —
``ModelConfig.mlp_bwd_block_*`` sweeps the tradeoff per chip). Off-TPU the
kernels run in interpret mode, so the same numerics tests run on CPU
(tests/test_bwd_kernels.py).

Adoption protocol (the VJP-null rigor): the kernel ships behind
``ModelConfig.mlp_bwd_impl`` and is adopted into the pinned bench config only
on an adjacent on-chip A/B win (experiments/bwd_kernels.py); a loss is
documented as a kernel-level definitive null, never silently dropped.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ditl_tpu.utils.compat import tpu_compiler_params

__all__ = ["fused_mlp_bwd", "supports", "DEFAULT_BLOCKS"]

NUM_LANES = 128
NUM_SUBLANES = 16  # bf16-safe sublane multiple (f32 needs only 8)


class BlockSizes(NamedTuple):
    block_n: int  # token tile (sublane dim of activation tiles)
    block_f: int  # intermediate-dim tile (pass 1)
    block_d: int  # hidden-dim tile (pass 2)


# Defaults sized for the 1b3 bench shapes on v5e (see module docstring);
# ModelConfig.mlp_bwd_block_{n,f,d} override per chip/model.
DEFAULT_BLOCKS = BlockSizes(256, 512, 128)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_blocks(n: int, d: int, f: int, blocks) -> BlockSizes:
    bn, bf, bd = blocks or (0, 0, 0)
    bn, bf, bd = (bn or DEFAULT_BLOCKS.block_n, bf or DEFAULT_BLOCKS.block_f,
                  bd or DEFAULT_BLOCKS.block_d)
    return BlockSizes(min(bn, n), min(bf, f), min(bd, d))


def supports(n: int, d: int, f: int, blocks=None) -> bool:
    """True if the kernels can tile (N=B*S tokens, D hidden, F intermediate).
    Callers (ops/mlp.py) fall back to the einsum-spelled backward otherwise —
    the bench JSON records which implementation actually ran, so an A/B can
    never silently measure the fallback."""
    bn, bf, bd = _pick_blocks(n, d, f, blocks)
    return (
        n % bn == 0
        and f % bf == 0
        and d % bd == 0
        and bn % NUM_SUBLANES == 0
        # Full-D rows in pass 1 and full-2F rows in pass 2 sit on lanes.
        and d % NUM_LANES == 0
        and bf % NUM_LANES == 0
        and bd % NUM_LANES == 0
    )


# ---------------------------------------------------------------------------
# Pass 1: dgate/dup tiles + d_w_down
# ---------------------------------------------------------------------------


def _dgu_dwdown_kernel(
    g_ref,      # (bn, D)
    wd_ref,     # (bf, D)
    gate_ref,   # (bn, bf)
    up_ref,     # (bn, bf)
    dgate_ref,  # (bn, bf) out
    dup_ref,    # (bn, bf) out
    dwd_ref,    # (bf, D) out, written on the last token tile
    acc_ref,    # (bf, D) f32 VMEM scratch
    *,
    n_n: int,
):
    i_n = pl.program_id(1)

    @pl.when(i_n == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    g = g_ref[...]
    wd = wd_ref[...]
    # dinner tile: both weight-grad and activation-grad consumers below read
    # this one f32 register-resident product — the shared read the issue's
    # schedule argument is about.
    dinner = jax.lax.dot_general(
        g, wd, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (bn, bf)
    gate = gate_ref[...].astype(jnp.float32)
    up = up_ref[...].astype(jnp.float32)
    sg = jax.nn.sigmoid(gate)
    silu = gate * sg
    # Same d/dgate spelling as ops/mlp.py's einsum backward (bit-for-bit in
    # f32): silu'(gate) = sg * (1 + gate * (1 - sg)).
    dgate = dinner * up * (sg * (1.0 + gate * (1.0 - sg)))
    dup = dinner * silu
    dgate_ref[...] = dgate.astype(dgate_ref.dtype)
    dup_ref[...] = dup.astype(dup_ref.dtype)
    inner = (silu * up).astype(g.dtype)
    acc_ref[...] += jax.lax.dot_general(
        inner, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # (bf, D)

    @pl.when(i_n == n_n - 1)
    def _finalize():
        dwd_ref[...] = acc_ref[...].astype(dwd_ref.dtype)


# ---------------------------------------------------------------------------
# Pass 2: dh + d_w_gu
# ---------------------------------------------------------------------------


def _dh_dwgu_kernel(
    h_ref,      # (bn, bd)
    dgu_ref,    # (bn, 2F)
    wgu_ref,    # (bd, 2F)
    dh_ref,     # (bn, bd) out
    dwgu_ref,   # (bd, 2F) out, written on the last token tile
    acc_ref,    # (bd, 2F) f32 VMEM scratch
    *,
    n_n: int,
):
    i_n = pl.program_id(1)

    @pl.when(i_n == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dgu = dgu_ref[...]
    dh = jax.lax.dot_general(
        dgu, wgu_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (bn, bd): full 2F contracted in-step, no cross-step accumulation
    dh_ref[...] = dh.astype(dh_ref.dtype)
    acc_ref[...] += jax.lax.dot_general(
        h_ref[...], dgu, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (bd, 2F)

    @pl.when(i_n == n_n - 1)
    def _finalize():
        dwgu_ref[...] = acc_ref[...].astype(dwgu_ref.dtype)


# ---------------------------------------------------------------------------
# Wrapper
# ---------------------------------------------------------------------------


def fused_mlp_bwd(
    h: jax.Array,      # (B, S, D)
    w_gu: jax.Array,   # (D, 2F)
    w_down: jax.Array,  # (F, D)
    gate: jax.Array,   # (B, S, F) forward residual
    up: jax.Array,     # (B, S, F) forward residual
    g: jax.Array,      # (B, S, D) output cotangent
    *,
    blocks=None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The fused-gate|up MLP block backward as the two Pallas passes above.
    Returns ``(dh, d_w_gu, d_w_down)`` matching ops/mlp.py's einsum backward
    to f32 tolerance (exactly, in f32). Raises ``ValueError`` on shapes
    ``supports`` rejects."""
    b, s, d = h.shape
    f = w_down.shape[0]
    n = b * s
    if not supports(n, d, f, blocks):
        raise ValueError(
            f"fused_mlp_bwd cannot tile N={n} D={d} F={f} (blocks={blocks})"
        )
    bn, bf, bd = _pick_blocks(n, d, f, blocks)
    if interpret is None:
        interpret = _interpret_default()

    h2 = h.reshape(n, d)
    g2 = g.reshape(n, d)
    gate2 = gate.reshape(n, f)
    up2 = up.reshape(n, f)
    n_n, n_f, n_d = n // bn, f // bf, d // bd

    dgate, dup, d_w_down = pl.pallas_call(
        functools.partial(_dgu_dwdown_kernel, n_n=n_n),
        grid=(n_f, n_n),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i_f, i_n: (i_n, 0)),    # g
            pl.BlockSpec((bf, d), lambda i_f, i_n: (i_f, 0)),    # w_down
            pl.BlockSpec((bn, bf), lambda i_f, i_n: (i_n, i_f)),  # gate
            pl.BlockSpec((bn, bf), lambda i_f, i_n: (i_n, i_f)),  # up
        ],
        out_specs=(
            pl.BlockSpec((bn, bf), lambda i_f, i_n: (i_n, i_f)),
            pl.BlockSpec((bn, bf), lambda i_f, i_n: (i_n, i_f)),
            pl.BlockSpec((bf, d), lambda i_f, i_n: (i_f, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, f), g.dtype),
            jax.ShapeDtypeStruct((n, f), g.dtype),
            jax.ShapeDtypeStruct((f, d), w_down.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((bf, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(g2, w_down, gate2, up2)

    # One concat — the same (N, 2F) intermediate XLA's backward builds; the
    # gate|up column order matches the fused w_gu layout.
    dgu = jnp.concatenate([dgate, dup], axis=-1)

    dh2, d_w_gu = pl.pallas_call(
        functools.partial(_dh_dwgu_kernel, n_n=n_n),
        grid=(n_d, n_n),
        in_specs=[
            pl.BlockSpec((bn, bd), lambda i_d, i_n: (i_n, i_d)),    # h
            pl.BlockSpec((bn, 2 * f), lambda i_d, i_n: (i_n, 0)),   # dgu
            pl.BlockSpec((bd, 2 * f), lambda i_d, i_n: (i_d, 0)),   # w_gu
        ],
        out_specs=(
            pl.BlockSpec((bn, bd), lambda i_d, i_n: (i_n, i_d)),
            pl.BlockSpec((bd, 2 * f), lambda i_d, i_n: (i_d, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, d), h.dtype),
            jax.ShapeDtypeStruct((d, 2 * f), w_gu.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((bd, 2 * f), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(h2, dgu, w_gu)

    return dh2.reshape(b, s, d), d_w_gu, d_w_down
