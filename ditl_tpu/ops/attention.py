"""Attention implementations (L1).

The reference contains no attention code at all (SURVEY.md §5 'long-context':
the 70B model lives behind an HTTP API). Here attention is a first-class op
with three interchangeable implementations selected by
``ModelConfig.attention_impl``:

- ``"xla"``:   einsum + softmax, fully fused by XLA. Correctness reference.
- ``"flash"``: Pallas (Mosaic) blockwise FlashAttention kernel — O(S) memory,
               tiles sized for MXU/VMEM (ops/flash_attention.py).
- ``"ring"``:  ring attention over the ``sequence`` mesh axis for contexts
               longer than one chip's HBM (ops/ring_attention.py).

All take GQA-layout tensors: q ``(B, S, H, D)``, k/v ``(B, S, K, D)`` with
``H % K == 0``; softmax is computed in float32 regardless of input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["dot_product_attention"]

NEG_INF = -2.3819763e38  # most-negative bf16-representable; avoids bf16 NaNs


def _xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    segment_ids: jax.Array | None,
) -> jax.Array:
    b, s_q, h, d = q.shape
    _, s_kv, kv_heads, _ = k.shape
    groups = h // kv_heads
    qg = q.reshape(b, s_q, kv_heads, groups, d)
    scale = d**-0.5
    # (B, K, G, Sq, Skv) scores; accumulate in f32 on the MXU.
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((s_q, s_kv), dtype=bool))
        scores = jnp.where(causal_mask[None, None, None], scores, NEG_INF)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]  # (B,Sq,Skv)
        scores = jnp.where(seg_mask[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
    return out.reshape(b, s_q, h, d)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: jax.Array | None = None,
    impl: str = "xla",
    mesh=None,
) -> jax.Array:
    """Grouped-query attention. ``segment_ids`` (B, S) int32 restricts
    attention to tokens of the same segment (sequence packing / padding:
    give pad tokens a segment id of -1-ish sentinel distinct from real ones)."""
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"q heads {q.shape[2]} not divisible by kv heads {k.shape[2]}")
    if impl == "xla":
        return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if impl == "flash":
        from ditl_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if impl == "ring":
        from ditl_tpu.ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, mesh=mesh
        )
    raise ValueError(f"unknown attention impl {impl!r}")
