"""Attention implementations (L1).

The reference contains no attention code at all (SURVEY.md §5 'long-context':
the 70B model lives behind an HTTP API). Here attention is a first-class op
with four interchangeable implementations selected by
``ModelConfig.attention_impl``:

- ``"xla"``:   einsum + softmax, fully fused by XLA. Correctness reference.
- ``"flash"``: Pallas (Mosaic) blockwise FlashAttention kernel — O(S) memory,
               tiles sized for MXU/VMEM (ops/flash_attention.py).
- ``"ring"``:  ring attention over the ``sequence`` mesh axis for contexts
               longer than one chip's HBM (ops/ring_attention.py).
- ``"ulysses"``: all-to-all sequence parallelism over the same axis — heads
               re-sharded instead of KV rotated (ops/ulysses.py).

All take GQA-layout tensors: q ``(B, S, H, D)``, k/v ``(B, S, K, D)`` with
``H % K == 0``; softmax is computed in float32 regardless of input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from ditl_tpu.utils.compat import shard_map

__all__ = ["dot_product_attention"]

NEG_INF = -2.3819763e38  # most-negative bf16-representable; avoids bf16 NaNs


def _xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    segment_ids: jax.Array | None,
    mask: jax.Array | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """``k_scale``/``v_scale`` (B, Skv, K) mark k/v as int8-quantized
    (infer/cache.py). The scales are factored OUT of the dots: the score
    matmul consumes raw int8 K (the int8->bf16 convert fuses into the dot's
    operand read, so HBM traffic stays int8-sized) and the per-slot scale
    multiplies the (B,K,G,Sq,Skv) score tile afterwards; likewise V's scale
    folds into the probabilities. Dequantizing before the dot instead would
    materialize a full bf16 cache copy in HBM and forfeit the bandwidth win."""
    b, s_q, h, d = q.shape
    _, s_kv, kv_heads, _ = k.shape
    groups = h // kv_heads
    qg = q.reshape(b, s_q, kv_heads, groups, d)
    scale = d**-0.5
    if k_scale is not None:
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    # (B, K, G, Sq, Skv) scores; accumulate in f32 on the MXU.
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if k_scale is not None:
        scores = scores * jnp.moveaxis(k_scale, 1, 2)[:, :, None, None, :]
    if causal:
        causal_mask = jnp.tril(jnp.ones((s_q, s_kv), dtype=bool))
        scores = jnp.where(causal_mask[None, None, None], scores, NEG_INF)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]  # (B,Sq,Skv)
        scores = jnp.where(seg_mask[:, None, None], scores, NEG_INF)
    if mask is not None:  # explicit (B, Sq, Skv) mask — KV-cache decode path
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        probs = probs * jnp.moveaxis(v_scale, 1, 2)[:, :, None, None, :]
    probs = probs.astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
    return out.reshape(b, s_q, h, d)


def _mesh_axes_size(mesh, axes) -> int:
    """Product of mesh-axis sizes for a rules value (str, tuple, or None).
    Canonical definition lives in parallel/sharding.mesh_axes_size; this
    alias keeps the op module's historical import surface."""
    from ditl_tpu.parallel.sharding import mesh_axes_size

    return mesh_axes_size(mesh, axes)


def _seq_sharded_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array,
    *,
    mesh,
    rules,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Flash-decoding over ICI: the KV cache's CONTEXT dim is sharded over
    the ``sequence`` mesh axis ("cache_seq" rule), each device computes
    partial attention over its context shard with online-softmax stats
    (m, l, unnormalized o), and the shards merge with one pmax + two psums
    — the standard log-sum-exp combine, so the result equals the unsharded
    softmax up to float addition order. Context capacity then scales with
    the mesh instead of one chip's HBM, and per-device attention reads
    drop by the shard factor. int8 KV composes: scales are per-position
    and shard with their positions."""
    from ditl_tpu.parallel.sharding import logical_to_spec

    seq_axes = rules.get("cache_seq")
    seq_axes = (seq_axes,) if isinstance(seq_axes, str) else tuple(seq_axes)
    scale = q.shape[-1] ** -0.5

    def local(q_, k_, v_, mask_, ks_, vs_):
        b, s_q, h, d = q_.shape
        kh = k_.shape[2]
        g = h // kh
        qg = q_.reshape(b, s_q, kh, g, d)
        kk, vv = k_, v_
        if ks_ is not None:
            kk = kk.astype(q_.dtype)
            vv = vv.astype(q_.dtype)
        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, kk, preferred_element_type=jnp.float32
        ) * scale
        if ks_ is not None:
            scores = scores * jnp.moveaxis(ks_, 1, 2)[:, :, None, None, :]
        scores = jnp.where(mask_[:, None, None], scores, NEG_INF)
        m = jnp.max(scores, axis=-1)  # (B, K, G, Sq)
        p = jnp.exp(scores - m[..., None])
        l = jnp.sum(p, axis=-1)
        if vs_ is not None:
            p = p * jnp.moveaxis(vs_, 1, 2)[:, :, None, None, :]
        o = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(vv.dtype), vv)
        # log-sum-exp merge across context shards
        m_g = m
        for ax in seq_axes:
            m_g = jax.lax.pmax(m_g, ax)
        corr = jnp.exp(m - m_g)  # (B, K, G, Sq)
        l_g = jax.lax.psum(l * corr, seq_axes)
        o_g = jax.lax.psum(
            o.astype(jnp.float32)
            * jnp.transpose(corr, (0, 3, 1, 2))[..., None],
            seq_axes,
        )
        l_t = jnp.transpose(jnp.maximum(l_g, 1e-30), (0, 3, 1, 2))[..., None]
        out = o_g / l_t  # (B, Sq, K, G, D)
        return out.reshape(b, s_q, h, d).astype(q_.dtype)

    q_spec = logical_to_spec(("batch", None, "act_heads", None), rules)
    kv_spec = logical_to_spec(("batch", "cache_seq", "act_kv_heads", None), rules)
    mask_spec = logical_to_spec(("batch", None, "cache_seq"), rules)
    scale_spec = logical_to_spec(("batch", "cache_seq", "act_kv_heads"), rules)

    if k_scale is None:
        def local4(q_, k_, v_, mask_):
            return local(q_, k_, v_, mask_, None, None)

        return shard_map(
            local4, mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, mask_spec),
            out_specs=q_spec, check_vma=False,
        )(q, k, v, mask)
    return shard_map(
        local, mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, mask_spec, scale_spec, scale_spec),
        out_specs=q_spec, check_vma=False,
    )(q, k, v, mask, k_scale, v_scale)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: jax.Array | None = None,
    mask: jax.Array | None = None,
    impl: str = "xla",
    mesh=None,
    rules=None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    block_sizes: tuple[int, int, int, int] | None = None,
) -> jax.Array:
    """Grouped-query attention. ``segment_ids`` (B, S) int32 restricts
    attention to tokens of the same segment (sequence packing / padding:
    give pad tokens a segment id of -1-ish sentinel distinct from real ones).
    ``mask`` is an explicit (B, Sq, Skv) boolean mask (True = attend), used by
    the KV-cache decode path where validity is per-slot, not causal.

    ``rules`` is the logical-axis table (parallel/sharding.py) used to derive
    shard_map specs for the flash and ring paths — the same single source of
    truth the rest of the model uses for its sharding constraints.

    ``block_sizes`` is ``(block_q, block_kv, block_q_bwd, block_kv_bwd)`` for
    the flash kernels; zeros mean kernel defaults (ModelConfig.flash_block_*)."""
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"q heads {q.shape[2]} not divisible by kv heads {k.shape[2]}")
    if k_scale is not None and mask is None:
        raise ValueError("quantized K/V (k_scale/v_scale) require the mask path")
    if mask is not None:
        # Explicit-mask (decode) path: bandwidth-bound, XLA fuses it fine; the
        # flash/ring kernels are for long training chunks, not 1-token queries.
        if mesh is not None:
            from ditl_tpu.parallel.sharding import (
                DEFAULT_RULES,
                mesh_axes_size,
                seq_shards,
            )

            r = rules if rules is not None else DEFAULT_RULES
            seq_n = seq_shards(mesh, r)
            dp = mesh_axes_size(mesh, r.get("batch"))
            tp = mesh_axes_size(mesh, r.get("act_kv_heads"))
            if (seq_n > 1 and k.shape[1] % seq_n == 0
                    and q.shape[0] % dp == 0 and k.shape[2] % tp == 0
                    and q.shape[2] % max(tp, 1) == 0):
                # Context (KV sequence) sharded over the mesh:
                # flash-decoding-style partial-softmax merge over ICI.
                return _seq_sharded_decode(
                    q, k, v, mask, mesh=mesh, rules=r,
                    k_scale=k_scale, v_scale=v_scale,
                )
        return _xla_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, mask=mask,
            k_scale=k_scale, v_scale=v_scale,
        )
    if impl == "xla":
        return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if impl == "ring":
        from ditl_tpu.ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, mesh=mesh, rules=rules
        )
    if impl == "ulysses":
        from ditl_tpu.ops.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, mesh=mesh, rules=rules
        )
    if impl == "flash":
        from ditl_tpu.ops import flash_attention as fa
        from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

        rules = rules if rules is not None else DEFAULT_RULES
        if mesh is not None and _mesh_axes_size(mesh, rules.get("seq")) > 1:
            # Sequence-sharded activations: ring attention IS the flash path
            # for context parallelism (blockwise kernel distributed over the
            # ring instead of the Pallas grid).
            from ditl_tpu.ops.ring_attention import ring_attention

            return ring_attention(
                q, k, v, causal=causal, segment_ids=segment_ids, mesh=mesh,
                rules=rules,
            )
        bq, bkv, bqb, bkvb = block_sizes or (0, 0, 0, 0)
        bq, bkv = bq or 512, bkv or 512
        if not (fa.supports(q.shape[1], k.shape[1], q.shape[3], bq, bkv)
                and fa.supports(q.shape[1], k.shape[1], q.shape[3],
                                bqb or bq, bkvb or bkv)):
            # Shapes the kernel can't tile (tiny tests, odd seq lens): XLA.
            return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
        if mesh is None:
            return fa.flash_attention(
                q, k, v, causal=causal, segment_ids=segment_ids,
                block_q=bq, block_kv=bkv, block_q_bwd=bqb, block_kv_bwd=bkvb,
            )
        # Pallas calls carry no GSPMD partitioning rules — under pjit they
        # must be explicitly mapped over the mesh. Batch splits over the
        # batch axes and heads over the heads axis; attention is independent
        # along both, so no collectives are induced.
        dp = _mesh_axes_size(mesh, rules.get("batch"))
        tp = _mesh_axes_size(mesh, rules.get("act_heads"))
        if q.shape[0] % dp or q.shape[2] % tp or k.shape[2] % tp:
            # Mesh doesn't divide batch/heads: the shard_map would fail at
            # trace time — use the GSPMD-partitionable XLA path instead.
            return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
        qkv_spec = logical_to_spec(("batch", None, "act_heads", None), rules)
        args = [q, k, v]
        in_specs = [qkv_spec, qkv_spec, qkv_spec]
        if segment_ids is not None:
            args.append(segment_ids)
            in_specs.append(logical_to_spec(("batch", None), rules))

        def local(q_, k_, v_, seg_=None):
            return fa.flash_attention(
                q_, k_, v_, causal=causal, segment_ids=seg_,
                block_q=bq, block_kv=bkv, block_q_bwd=bqb, block_kv_bwd=bkvb,
            )

        return shard_map(
            local,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=qkv_spec,
            check_vma=False,
        )(*args)
    raise ValueError(f"unknown attention impl {impl!r}")
