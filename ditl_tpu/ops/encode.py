"""Capability-parity device op.

The reference's entire on-device workload is ``gpu_tensor_operation(text,
device)``: encode characters as float ordinals, move to device, ``.mean()``,
sync back with ``.item()`` (ref ``src/utils.py:25-28``) — one H2D/D2H round
trip *per example*. The TPU-native version is batched, jitted, and padded to a
static shape so XLA compiles it once; the mean is masked so padding does not
bias it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["encode_texts", "encode_and_reduce"]


def encode_texts(texts: list[str], max_len: int = 1024) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: UTF-8 code points -> padded (B, max_len) float32 + mask."""
    out = np.zeros((len(texts), max_len), dtype=np.float32)
    mask = np.zeros((len(texts), max_len), dtype=np.float32)
    for i, t in enumerate(texts):
        ords = np.frombuffer(t.encode("utf-32-le"), dtype=np.uint32)[:max_len]
        out[i, : len(ords)] = ords.astype(np.float32)
        mask[i, : len(ords)] = 1.0
    return out, mask


@functools.partial(jax.jit, static_argnames=())
def _masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    return (x * mask).sum(axis=-1) / jnp.maximum(mask.sum(axis=-1), 1.0)


def encode_and_reduce(texts: list[str], max_len: int = 1024) -> np.ndarray:
    """Batched equivalent of ``[gpu_tensor_operation(t) for t in texts]``:
    one compiled call, one transfer each way, per-example masked means."""
    x, mask = encode_texts(texts, max_len)
    return np.asarray(_masked_mean(jnp.asarray(x), jnp.asarray(mask)))
