"""Weight-only int8 quantization for inference.

Decoding at small batch is weight-bandwidth-bound: every generated token
re-reads every weight from HBM while the MXU idles. Symmetric per-channel
int8 halves those bytes versus bf16. The matmul consumes the int8 tensor
directly (converted on the fly in-register); the per-output-channel scale is
applied to the matmul *output* — valid because a column scale commutes
through the contraction: ``h @ (q · s_col) == (h @ q) · s_col``. So HBM sees
int8, the MXU sees its native bf16, and accuracy loss is per-channel-bounded.

Quantized leaves are ``{"q": int8 (..., d_in, d_out), "scale": f32
(..., 1, d_out)}`` dicts; ``models/llama.py``'s projection helper detects
them, so the same forward serves float and quantized params (training always
uses float — this is an inference-side transform, applied after
fine-tuning/merging).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["quantize_weights", "is_quantized_leaf", "weight_einsum"]

# Param-tree leaves that are (…, d_in, d_out) matmul weights.
_QUANT_KEYS = ("wq", "wk", "wv", "w_qkv", "wo", "w_gate", "w_up", "w_gu", "w_down", "kernel")


def is_quantized_leaf(w: Any) -> bool:
    return isinstance(w, dict) and set(w) == {"q", "scale"}


def _quantize_matrix(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8 over the input (contraction) dim."""
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)  # (..., 1, d_out)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def weight_einsum(
    pattern: str,
    x: jax.Array,
    w: Any,
    *,
    compute_dtype,
    preferred=None,
) -> jax.Array:
    """``einsum(pattern, x, w)`` where ``w`` is a float matrix OR a quantized
    ``{"q", "scale"}`` leaf. The int8 tensor feeds the matmul directly (HBM
    reads stay int8); the per-output-channel scale multiplies the output.
    Works for any pattern whose last output dim is the weight's ``d_out``
    (scale shape (..., 1, d_out) broadcasts from the right)."""
    if is_quantized_leaf(w):
        out = jnp.einsum(
            pattern,
            x,
            w["q"].astype(compute_dtype),
            preferred_element_type=preferred or compute_dtype,
        )
        return out * w["scale"].astype(out.dtype)
    return jnp.einsum(
        pattern, x, w.astype(compute_dtype),
        preferred_element_type=preferred or compute_dtype,
    )


def quantize_weights(params: Any) -> Any:
    """Quantize the projection/MLP/lm-head weights of a (dense) param tree.

    Norm scales and the embedding table stay float (the embedding is a
    gather, not a matmul; norms are tiny and precision-sensitive). LoRA
    trees must be merged first (models/lora.py) — adapters train in float.
    """
    if "lora" in params.get("layers", {}):
        raise ValueError(
            "merge LoRA adapters before quantizing (models.lora.merge_lora)"
        )

    def walk(tree: Any) -> Any:
        if isinstance(tree, dict):
            return {
                k: _quantize_matrix(v)
                if k in _QUANT_KEYS and not isinstance(v, dict)
                else walk(v)
                for k, v in tree.items()
            }
        return tree

    return walk(params)
