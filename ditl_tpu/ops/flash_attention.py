"""Blockwise FlashAttention as a Pallas (Mosaic) TPU kernel.

The reference has no attention code at all (its model lives behind an HTTP
API — ref ``src/distributed_inference.py:34-41``); this kernel is part of the
TPU-native compute path that replaces the reference's device op
(``src/utils.py:25-28``) with a real transformer forward.

Design (TPU-first):
- **O(S) memory**: online softmax over KV blocks; the (S, S) score matrix is
  never materialized in HBM. Residuals for the backward pass are ``o`` and the
  per-row log-sum-exp.
- **MXU tiling**: q/k/v are consumed in (block, head_dim) tiles; both matmuls
  (``q·kᵀ`` and ``p·v``) run on the MXU with f32 accumulation; the second
  matmul feeds ``p`` in the value dtype (bf16) for MXU throughput.
- **Lane-replicated row stats**: running max ``m`` and normalizer ``l`` are
  kept as (block_q, 128) with all lanes equal — row-broadcasts become free
  elementwise ops, avoiding sublane↔lane transposes Mosaic handles poorly.
  The log-sum-exp residual is stored lane-replicated the same way.
- **GQA-native**: H query heads share H//K KV heads; the KV block index map
  divides the head index, so KV tiles are fetched once per group.
- **Causal block skipping**: fully-masked KV blocks are predicated off with
  ``pl.when`` (the grid still visits them; compute and the second matmul are
  skipped).
- **Custom VJP**: backward runs two Pallas kernels — one accumulating dq over
  KV blocks, one accumulating dk/dv over (group × query) blocks — both
  recomputing p from the saved log-sum-exp (FlashAttention-2 style).

Layouts are (B, H, S, D) inside the kernels (callers pass (B, S, H, D); the
wrapper transposes — XLA fuses the transpose into neighboring ops).
Automatically runs in interpreter mode off-TPU so the same tests run on CPU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

from ditl_tpu.ops.attention import NEG_INF  # single source of the mask value
from ditl_tpu.utils.compat import tpu_compiler_params

NUM_LANES = 128
NUM_SUBLANES = 8


class BlockSizes(NamedTuple):
    block_q: int
    block_kv: int


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_blocks(s_q: int, s_kv: int, block_q: int, block_kv: int) -> BlockSizes:
    return BlockSizes(min(block_q, s_q), min(block_kv, s_kv))


def supports(s_q: int, s_kv: int, head_dim: int, block_q: int = 512,
             block_kv: int = 512) -> bool:
    """True if the kernel can handle these shapes (callers fall back to XLA)."""
    bq, bkv = _pick_blocks(s_q, s_kv, block_q, block_kv)
    return (
        s_q % bq == 0
        and s_kv % bkv == 0
        and bkv % NUM_LANES == 0
        and bq % NUM_SUBLANES == 0
        # _lane_tile can slice (64) or tile whole lanes (128k), nothing else.
        and (head_dim == 64 or head_dim % NUM_LANES == 0)
    )


def _lane_tile(x: jax.Array, width: int) -> jax.Array:
    """Tile a lane-replicated (rows, 128) array to (rows, width)."""
    if width == NUM_LANES:
        return x
    if width < NUM_LANES:
        return x[:, :width]
    return jnp.tile(x, (1, width // NUM_LANES))


def _block_mask(
    s: jax.Array,
    *,
    iq: jax.Array,
    ikv: jax.Array,
    block_q: int,
    block_kv: int,
    causal: bool,
    q_seg: jax.Array | None,
    kv_seg: jax.Array | None,
) -> jax.Array:
    """Apply causal + segment masking to a (block_q, block_kv) score tile."""
    mask = None
    if causal:
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=0
        )
        cols = ikv * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        )
        mask = rows >= cols
    if q_seg is not None:
        # q_seg: (block_q, 128) lane-replicated; kv_seg: (8, block_kv)
        # sublane-replicated. Tile q over lanes, slice kv's first sublane row
        # via broadcasting: both end up (block_q, block_kv).
        qs = _lane_tile(q_seg, s.shape[1])
        ks = kv_seg[:1, :]
        seg = qs == ks
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if mask is None:
        return s
    return jnp.where(mask, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    q_seg_ref,
    kv_seg_ref,
    o_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_kv: int,
    n_kv: int,
):
    iq = pl.program_id(2)
    ikv = pl.program_id(3)

    @pl.when(ikv == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # With causal masking, blocks strictly above the diagonal contribute
    # nothing: skip their compute (the grid still visits them).
    needed = (
        (iq + 1) * block_q - 1 >= ikv * block_kv if causal else True
    )

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # (block_q, D)
        k = k_ref[0, 0]  # (block_kv, D)
        s = jax.lax.dot_general(
            q,
            k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_kv)
        s = _block_mask(
            s,
            iq=iq,
            ikv=ikv,
            block_q=block_q,
            block_kv=block_kv,
            causal=causal,
            q_seg=q_seg_ref[0] if q_seg_ref is not None else None,
            kv_seg=kv_seg_ref[0] if kv_seg_ref is not None else None,
        )

        m_prev = m_scr[...]  # (block_q, 128) lane-replicated
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (block_q, 1)
        m_next = jnp.maximum(m_prev, m_cur)  # lane-replicated again
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - _lane_tile(m_next, block_kv))
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next

        v = v_ref[0, 0]  # (block_kv, D)
        pv = jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, D)
        acc_scr[...] = acc_scr[...] * _lane_tile(alpha, acc_scr.shape[-1]) + pv

    @pl.when(ikv == n_kv - 1)
    def _finalize():
        l = l_scr[...]
        # Fully-masked rows have l == 0; emit 0 there instead of NaN.
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (
            acc_scr[...] / _lane_tile(l_safe, acc_scr.shape[-1])
        ).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log(l_safe)


def _fwd(
    q: jax.Array,  # (B, H, Sq, D)
    k: jax.Array,  # (B, K, Skv, D)
    v: jax.Array,
    q_seg: jax.Array | None,  # (B, Sq)
    kv_seg: jax.Array | None,  # (B, Skv)
    *,
    causal: bool,
    scale: float,
    blocks: BlockSizes,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    b, h, s_q, d = q.shape
    _, kv_heads, s_kv, _ = k.shape
    groups = h // kv_heads
    bq, bkv = blocks
    n_q, n_kv = s_q // bq, s_kv // bkv
    grid = (b, h, n_q, n_kv)

    def q_map(ib, ih, iq, ikv):
        return (ib, ih, iq, 0)

    def kv_map(ib, ih, iq, ikv):
        return (ib, ih // groups, ikv, 0)

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
    ]
    args = [q, k, v]
    if q_seg is not None:
        in_specs.append(
            pl.BlockSpec((1, bq, NUM_LANES), lambda ib, ih, iq, ikv: (ib, iq, 0))
        )
        in_specs.append(
            pl.BlockSpec(
                (1, NUM_SUBLANES, bkv), lambda ib, ih, iq, ikv: (ib, 0, ikv)
            )
        )
        args.append(
            jax.lax.broadcast_in_dim(q_seg, (b, s_q, NUM_LANES), (0, 1))
        )
        args.append(
            jax.lax.broadcast_in_dim(kv_seg, (b, NUM_SUBLANES, s_kv), (0, 2))
        )
    else:
        in_specs += [None, None]
        args += [None, None]

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=bq,
        block_kv=bkv,
        n_kv=n_kv,
    )
    out_shapes = (
        jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, s_q, NUM_LANES), jnp.float32),
    )
    out_specs = (
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bq, NUM_LANES), q_map),
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((bq, NUM_LANES), jnp.float32),  # m
            pltpu.VMEM((bq, NUM_LANES), jnp.float32),  # l
            pltpu.VMEM((bq, d), jnp.float32),  # acc
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    q_seg_ref,
    kv_seg_ref,
    dq_ref,
    dq_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_kv: int,
    n_kv: int,
):
    iq = pl.program_id(2)
    ikv = pl.program_id(3)

    @pl.when(ikv == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    needed = (
        (iq + 1) * block_q - 1 >= ikv * block_kv if causal else True
    )

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = _block_mask(
            s,
            iq=iq,
            ikv=ikv,
            block_q=block_q,
            block_kv=block_kv,
            causal=causal,
            q_seg=q_seg_ref[0] if q_seg_ref is not None else None,
            kv_seg=kv_seg_ref[0] if kv_seg_ref is not None else None,
        )
        p = jnp.exp(s - _lane_tile(lse_ref[0, 0], block_kv))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lane_tile(delta_ref[0, 0], block_kv))
        dq_scr[...] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ikv == n_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    q_seg_ref,
    kv_seg_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_kv: int,
    n_q: int,
    n_inner: int,
):
    """Grid (B, K, n_kv, groups * n_q): the innermost (sequential) dim folds
    the GQA group loop into the q loop so dk/dv accumulation is race-free."""
    ikv = pl.program_id(2)
    inner = pl.program_id(3)
    iq = inner % n_q

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = (
        (iq + 1) * block_q - 1 >= ikv * block_kv if causal else True
    )

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = _block_mask(
            s,
            iq=iq,
            ikv=ikv,
            block_q=block_q,
            block_kv=block_kv,
            causal=causal,
            q_seg=q_seg_ref[0] if q_seg_ref is not None else None,
            kv_seg=kv_seg_ref[0] if kv_seg_ref is not None else None,
        )
        p = jnp.exp(s - _lane_tile(lse_ref[0, 0], block_kv))
        # dv += pᵀ @ do
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lane_tile(delta_ref[0, 0], block_kv))
        # dk = scale·dsᵀ@q_unscaled = dsᵀ@q_scaled (q was pre-scaled above).
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(inner == n_inner - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_impl(
    q,
    k,
    v,
    q_seg,
    kv_seg,
    o,
    lse,
    do,
    *,
    causal: bool,
    scale: float,
    blocks: BlockSizes,
    interpret: bool,
):
    b, h, s_q, d = q.shape
    _, kv_heads, s_kv, _ = k.shape
    groups = h // kv_heads
    bq, bkv = blocks
    n_q, n_kv = s_q // bq, s_kv // bkv

    # delta_i = rowsum(do ⊙ o): cheap elementwise+reduce, XLA fuses it.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (B, H, Sq)
    delta = jax.lax.broadcast_in_dim(
        delta, (b, h, s_q, NUM_LANES), (0, 1, 2)
    )

    seg_args = [None, None]
    if q_seg is not None:
        q_seg_b = jax.lax.broadcast_in_dim(q_seg, (b, s_q, NUM_LANES), (0, 1))
        kv_seg_b = jax.lax.broadcast_in_dim(
            kv_seg, (b, NUM_SUBLANES, s_kv), (0, 2)
        )
        seg_args = [q_seg_b, kv_seg_b]

    # dk = scale·dsᵀq_unscaled = dsᵀ(scale·q): pre-scaling q once inside the
    # kernels folds the scale into both s and dk, so no post-multiply needed.

    # ---- dq: grid (B, H, n_q, n_kv), accumulate over kv blocks ----
    def q_map(ib, ih, iq, ikv):
        return (ib, ih, iq, 0)

    def kv_map(ib, ih, iq, ikv):
        return (ib, ih // groups, ikv, 0)

    dq_in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bkv, d), kv_map),
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bq, NUM_LANES), q_map),
        pl.BlockSpec((1, 1, bq, NUM_LANES), q_map),
    ]
    if q_seg is not None:
        dq_in_specs.append(
            pl.BlockSpec((1, bq, NUM_LANES), lambda ib, ih, iq, ikv: (ib, iq, 0))
        )
        dq_in_specs.append(
            pl.BlockSpec(
                (1, NUM_SUBLANES, bkv), lambda ib, ih, iq, ikv: (ib, 0, ikv)
            )
        )
    else:
        dq_in_specs += [None, None]

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            scale=scale,
            causal=causal,
            block_q=bq,
            block_kv=bkv,
            n_kv=n_kv,
        ),
        grid=(b, h, n_q, n_kv),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), q_map),
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_args)

    # ---- dk/dv: grid (B, K, n_kv, groups·n_q), accumulate over (g, q) ----
    n_inner = groups * n_q

    def q_map2(ib, ikh, ikv, inner):
        return (ib, ikh * groups + inner // n_q, inner % n_q, 0)

    def kv_map2(ib, ikh, ikv, inner):
        return (ib, ikh, ikv, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map2),
        pl.BlockSpec((1, 1, bkv, d), kv_map2),
        pl.BlockSpec((1, 1, bkv, d), kv_map2),
        pl.BlockSpec((1, 1, bq, d), q_map2),
        pl.BlockSpec((1, 1, bq, NUM_LANES), q_map2),
        pl.BlockSpec((1, 1, bq, NUM_LANES), q_map2),
    ]
    if q_seg is not None:
        dkv_in_specs.append(
            pl.BlockSpec(
                (1, bq, NUM_LANES),
                lambda ib, ikh, ikv, inner: (ib, inner % n_q, 0),
            )
        )
        dkv_in_specs.append(
            pl.BlockSpec(
                (1, NUM_SUBLANES, bkv),
                lambda ib, ikh, ikv, inner: (ib, 0, ikv),
            )
        )
    else:
        dkv_in_specs += [None, None]

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            scale=scale,
            causal=causal,
            block_q=bq,
            block_kv=bkv,
            n_q=n_q,
            n_inner=n_inner,
        ),
        grid=(b, kv_heads, n_kv, n_inner),
        in_specs=dkv_in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, bkv, d), kv_map2),
            pl.BlockSpec((1, 1, bkv, d), kv_map2),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, kv_heads, s_kv, d), k.dtype),
            jax.ShapeDtypeStruct((b, kv_heads, s_kv, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing (on (B, H, S, D) layouts)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, q_seg, kv_seg, causal, scale, blocks, blocks_bwd,
                interpret):
    o, _ = _fwd(
        q, k, v, q_seg, kv_seg,
        causal=causal, scale=scale, blocks=blocks, interpret=interpret,
    )
    return o


def _flash_bhsd_fwd(q, k, v, q_seg, kv_seg, causal, scale, blocks, blocks_bwd,
                    interpret):
    o, lse = _fwd(
        q, k, v, q_seg, kv_seg,
        causal=causal, scale=scale, blocks=blocks, interpret=interpret,
    )
    return o, (q, k, v, q_seg, kv_seg, o, lse)


def _flash_bhsd_bwd(causal, scale, blocks, blocks_bwd, interpret, residuals, do):
    q, k, v, q_seg, kv_seg, o, lse = residuals
    dq, dk, dv = _bwd_impl(
        q, k, v, q_seg, kv_seg, o, lse, do,
        causal=causal, scale=scale, blocks=blocks_bwd, interpret=interpret,
    )
    return dq, dk, dv, None, None


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(
    q: jax.Array,  # (B, S, H, D)
    k: jax.Array,  # (B, S, K, D)
    v: jax.Array,  # (B, S, K, D)
    *,
    causal: bool = True,
    segment_ids: jax.Array | None = None,  # (B, S) int32
    block_q: int = 512,
    block_kv: int = 512,
    block_q_bwd: int = 0,
    block_kv_bwd: int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """FlashAttention with GQA + sequence-packing segment masks.

    Takes/returns the model's (B, S, H, D) layout. Raises ``ValueError`` on
    shapes the kernel cannot tile — callers (``ops.attention``) fall back to
    the XLA implementation. ``block_*_bwd`` size the backward kernels' tiles
    independently (0 = same as forward).
    """
    b, s_q, h, d = q.shape
    _, s_kv, kv_heads, _ = k.shape
    block_q_bwd = block_q_bwd or block_q
    block_kv_bwd = block_kv_bwd or block_kv
    if h % kv_heads:
        raise ValueError(f"q heads {h} not divisible by kv heads {kv_heads}")
    if not (supports(s_q, s_kv, d, block_q, block_kv)
            and supports(s_q, s_kv, d, block_q_bwd, block_kv_bwd)):
        raise ValueError(
            f"flash_attention cannot tile Sq={s_q} Skv={s_kv} D={d} "
            f"(block_q={block_q}, block_kv={block_kv}, "
            f"bwd {block_q_bwd}/{block_kv_bwd})"
        )
    blocks = _pick_blocks(s_q, s_kv, block_q, block_kv)
    blocks_bwd = _pick_blocks(s_q, s_kv, block_q_bwd, block_kv_bwd)
    if interpret is None:
        interpret = _interpret_default()

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    o = _flash_bhsd(
        qt, kt, vt, segment_ids, segment_ids,
        causal, d**-0.5, blocks, blocks_bwd, interpret,
    )
    return jnp.transpose(o, (0, 2, 1, 3))
