"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has no long-context machinery at all — sequence length is never
even a variable there (SURVEY.md §5 'long-context'; prompts go unchunked to an
HTTP API, ref ``src/distributed_inference.py:65,69``). This module is the
TPU-native long-context path: the sequence dimension is sharded over the
``sequence`` mesh axis, each device holds S/n query and KV chunks, and KV
chunks rotate around the ring via ``lax.ppermute`` (XLA lowers neighbor
permutes to ICI sends) while an online-softmax accumulator merges partial
attention results. HBM per device is O(S/n · S/n) for the score tile and
O(S/n · D) for the output — sequences n× longer than one chip's HBM fit.

Semantics match ``ops.attention._xla_attention`` exactly (GQA, causal,
segment-id packing masks) — tested against it on the 8-device CPU mesh.
With causal masking, chunk pairs strictly above the diagonal are skipped with
``lax.cond`` (the ppermute still runs — the ring must keep rotating — but the
score/pv einsums are not computed), saving ~half the attention FLOPs.

The algorithm is blockwise-parallel exact attention (Liu et al., "Ring
Attention with Blockwise Transformers"; see PAPERS.md) — log-sum-exp merging
identical to the flash kernel's, with the block loop distributed over chips
instead of over the Pallas grid.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ditl_tpu.ops.attention import NEG_INF
from ditl_tpu.utils.compat import axis_size, shard_map

__all__ = ["ring_attention"]


def _masked_scores(
    q: jax.Array,  # (B, Sq, K, G, D) f32, pre-scaled
    k: jax.Array,  # (B, Skv, K, D)
    q_pos: jax.Array,  # (Sq,) global positions of the query chunk
    kv_pos: jax.Array,  # (Skv,) global positions of the kv chunk
    q_seg: jax.Array | None,  # (B, Sq)
    kv_seg: jax.Array | None,  # (B, Skv)
    *,
    causal: bool,
) -> jax.Array:
    """Masked score tile (B, K, G, Sq, Skv) in f32 for one chunk pair."""
    s = jnp.einsum(
        "bqkgd,bskd->bkgqs", q, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]  # (Sq, Skv)
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    if q_seg is not None:
        seg = q_seg[:, :, None] == kv_seg[:, None, :]  # (B, Sq, Skv)
        s = jnp.where(seg[:, None, None], s, NEG_INF)
    return s


def _ring_body(axis_name: str, causal: bool, n: int, carry, _):
    (k_cur, v_cur, kv_seg_cur, src, m, l, acc, q, q_pos, q_seg) = carry
    s_local = k_cur.shape[1]
    my = jax.lax.axis_index(axis_name)

    def merge(operand):
        k_c, v_c, kv_seg_c, src_, m_, l_, acc_ = operand
        kv_pos = src_ * s_local + jnp.arange(s_local, dtype=jnp.int32)
        s = _masked_scores(
            q, k_c, q_pos, kv_pos, q_seg, kv_seg_c, causal=causal
        )  # (B, K, G, Sq, Skv)
        m_chunk = jnp.max(s, axis=-1)  # (B, K, G, Sq)
        m_new = jnp.maximum(m_, m_chunk)
        # Fully-masked rows leave m at NEG_INF; exp(NEG_INF - NEG_INF) would
        # be exp(0)=1 on garbage rows — clamp the shift so they stay zero.
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift[..., None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        alpha = jnp.exp(jnp.where(m_ == NEG_INF, NEG_INF, m_ - shift))
        l_ = alpha * l_ + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bkgqs,bskd->bqkgd", p, v_c.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc_ = acc_ * jnp.moveaxis(alpha, -1, 1)[..., None] + pv
        return m_new, l_, acc_

    operand = (k_cur, v_cur, kv_seg_cur, src, m, l, acc)
    if causal:
        # Chunks are contiguous position ranges, so a KV chunk from a later
        # device (src > my) is entirely in the future: skip its compute.
        m, l, acc = jax.lax.cond(
            src <= my, merge, lambda op: (op[4], op[5], op[6]), operand
        )
    else:
        m, l, acc = merge(operand)

    # Rotate: send our current KV chunk to the next device in the ring; after
    # n-1 rotations every device has seen every chunk.
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
    v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    if kv_seg_cur is not None:
        kv_seg_cur = jax.lax.ppermute(kv_seg_cur, axis_name, perm)
    src = (src - 1) % n
    return (k_cur, v_cur, kv_seg_cur, src, m, l, acc, q, q_pos, q_seg), None


def _ring_attention_local(
    q: jax.Array,  # (B, S_local, H, D) — this device's query chunk
    k: jax.Array,  # (B, S_local, K, D)
    v: jax.Array,
    segment_ids: jax.Array | None,  # (B, S_local)
    *,
    axis_name: str,
    causal: bool,
) -> jax.Array:
    b, s_local, h, d = q.shape
    kv_heads = k.shape[2]
    groups = h // kv_heads
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    qg = (q.astype(jnp.float32) * d**-0.5).reshape(b, s_local, kv_heads, groups, d)
    q_pos = my * s_local + jnp.arange(s_local, dtype=jnp.int32)

    m = jnp.full((b, kv_heads, groups, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv_heads, groups, s_local), jnp.float32)
    acc = jnp.zeros((b, s_local, kv_heads, groups, d), jnp.float32)

    carry = (k, v, segment_ids, my, m, l, acc, qg, q_pos, segment_ids)
    body = functools.partial(_ring_body, axis_name, causal, n)
    carry, _ = jax.lax.scan(body, carry, None, length=n)
    _, _, _, _, m, l, acc, _, _, _ = carry

    l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 not NaN
    out = acc / jnp.moveaxis(l_safe, -1, 1)[..., None]
    return out.reshape(b, s_local, h, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # (B, S, H, D) global
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: jax.Array | None = None,
    mesh: jax.sharding.Mesh | None = None,
    rules=None,
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over the mesh axis
    named by ``rules['seq']`` (default: ``sequence``).

    Specs are derived from the same logical-axis rule table the rest of the
    model uses (parallel/sharding.py), so batch/head layouts stay consistent
    with the surrounding sharding constraints. Falls back to the XLA
    implementation when there is no mesh or the sequence axis has size 1.
    """
    from ditl_tpu.ops.attention import _mesh_axes_size, _xla_attention
    from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

    rules = rules if rules is not None else DEFAULT_RULES
    axis_name = rules.get("seq")
    if (
        mesh is None
        or not isinstance(axis_name, str)
        or axis_name not in mesh.shape
        or mesh.shape[axis_name] == 1
    ):
        return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    dp = _mesh_axes_size(mesh, rules.get("batch"))
    tp = _mesh_axes_size(mesh, rules.get("act_heads"))
    if (
        q.shape[0] % dp
        or q.shape[2] % tp
        or k.shape[2] % tp
        or q.shape[1] % mesh.shape[axis_name]
    ):
        # Batch/heads/seq don't divide the mesh: shard_map would fail at trace
        # time. XLA's GSPMD attention partitions any layout (at more comms).
        return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)

    qkv_spec = logical_to_spec(("batch", "seq", "act_heads", None), rules)
    args = [q, k, v]
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    if segment_ids is not None:
        args.append(segment_ids)
        in_specs.append(logical_to_spec(("batch", "seq"), rules))

    def local(q_, k_, v_, seg_=None):
        return _ring_attention_local(
            q_, k_, v_, seg_, axis_name=axis_name, causal=causal
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )(*args)
