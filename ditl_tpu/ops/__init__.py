from ditl_tpu.ops.attention import dot_product_attention  # noqa: F401
from ditl_tpu.ops.encode import encode_and_reduce  # noqa: F401
