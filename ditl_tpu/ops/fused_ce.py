"""Fused (blockwise) cross-entropy: lm_head matmul + log-softmax, chunked.

The reference computes no loss at all on device (its loss helper is dead code,
ref ``src/utils.py:12-23``). The naive TPU loss path (train/step.py) projects
the final hidden states to logits of shape ``(B, S, V)`` in float32 — at
bench shapes (8 x 1024 x 32768) that is a 1 GiB HBM tensor written by the
forward and read again by the backward, plus its bf16 twin from the matmul.
HBM bandwidth, not FLOPs, pays for that.

This op never materializes the full logits. Tokens are processed in blocks of
``block_tokens``: each block's ``(block, V)`` logits live only inside one
``lax.scan`` step, reduced immediately to the block's summed NLL;
``jax.checkpoint`` around the block recomputes those logits during the
backward instead of saving them. Peak logits memory drops from ``B*S*V`` to
``block_tokens*V`` (32 MiB at the default block), while the matmuls stay
``(block, D) @ (D, V)`` — large, static, MXU-shaped.

The gradient needs no custom VJP: autodiff of the blockwise scan yields
exactly the classic ``(softmax - onehot) @ Wᵀ`` per block, with the head
gradient accumulated across blocks by the scan's cotangent carry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_cross_entropy"]


@functools.partial(jax.jit, static_argnames=("block_tokens", "compute_dtype"))
def fused_cross_entropy(
    x: jax.Array,  # (N, D) final hidden states (already final-normed)
    head: jax.Array,  # (D, V) lm head weights
    targets: jax.Array,  # (N,) int target ids
    mask: jax.Array,  # (N,) float 0/1 loss mask
    *,
    block_tokens: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Summed masked NLL over all N tokens, without full-logit materialization.

    Callers divide by ``mask.sum()`` themselves (keeping this a pure sum makes
    the gradient-accumulation and data-parallel reductions exact).
    """
    n, d = x.shape
    block = min(block_tokens, n) if n > 0 else block_tokens
    pad = (-n) % block
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        mask = jnp.pad(mask, (0, pad))  # padded tokens are masked out
    nb = (n + pad) // block
    xb = x.reshape(nb, block, d)
    tb = targets.reshape(nb, block).astype(jnp.int32)
    mb = mask.reshape(nb, block).astype(jnp.float32)

    def block_nll(head, x_blk, t_blk, m_blk):
        logits = jnp.einsum(
            "td,dv->tv",
            x_blk.astype(compute_dtype),
            head.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )  # (block, V) — lives only inside this scan step
        lse = jax.nn.logsumexp(logits, axis=-1)
        target_logit = jnp.take_along_axis(logits, t_blk[:, None], axis=1)[:, 0]
        return jnp.sum((lse - target_logit) * m_blk)

    # Recompute the block's logits in the backward pass instead of saving them.
    block_nll = jax.checkpoint(block_nll)

    def scan_step(nll_sum, xs):
        x_blk, t_blk, m_blk = xs
        return nll_sum + block_nll(head, x_blk, t_blk, m_blk), None

    nll_sum, _ = jax.lax.scan(scan_step, jnp.zeros((), jnp.float32), (xb, tb, mb))
    return nll_sum
