"""Fused-gate|up MLP block with a hand-written VJP (r5 experiment).

The r5 stop-gradient ablation (BASELINE.md, experiments/bwd_ablation.py)
showed the MLP family's in-step weight-gradient GEMMs running at ~2x
their isolated-peak rates — a property of XLA's backward SCHEDULE, not of
the GEMM shapes. This module is the instrument against that: the whole
block's backward (activation grads and BOTH weight grads) is emitted as
ONE function with explicit einsum contractions — no autodiff-generated
transposes, residuals chosen by hand (h, gate, up; ``inner`` recomputed
elementwise like the "dots" remat policy would) — so XLA schedules the
backward exactly as written.

Exactness: forward is bit-identical to the inline path (same ops); the
backward matches autodiff to f32 test tolerance
(tests/test_model.py::test_mlp_custom_vjp_matches_autodiff). Enabled per
config via ``ModelConfig.mlp_custom_vjp`` (requires ``fused_gate_up``;
plain float weights only — quantized serving never differentiates).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["mlp_gu"]


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def mlp_gu(constrain, h: jax.Array, w_gu: jax.Array,
           w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP over the fused gate|up layout: ``h @ w_gu`` → split →
    ``silu(gate)*up @ w_down``. Shapes: h (B,S,D), w_gu (D,2F),
    w_down (F,D). ``constrain`` (static): sharding-hint callback applied
    to the inner activation — mirrors the inline path's
    ``_constrain(inner, act_mlp)`` so a mesh A/B isolates the backward
    SPELLING, not sharding-propagation differences. Pass identity for
    single-chip."""
    out, _ = _fwd(constrain, h, w_gu, w_down)
    return out


def _fwd(constrain, h, w_gu, w_down):
    gu = jnp.einsum("bsd,df->bsf", h, w_gu)
    gate, up = jnp.split(gu, 2, axis=-1)
    inner = constrain(jax.nn.silu(gate) * up)
    out = jnp.einsum("bsf,fd->bsd", inner, w_down)
    return out, (h, w_gu, w_down, gate, up)


def _bwd(constrain, res, g):
    h, w_gu, w_down, gate, up = res
    # Recompute the cheap elementwise pieces (the "dots"-policy choice).
    sg = jax.nn.sigmoid(gate)
    silu_gate = gate * sg
    inner = constrain(silu_gate * up)
    # One explicit contraction per gradient; all four GEMMs share the g /
    # dgu operands, written so XLA sees the reuse directly.
    d_w_down = jnp.einsum("bsf,bsd->fd", inner, g).astype(w_down.dtype)
    dinner = jnp.einsum("bsd,fd->bsf", g, w_down)
    dgate = dinner * up * (sg * (1.0 + gate * (1.0 - sg)))
    dup = dinner * silu_gate
    dgu = jnp.concatenate([dgate, dup], axis=-1)
    d_w_gu = jnp.einsum("bsd,bsf->df", h, dgu).astype(w_gu.dtype)
    dh = jnp.einsum("bsf,df->bsd", dgu, w_gu).astype(h.dtype)
    return dh, d_w_gu, d_w_down


mlp_gu.defvjp(_fwd, _bwd)
