"""Fused-gate|up MLP block with a hand-written VJP and, per config, a
Pallas fused-backward implementation.

The r5 stop-gradient ablation (BASELINE.md, experiments/bwd_ablation.py)
showed the MLP family's in-step weight-gradient GEMMs running at ~2x
their isolated-peak rates — a property of XLA's backward SCHEDULE, not of
the GEMM shapes. The first instrument against that was this module's
custom VJP: the whole block's backward (activation grads and BOTH weight
grads) emitted as ONE function with explicit einsum contractions. The r5
A/B came back a definitive null — XLA still owned tiling and interleaving
— which is exactly what ``bwd_impl="pallas"`` now changes: the same
backward emitted as hand-tiled Pallas kernels (ops/mlp_bwd.py), so the
schedule is pinned by the grid, not chosen by XLA.

Exactness: forward is bit-identical to the inline path (same ops); the
backward matches autodiff to f32 test tolerance for BOTH implementations
(tests/test_model.py::test_mlp_custom_vjp_matches_autodiff,
tests/test_bwd_kernels.py). Enabled per config via
``ModelConfig.mlp_custom_vjp`` (einsum spelling) /
``ModelConfig.mlp_bwd_impl="pallas"`` (Pallas kernels; requires
``fused_gate_up``; plain float weights only — quantized serving never
differentiates). Shapes ops/mlp_bwd.supports rejects fall back to the
einsum spelling; bench.py records the implementation that actually ran.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["mlp_gu", "mlp_block", "effective_bwd_impl"]


@partial(jax.custom_vjp, nondiff_argnums=(0, 4, 5, 6))
def _mlp_gu(constrain, h: jax.Array, w_gu: jax.Array, w_down: jax.Array,
            bwd_impl, bwd_blocks, interpret) -> jax.Array:
    out, _ = _fwd(constrain, h, w_gu, w_down, bwd_impl, bwd_blocks, interpret)
    return out


def _fwd(constrain, h, w_gu, w_down, bwd_impl, bwd_blocks, interpret):
    gu = jnp.einsum("bsd,df->bsf", h, w_gu)
    gate, up = jnp.split(gu, 2, axis=-1)
    inner = constrain(jax.nn.silu(gate) * up)
    out = jnp.einsum("bsf,fd->bsd", inner, w_down)
    return out, (h, w_gu, w_down, gate, up)


def _bwd(constrain, bwd_impl, bwd_blocks, interpret, res, g):
    h, w_gu, w_down, gate, up = res
    if bwd_impl == "pallas":
        from ditl_tpu.ops import mlp_bwd

        b, s, d = h.shape
        if mlp_bwd.supports(b * s, d, w_down.shape[0], bwd_blocks):
            return mlp_bwd.fused_mlp_bwd(
                h, w_gu, w_down, gate, up, g,
                blocks=bwd_blocks, interpret=interpret,
            )
        # Shapes the kernel can't tile (tiny tests, odd dims): the einsum
        # spelling below. bench.py re-derives this decision and records the
        # implementation that actually ran, so an A/B stays attributable.
    # Recompute the cheap elementwise pieces (the "dots"-policy choice).
    sg = jax.nn.sigmoid(gate)
    silu_gate = gate * sg
    inner = constrain(silu_gate * up)
    # One explicit contraction per gradient; all four GEMMs share the g /
    # dgu operands, written so XLA sees the reuse directly.
    d_w_down = jnp.einsum("bsf,bsd->fd", inner, g).astype(w_down.dtype)
    dinner = jnp.einsum("bsd,fd->bsf", g, w_down)
    dgate = dinner * up * (sg * (1.0 + gate * (1.0 - sg)))
    dup = dinner * silu_gate
    dgu = jnp.concatenate([dgate, dup], axis=-1)
    d_w_gu = jnp.einsum("bsd,bsf->df", h, dgu).astype(w_gu.dtype)
    dh = jnp.einsum("bsf,df->bsd", dgu, w_gu).astype(h.dtype)
    return dh, d_w_gu, d_w_down


_mlp_gu.defvjp(_fwd, _bwd)


def mlp_gu(constrain, h: jax.Array, w_gu: jax.Array, w_down: jax.Array,
           bwd_impl: str = "xla", bwd_blocks=(), interpret=None) -> jax.Array:
    """SwiGLU MLP over the fused gate|up layout: ``h @ w_gu`` → split →
    ``silu(gate)*up @ w_down``. Shapes: h (B,S,D), w_gu (D,2F),
    w_down (F,D). ``constrain`` (static): sharding-hint callback applied
    to the inner activation — mirrors the inline path's
    ``_constrain(inner, act_mlp)`` so a mesh A/B isolates the backward
    SPELLING, not sharding-propagation differences. Pass identity for
    single-chip. ``bwd_impl`` selects the backward: "xla" (explicit
    einsums, scheduled by XLA) or "pallas" (ops/mlp_bwd.py kernels;
    ``bwd_blocks`` = (block_n, block_f, block_d), 0/empty = defaults)."""
    return _mlp_gu(constrain, h, w_gu, w_down, bwd_impl,
                   tuple(bwd_blocks or ()), interpret)


def _identity(t):
    return t


def effective_bwd_impl(bwd_impl: str, b: int, s: int, d: int, f: int,
                       blocks=(), mesh=None, rules=None) -> str:
    """The backward implementation ``mlp_block`` will ACTUALLY run for these
    shapes — shared gate logic in parallel/sharding.pallas_bwd_effective,
    bound to this op's shape predicate; bench.py records the same call, so
    an A/B can never attribute a delta to a kernel that fell back."""
    from ditl_tpu.ops import mlp_bwd
    from ditl_tpu.parallel.sharding import pallas_bwd_effective

    return pallas_bwd_effective(bwd_impl, b, s, d, f, blocks, mesh, rules,
                                mlp_bwd.supports)


def mlp_block(constrain, h: jax.Array, w_gu: jax.Array, w_down: jax.Array,
              *, bwd_impl: str = "xla", bwd_blocks=(), mesh=None,
              rules=None) -> jax.Array:
    """Mesh-aware dispatch for the custom-VJP MLP block (models/llama.py).

    Pallas calls carry no GSPMD partitioning rules, so under a mesh the
    Pallas-backward variant is shard_map'ed over the batch axes with
    replicated weights — shard_map's transpose inserts the psum that turns
    per-shard weight grads into the global ones (mirrors
    ops/attention.py's flash dispatch). Meshes that don't divide the batch,
    or sequence-sharded activations, keep the GSPMD-partitionable einsum
    backward instead (the constrain hint preserves the activation
    sharding A/Bs rely on)."""
    b, s, d = h.shape
    eff = effective_bwd_impl(bwd_impl, b, s, d, w_down.shape[0], bwd_blocks,
                             mesh, rules)
    if eff != "pallas" or mesh is None:
        return mlp_gu(constrain, h, w_gu, w_down, eff, bwd_blocks)
    from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec
    from ditl_tpu.utils.compat import shard_map

    rules = rules if rules is not None else DEFAULT_RULES
    h_spec = logical_to_spec(("batch", None, None), rules)
    w_spec = logical_to_spec((None, None), rules)

    def local(h_, wgu_, wdn_):
        return mlp_gu(_identity, h_, wgu_, wdn_, "pallas", bwd_blocks)

    return shard_map(
        local, mesh=mesh, in_specs=(h_spec, w_spec, w_spec),
        out_specs=h_spec, check_vma=False,
    )(h, w_gu, w_down)
