"""Dense projection with a hand-scheduled (Pallas) backward.

The second-largest residual in the r4/r5 backward-schedule accounting
(~33 ms of a 562 ms step) is the attention qkv/out-projection weight
gradients — plain ``x^T @ g`` contractions whose in-step rates ran at ~2x
their isolated cost under XLA's backward schedule (experiments/bwd_levers.py
``iso`` receipts). This module is the projection-shaped sibling of
ops/mlp_bwd.py: a ``custom_vjp`` whose forward is the exact inline einsum
(bit-identical — same op, same dtypes as ops/quant.weight_einsum on float
weights) and whose backward emits BOTH gradients from one Pallas kernel:

- grid ``(D/bd, N/bn)``, token dim sequential-innermost;
- per (d, n) tile: ``dx = g @ w^T`` (full F contracted in-step) and
  ``d_w = x^T @ g`` accumulated in a (bd, F) f32 VMEM scratch, written out
  on the last token tile — the cotangent tile ``g`` is read once and feeds
  both products.

Selected per config via ``ModelConfig.proj_bwd_impl`` for the attention
projections in models/llama.py; shapes the kernel cannot tile fall back to
the einsum backward (the bench JSON records the implementation that actually
ran). Off-TPU the kernel runs in interpret mode so numerics tests run on
CPU. Mesh composition mirrors ops/attention.py's flash dispatch: Pallas
calls carry no GSPMD partitioning rules, so under a mesh the op is
shard_map'ed over the batch axes with replicated weights — shard_map's
transpose inserts the weight-gradient psum.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ditl_tpu.utils.compat import shard_map, tpu_compiler_params

__all__ = ["projection", "supports", "effective_bwd_impl", "DEFAULT_BLOCKS"]

NUM_LANES = 128
NUM_SUBLANES = 16


class BlockSizes(NamedTuple):
    block_n: int  # token tile
    block_d: int  # input-feature tile


# (bn=256, bd=256) holds ~4.3 MB VMEM at the largest 1b3 projection
# (D=2048, F=4096 fused qkv): w tile 2 MB bf16 + (bd, F) f32 scratch 4 MB is
# the ceiling term; ModelConfig.proj_bwd_block_{n,d} sweep it per chip.
DEFAULT_BLOCKS = BlockSizes(256, 256)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_blocks(n: int, d: int, blocks) -> BlockSizes:
    bn, bd = blocks or (0, 0)
    bn, bd = bn or DEFAULT_BLOCKS.block_n, bd or DEFAULT_BLOCKS.block_d
    return BlockSizes(min(bn, n), min(bd, d))


def supports(n: int, d: int, f: int, blocks=None) -> bool:
    """True if the backward kernel can tile x (N=B*S, D) @ w (D, F)."""
    bn, bd = _pick_blocks(n, d, blocks)
    return (
        n % bn == 0
        and d % bd == 0
        and bn % NUM_SUBLANES == 0
        and bd % NUM_LANES == 0
        and f % NUM_LANES == 0
    )


def _bwd_kernel(
    x_ref,    # (bn, bd)
    w_ref,    # (bd, F)
    g_ref,    # (bn, F)
    dx_ref,   # (bn, bd) out
    dw_ref,   # (bd, F) out, written on the last token tile
    acc_ref,  # (bd, F) f32 VMEM scratch
    *,
    n_n: int,
):
    i_n = pl.program_id(1)

    @pl.when(i_n == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    g = g_ref[...]
    dx = jax.lax.dot_general(
        g, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (bn, bd)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (bd, F)

    @pl.when(i_n == n_n - 1)
    def _finalize():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


def _pallas_bwd(x, w, g, *, blocks, interpret):
    b, s, d = x.shape
    f = w.shape[1]
    n = b * s
    bn, bd = _pick_blocks(n, d, blocks)
    if interpret is None:
        interpret = _interpret_default()
    x2 = x.reshape(n, d)
    g2 = g.reshape(n, f)
    n_n, n_d = n // bn, d // bd
    dx2, dw = pl.pallas_call(
        partial(_bwd_kernel, n_n=n_n),
        grid=(n_d, n_n),
        in_specs=[
            pl.BlockSpec((bn, bd), lambda i_d, i_n: (i_n, i_d)),  # x
            pl.BlockSpec((bd, f), lambda i_d, i_n: (i_d, 0)),     # w
            pl.BlockSpec((bn, f), lambda i_d, i_n: (i_n, 0)),     # g
        ],
        out_specs=(
            pl.BlockSpec((bn, bd), lambda i_d, i_n: (i_n, i_d)),
            pl.BlockSpec((bd, f), lambda i_d, i_n: (i_d, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((d, f), w.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((bd, f), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(x2, w, g2)
    return dx2.reshape(b, s, d), dw


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _proj(x, w, bwd_impl, blocks, interpret):
    out, _ = _proj_fwd(x, w, bwd_impl, blocks, interpret)
    return out


def _proj_fwd(x, w, bwd_impl, blocks, interpret):
    # Bit-identical to the inline path's weight_einsum on float weights.
    out = jnp.einsum("bsd,df->bsf", x, w, preferred_element_type=x.dtype)
    return out, (x, w)


def _proj_bwd(bwd_impl, blocks, interpret, res, g):
    x, w = res
    if bwd_impl == "pallas" and supports(
        x.shape[0] * x.shape[1], x.shape[2], w.shape[1], blocks
    ):
        return _pallas_bwd(x, w, g, blocks=blocks, interpret=interpret)
    dx = jnp.einsum("bsf,df->bsd", g, w).astype(x.dtype)
    dw = jnp.einsum("bsd,bsf->df", x, g).astype(w.dtype)
    return dx, dw


_proj.defvjp(_proj_fwd, _proj_bwd)


def effective_bwd_impl(bwd_impl: str, b: int, s: int, d: int, f: int,
                       blocks=(), mesh=None, rules=None) -> str:
    """The backward implementation ``projection`` will ACTUALLY run for an
    (B,S,d) @ (d,f) projection — shared gate logic in
    parallel/sharding.pallas_bwd_effective bound to this op's shape
    predicate (mirrors ops/mlp.effective_bwd_impl)."""
    from ditl_tpu.parallel.sharding import pallas_bwd_effective

    return pallas_bwd_effective(bwd_impl, b, s, d, f, blocks, mesh, rules,
                                supports)


def projection(
    x: jax.Array,  # (B, S, D)
    w: jax.Array,  # (D, F) plain float (quantized serving never differentiates)
    *,
    bwd_impl: str = "xla",
    blocks=None,
    mesh=None,
    rules=None,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ w`` whose backward is dispatched per ``bwd_impl``. Under a mesh
    the Pallas variant is shard_map'ed over the batch axes (weights
    replicated; shard_map's transpose psums ``d_w``); meshes that don't
    divide the batch, or sequence-sharded activations, keep the
    GSPMD-partitionable einsum backward."""
    b, s, d = x.shape
    eff = effective_bwd_impl(bwd_impl, b, s, d, w.shape[1], blocks, mesh,
                             rules)
    if eff != "pallas" or mesh is None:
        return _proj(x, w, eff, tuple(blocks or ()), interpret)
    from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

    rules = rules if rules is not None else DEFAULT_RULES
    x_spec = logical_to_spec(("batch", None, None), rules)
    w_spec = logical_to_spec((None, None), rules)

    def local(x_, w_):
        return _proj(x_, w_, "pallas", tuple(blocks or ()), interpret)

    return shard_map(
        local, mesh=mesh, in_specs=(x_spec, w_spec), out_specs=x_spec,
        check_vma=False,
    )(x, w)
