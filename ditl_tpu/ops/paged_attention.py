"""Paged decode attention: KV lives in a shared page pool, per-slot page
tables map logical block -> physical page (vLLM-style), TPU-first.

The reference has no serving stack at all (its model is behind an HTTP API,
ref ``src/distributed_inference.py:34-41``); this op underpins the paged
mode of the continuous-batching engine (infer/continuous.py) that replaces
it. Contiguous per-slot caches (infer/cache.py) bound capacity by
``n_slots x max_context`` and make prefix sharing whole-prefix and explicit;
a page pool bounds capacity by *total tokens resident* and shares any
common full page between slots (automatic prefix reuse, infer/paged_cache.py).

Two implementations, equal by construction (tested against each other):

- ``paged_attention_xla``: gather pages -> contiguous (B, maxp*ps, K, D) ->
  masked GQA attention. Materializes the gathered cache every step (double
  HBM traffic); used as the correctness reference and the CPU path.
- ``paged_attention`` (Pallas/Mosaic): grid (B, kv_heads, maxp); the page
  table rides the scalar-prefetch channel so each grid step's *block index
  map* fetches the right physical page from HBM — no gathered copy is ever
  materialized. Online softmax over pages (same lane-replicated row-stat
  scheme as ops/flash_attention.py). Pages past a slot's length are mapped
  to page 0 by the host table; Mosaic's revisit optimization skips the
  re-fetch of an identical block index, so dead tail pages cost ~nothing.

Layouts: q is (B, H, D) — one query token per slot (the decode tick shape);
pools are (P, K, ps, D) — kv-heads BEFORE page slots, so a Pallas block
slicing one kv head keeps (ps, D) as its trailing dims (Mosaic requires the
last two block dims divisible by (8, 128) or equal to the array's);
page_table is (B, maxp) int32; lengths (B,) counts valid tokens per slot
(0 = dead slot -> zero output).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ditl_tpu.ops.attention import NEG_INF
from ditl_tpu.ops.flash_attention import NUM_LANES, _lane_tile
from ditl_tpu.utils.compat import shard_map, tpu_compiler_params

__all__ = ["paged_attention", "paged_attention_xla"]


def paged_attention_xla(
    q: jax.Array,  # (B, H, D) or (B, Q, H, D) — multi-query verify
    k_pages: jax.Array,  # (P, K, ps, D)
    v_pages: jax.Array,
    page_table: jax.Array,  # (B, maxp) int32
    lengths: jax.Array,  # (B,) int32
    tail_k: jax.Array | None = None,  # (B, K, T, D)
    tail_v: jax.Array | None = None,
    starts: jax.Array | None = None,  # (B,) — tokens resident in pages
    k_scale: jax.Array | None = None,  # (P, K, 1, ps) — int8 pool scales
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Gather-based reference: correctness oracle + CPU fallback.

    With a tail (the deferred-flush decode path), tokens [0, starts) live
    in pages and [starts, lengths) in the tail buffer at columns
    [0, lengths - starts). With ``k_scale``/``v_scale`` the pools are int8
    (symmetric per-row absmax; tails stay float).

    4-D ``q`` is the speculative-verify shape: Q consecutive query tokens
    per slot at positions lengths-1 .. lengths-2+Q; query qi additionally
    sees tail columns up to ``lengths + qi`` (causal within the chunk).
    Page columns need no per-query limit — they all precede ``starts``,
    which every query's limit covers."""
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, nq, h, d = q.shape
    _, kv_heads, ps, _ = k_pages.shape
    maxp = page_table.shape[1]
    groups = h // kv_heads
    kg = k_pages[page_table]  # (B, maxp, K, ps, D)
    vg = v_pages[page_table]
    dtype = k_pages.dtype
    if k_scale is not None:
        kg = (kg.astype(jnp.float32)
              * jnp.swapaxes(k_scale[page_table], 3, 4))  # scales (B,maxp,K,ps,1)
        vg = (vg.astype(jnp.float32)
              * jnp.swapaxes(v_scale[page_table], 3, 4))
        dtype = q.dtype
    k = jnp.swapaxes(kg, 2, 3).reshape(b, maxp * ps, kv_heads, d).astype(dtype)
    v = jnp.swapaxes(vg, 2, 3).reshape(b, maxp * ps, kv_heads, d).astype(dtype)
    page_limit = lengths if starts is None else jnp.minimum(starts, lengths)
    qi = jnp.arange(nq, dtype=jnp.int32)
    valid = (
        jnp.arange(maxp * ps, dtype=jnp.int32)[None, None, :]
        < page_limit[:, None, None]
    )  # (B, 1, S) -> broadcast over queries
    valid = jnp.broadcast_to(valid, (b, nq, maxp * ps))
    if tail_k is not None:
        t = tail_k.shape[2]
        k = jnp.concatenate([k, jnp.swapaxes(tail_k, 1, 2)], axis=1)
        v = jnp.concatenate([v, jnp.swapaxes(tail_v, 1, 2)], axis=1)
        tail_valid = (
            starts[:, None, None] + jnp.arange(t, dtype=jnp.int32)[None, None, :]
            < (lengths[:, None] + qi[None, :])[:, :, None]
        )  # (B, Q, T)
        valid = jnp.concatenate([valid, tail_valid], axis=2)
    qg = q.reshape(b, nq, kv_heads, groups, d)
    scores = jnp.einsum(
        "bqkgd,bskd->bqkgs", qg, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    scores = jnp.where(valid[:, :, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Dead slots (length 0) have an all-masked row; emit zeros, not NaN.
    probs = jnp.where(lengths[:, None, None, None, None] > 0, probs, 0.0)
    out = jnp.einsum("bqkgs,bskd->bqkgd", probs.astype(v.dtype), v)
    out = out.reshape(b, nq, h, d)
    return out[:, 0] if squeeze else out


def _accumulate_block(
    q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, *,
    scale, base, width, limit, ks_ref=None, vs_ref=None, q_groups=None,
):
    """Online-softmax accumulation of one (all-kv-heads) KV block whose
    columns are global positions [base, base+width), masked to < limit.

    ``ks_ref``/``vs_ref`` ((1, K, 1, width) f32) mark the block as int8:
    the scales factor OUT of the dots — the score matmul consumes raw int8
    K (HBM reads stay int8-sized) and the per-position scale multiplies the
    (G, width) score row afterwards; V's scale folds into the
    probabilities before the pv matmul. Lane-aligned broadcasts both
    times (same scheme as the contiguous int8 cache, ops/attention.py).

    ``q_groups`` (multi-query / speculative verify): the q block's rows are
    Q consecutive query tokens x ``q_groups`` GQA group members (row
    r = qi * q_groups + g), and row r's column limit is ``limit + qi`` —
    causal masking WITHIN the verify chunk at zero extra block traffic."""
    kv_heads, groups = q_ref.shape[1], q_ref.shape[2]
    d = acc_scr.shape[-1]
    tile = _lane_tile  # shared lane-replication helper (ops/flash_attention)
    cols = base + jax.lax.broadcasted_iota(jnp.int32, (groups, width), 1)
    if q_groups is None:
        col_mask = cols < limit
    else:
        qi = jax.lax.broadcasted_iota(jnp.int32, (groups, width), 0) // q_groups
        col_mask = cols < (limit + qi)
    for kh in range(kv_heads):
        q = q_ref[0, kh].astype(jnp.float32) * scale  # (G, D)
        k = k_ref[0, kh].astype(jnp.float32)  # (width, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (G, width)
        if ks_ref is not None:
            s = s * ks_ref[0, kh]  # (1, width) broadcast over G sublanes
        s = jnp.where(col_mask, s, NEG_INF)
        rows = slice(kh * groups, (kh + 1) * groups)
        m_prev = m_scr[rows]  # (G, NUM_LANES) lane-replicated
        l_prev = l_scr[rows]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        ptab = jnp.exp(s - tile(m_next, width))
        l_scr[rows] = alpha * l_prev + jnp.sum(ptab, axis=1, keepdims=True)
        m_scr[rows] = m_next
        v = v_ref[0, kh]  # (width, D)
        if vs_ref is not None:
            pv = jax.lax.dot_general(
                ptab * vs_ref[0, kh], v.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (G, D)
        else:
            pv = jax.lax.dot_general(
                ptab.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (G, D)
        acc_scr[rows] = acc_scr[rows] * tile(alpha, d) + pv


def _finalize_out(o_ref, m_scr, l_scr, acc_scr):
    kv_heads, groups = o_ref.shape[1], o_ref.shape[2]
    d = acc_scr.shape[-1]
    for kh in range(kv_heads):
        rows = slice(kh * groups, (kh + 1) * groups)
        l = l_scr[rows]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, kh] = (acc_scr[rows] / _lane_tile(l_safe, d)).astype(o_ref.dtype)


def _paged_kernel(
    table_ref,  # scalar prefetch: (B, maxp) int32
    lengths_ref,  # scalar prefetch: (B,) int32
    q_ref,  # (1, K, G, D)
    k_ref,  # (1, K, ps, D)
    v_ref,
    o_ref,  # (1, K, G, D)
    m_scr,  # (K*G padded, NUM_LANES)
    l_scr,
    acc_scr,  # (K*G padded, D)
    *,
    scale: float,
    page_size: int,
    n_pages: int,
):
    """Grid (B, maxp): each step consumes one PAGE for ALL kv heads — the
    kv-head loop is unrolled inside the kernel (static K small dots) so the
    grid stays small; per-(b, h, page) grids are latency-bound at ~2k tiny
    steps on v5e. Row r = k*G + g of the stats/acc scratch belongs to
    (kv head k, group member g)."""
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]
    base = p * page_size

    @pl.when(base < length)
    def _compute():
        _accumulate_block(
            q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
            scale=scale, base=base, width=page_size, limit=length,
        )

    @pl.when(p == n_pages - 1)
    def _finalize():
        _finalize_out(o_ref, m_scr, l_scr, acc_scr)


def _paged_tail_kernel(
    table_ref,  # scalar prefetch: (B, maxp) int32
    lengths_ref,  # scalar prefetch: (B,) int32
    starts_ref,  # scalar prefetch: (B,) int32 — tokens resident in pages
    q_ref,  # (1, K, G, D)
    k_ref,  # (1, K, ps, D) — int8 when quantized
    v_ref,
    *rest,  # [ks_ref, vs_ref ((1, K, 1, ps) f32)], tk_ref, tv_ref, o_ref,
            # m_scr, l_scr, acc_scr
    scale: float,
    page_size: int,
    n_pages: int,
    quantized: bool,
    q_groups: int | None = None,
):
    """Deferred-flush variant: grid (B, maxp + 1). Steps p < maxp consume
    flushed pages (positions < starts[b]); the final step consumes the hot
    TAIL block — the current decode chunk's KV, held in a small contiguous
    buffer until the per-tick flush (positions [starts, lengths)). With
    ``quantized``, the pools are int8 and their per-position scales factor
    out of the dots; the tail stays float until the flush. ``q_groups``
    (speculative verify): the q block packs Q query tokens; per-query
    causal limits apply to the TAIL only — every page column precedes
    ``starts``, which every query's limit already covers."""
    if quantized:
        ks_ref, vs_ref, tk_ref, tv_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        tk_ref, tv_ref, o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]
    start = starts_ref[b]
    page_limit = jnp.minimum(start, length)
    base = p * page_size

    @pl.when((p < n_pages) & (base < page_limit))
    def _pages():
        _accumulate_block(
            q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
            scale=scale, base=base, width=page_size, limit=page_limit,
            ks_ref=ks_ref, vs_ref=vs_ref,
        )

    @pl.when((p == n_pages) & (length > start))
    def _tail():
        _accumulate_block(
            q_ref, tk_ref, tv_ref, m_scr, l_scr, acc_scr,
            scale=scale, base=start, width=tk_ref.shape[2], limit=length,
            q_groups=q_groups,
        )

    @pl.when(p == n_pages)
    def _finalize():
        _finalize_out(o_ref, m_scr, l_scr, acc_scr)


def paged_attention(
    q: jax.Array,  # (B, H, D); (B, Q, H, D) = multi-query speculative verify
    k_pages: jax.Array,  # (P, K, ps, D)
    v_pages: jax.Array,
    page_table: jax.Array,  # (B, maxp) int32
    lengths: jax.Array,  # (B,) int32
    *,
    tail_k: jax.Array | None = None,  # (B, K, T, D) — unflushed chunk KV
    tail_v: jax.Array | None = None,
    starts: jax.Array | None = None,  # (B,) tokens resident in pages
    k_scale: jax.Array | None = None,  # (P, K, 1, ps) — int8 pools
    v_scale: jax.Array | None = None,
    interpret: bool | None = None,
    mesh=None,
    rules=None,
) -> jax.Array:
    """Pallas paged GQA decode attention (see module docstring).

    With ``tail_k/tail_v/starts`` (the deferred-flush decode path), the
    grid gains one final step that accumulates the hot tail block —
    positions [starts, lengths) held in a small contiguous buffer — so
    per-token page writes never happen inside the decode scan.

    4-D ``q`` (requires the tail path) is the speculative K+1-token verify:
    Q queries per slot share every page fetch — the whole point of
    speculation on a bandwidth-bound decoder — and get per-query causal
    limits on the tail block only (query qi sees tail positions
    < lengths + qi; page columns all precede ``starts``).

    With a ``mesh``, the kernel is shard_mapped over the TENSOR axis:
    pools, tails and q/output split on kv-heads (the rule table's
    ``act_kv_heads``), page table / lengths / starts replicated — heads
    are independent in attention, so no collectives are induced. The
    batch axes stay unsharded here (a paged pool is one shared resource;
    multi-host paged serving replicates the batch like the pod protocols
    do)."""
    multi_q = q.ndim == 4
    if mesh is not None:
        from ditl_tpu.ops.attention import _mesh_axes_size
        from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

        rules = rules if rules is not None else DEFAULT_RULES
        tp = _mesh_axes_size(mesh, rules.get("act_kv_heads"))
        tp_q = _mesh_axes_size(mesh, rules.get("act_heads"))
        dp = _mesh_axes_size(mesh, rules.get("batch"))
        kv_heads = k_pages.shape[1]
        shardable = (
            (tp > 1 or dp > 1)
            # q and kv specs must resolve to the SAME head split — a rule
            # table splitting them differently would silently mispair q
            # heads with kv heads inside the map.
            and rules.get("act_heads") == rules.get("act_kv_heads")
            and tp == tp_q
            and kv_heads % tp == 0
            and q.shape[-2] % tp == 0
            and q.shape[0] % dp == 0
        )
        if shardable:
            q_axes = (
                ("batch", None, "act_heads", None) if multi_q
                else ("batch", "act_heads", None)
            )
            pool_spec = logical_to_spec((None, "act_kv_heads", None, None), rules)
            tail_spec = logical_to_spec(("batch", "act_kv_heads", None, None), rules)
            row_spec = logical_to_spec(("batch",), rules)
            in_specs = [
                logical_to_spec(q_axes, rules),  # q
                pool_spec, pool_spec,  # pools (P,K,ps,D): replicated over dp
                logical_to_spec(("batch", None), rules),  # table
                row_spec,  # lengths
            ]
            args = [q, k_pages, v_pages, page_table, lengths]
            has_tail = tail_k is not None
            has_scale = k_scale is not None
            if has_tail:
                in_specs += [tail_spec, tail_spec, row_spec]
                args += [tail_k, tail_v, starts]
            if has_scale:
                scale_spec = logical_to_spec(
                    (None, "act_kv_heads", None, None), rules
                )
                in_specs += [scale_spec, scale_spec]
                args += [k_scale, v_scale]

            def local(q_, kp_, vp_, tab_, lens_, *rest):
                tk_ = tv_ = st_ = ks_ = vs_ = None
                if has_tail:
                    tk_, tv_, st_, *rest = rest
                if has_scale:
                    ks_, vs_ = rest
                return paged_attention(
                    q_, kp_, vp_, tab_, lens_,
                    tail_k=tk_, tail_v=tv_, starts=st_,
                    k_scale=ks_, v_scale=vs_, interpret=interpret,
                )

            return shard_map(
                local,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=logical_to_spec(q_axes, rules),
                check_vma=False,
            )(*args)
        # Mesh doesn't divide heads/batch (or no such axes): single-program
        # path under GSPMD — fall through unsharded. Warn: under GSPMD the
        # unsharded pallas_call forces the whole page pool to be
        # replicated/resharded every decode step — a large silent perf/HBM
        # cliff on exactly the configs sharding exists for (ADVICE r2).
        if tp > 1 or dp > 1:
            import warnings

            warnings.warn(
                f"paged_attention: mesh given but not shardable (kv_heads="
                f"{kv_heads} vs tp={tp}/{tp_q}, batch={q.shape[0]} vs "
                f"dp={dp}); falling back to the unsharded kernel under "
                f"GSPMD — expect per-step pool resharding",
                stacklevel=2,
            )
    if multi_q:
        b, nq, h, d = q.shape
    else:
        b, h, d = q.shape
        nq = 1
    n_pool, kv_heads, ps, _ = k_pages.shape
    maxp = page_table.shape[1]
    groups = h // kv_heads
    if h % kv_heads:
        raise ValueError(f"q heads {h} not divisible by kv heads {kv_heads}")
    if multi_q and tail_k is None:
        raise ValueError(
            "multi-query paged_attention requires the tail path (the verify "
            "chunk's own KV lives in the tail buffer)"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # (B, K, Q*G, D): one grid step's q block is ALL kv heads of one slot —
    # rows ordered query-major within a kv head (row = qi * G + g).
    qg = (
        q.reshape(b, nq, kv_heads, groups, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, kv_heads, nq * groups, d)
    )
    qg_rows = nq * groups
    g_rows = max(kv_heads * qg_rows, 8)  # scratch sublane floor
    has_tail = tail_k is not None
    scratch = [
        pltpu.VMEM((g_rows, NUM_LANES), jnp.float32),  # m
        pltpu.VMEM((g_rows, NUM_LANES), jnp.float32),  # l
        pltpu.VMEM((g_rows, d), jnp.float32),  # acc
    ]
    out_shape = jax.ShapeDtypeStruct((b, kv_heads, qg_rows, d), q.dtype)
    compiler_params = tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary")
    )

    def out_4d(o):
        o = o.reshape(b, kv_heads, nq, groups, d).transpose(0, 2, 1, 3, 4)
        o = o.reshape(b, nq, h, d)
        return o if multi_q else o[:, 0]

    if has_tail:
        # Page fetches clamp to pages holding FLUSHED tokens (< starts) and
        # redirect everything else to sentinel page 0 (Mosaic's revisit
        # optimization skips the duplicate fetch); the final grid step
        # consumes the tail block instead of a page.
        def page_map(ib, ip, tab, lens, st):
            pi = jnp.minimum(ip, maxp - 1)
            live = (ip < maxp) & (pi * ps < jnp.minimum(st[ib], lens[ib]))
            return jnp.where(live, tab[ib, pi], 0), 0, 0, 0

        def slot_map(ib, ip, tab, lens, st):
            return (ib, 0, 0, 0)

        quantized = k_scale is not None
        in_specs = [
            pl.BlockSpec((1, kv_heads, qg_rows, d), slot_map),
            pl.BlockSpec((1, kv_heads, ps, d), page_map),
            pl.BlockSpec((1, kv_heads, ps, d), page_map),
        ]
        args = [page_table, lengths, starts, qg, k_pages, v_pages]
        if quantized:
            in_specs += [
                pl.BlockSpec((1, kv_heads, 1, ps), page_map),
                pl.BlockSpec((1, kv_heads, 1, ps), page_map),
            ]
            args += [k_scale, v_scale]
        in_specs += [
            pl.BlockSpec((1, kv_heads, tail_k.shape[2], d), slot_map),
            pl.BlockSpec((1, kv_heads, tail_k.shape[2], d), slot_map),
        ]
        args += [tail_k, tail_v]
        out = pl.pallas_call(
            functools.partial(
                _paged_tail_kernel, scale=d**-0.5, page_size=ps,
                n_pages=maxp, quantized=quantized,
                q_groups=groups if nq > 1 else None,
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=3,
                grid=(b, maxp + 1),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((1, kv_heads, qg_rows, d), slot_map),
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            compiler_params=compiler_params,
            interpret=interpret,
        )(*args)
        return out_4d(out)

    if k_scale is not None:
        raise ValueError(
            "paged_attention with k_scale/v_scale requires the tail path "
            "(tail_k/tail_v/starts) — the no-tail kernel would silently "
            "attend over raw int8 values"
        )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=d**-0.5, page_size=ps, n_pages=maxp
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, maxp),
            in_specs=[
                pl.BlockSpec(
                    (1, kv_heads, groups, d), lambda ib, ip, tab, lens: (ib, 0, 0, 0)
                ),
                # Pages at or past the slot's length are redirected to the
                # sentinel page 0 (their compute is pl.when-skipped anyway):
                # consecutive identical block indices make Mosaic skip the
                # re-fetch, so a slot whose admission reserved max_new pages
                # only pays DMA for the pages actually written so far.
                pl.BlockSpec(
                    (1, kv_heads, ps, d),
                    lambda ib, ip, tab, lens: (
                        jnp.where(ip * ps < lens[ib], tab[ib, ip], 0), 0, 0, 0
                    ),
                ),
                pl.BlockSpec(
                    (1, kv_heads, ps, d),
                    lambda ib, ip, tab, lens: (
                        jnp.where(ip * ps < lens[ib], tab[ib, ip], 0), 0, 0, 0
                    ),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, kv_heads, groups, d), lambda ib, ip, tab, lens: (ib, 0, 0, 0)
            ),
            scratch_shapes=scratch,
        ),
        out_shape=out_shape,
        compiler_params=compiler_params,
        interpret=interpret,
    )(page_table, lengths, qg, k_pages, v_pages)
    return out.reshape(b, h, d)
