"""Ulysses (all-to-all) sequence parallelism: the second context-parallel path.

The reference has no long-context machinery at all (SURVEY.md §5
'long-context' — sequence length is never even a variable there). This module
complements ring attention (ops/ring_attention.py) with the DeepSpeed-Ulysses
scheme (Jacobs et al.; see PAPERS.md): instead of rotating KV chunks around a
ring, two ``all_to_all`` collectives re-shard the activations from
sequence-sharded to head-sharded and back:

    (B, S/n, H, D) --all_to_all--> (B, S, H/n, D)   # full sequence, 1/n heads
        -> exact local attention (Pallas flash kernel when shapes allow)
    (B, S, H/n, D) --all_to_all--> (B, S/n, H, D)

Trade-off vs ring: Ulysses moves O(S·H·D/n) bytes in two dense all-to-alls
(ICI-friendly, overlappable, and the attention itself is a single unsplit
kernel — better MXU utilization), while ring moves the KV pair n-1 times but
never needs the head dim divisible by n. Hence the dispatch rule here: heads
and KV heads must both divide by the sequence-axis size or we fall back to
ring attention, which handles every GQA layout.

Semantics match ``ops.attention._xla_attention`` exactly (GQA, causal,
segment-id packing masks) — tested against it on the 8-device CPU mesh,
including gradients through both all-to-alls.
"""

from __future__ import annotations

import jax
from ditl_tpu.utils.compat import shard_map

__all__ = ["ulysses_attention"]


def _local_attention(q, k, v, *, causal, segment_ids):
    """Full-sequence attention on this device's head slice: Pallas flash
    kernel when the shapes tile, XLA einsum otherwise (tiny tests, odd lens)."""
    from ditl_tpu.ops import flash_attention as fa
    from ditl_tpu.ops.attention import _xla_attention

    if fa.supports(q.shape[1], k.shape[1], q.shape[3]):
        return fa.flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)


def ulysses_attention(
    q: jax.Array,  # (B, S, H, D) global
    k: jax.Array,  # (B, S, K, D)
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: jax.Array | None = None,
    mesh: jax.sharding.Mesh | None = None,
    rules=None,
) -> jax.Array:
    """Exact attention with the sequence dim sharded over ``rules['seq']``,
    implemented with all-to-all head/sequence transposition.

    Falls back to (a) plain XLA attention when there is no mesh or the
    sequence axis has size 1, (b) ring attention when the per-device head
    counts don't divide by the sequence-axis size (GQA with few KV heads).
    """
    from ditl_tpu.ops.attention import _mesh_axes_size, _xla_attention
    from ditl_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

    rules = rules if rules is not None else DEFAULT_RULES
    axis_name = rules.get("seq")
    if (
        mesh is None
        or not isinstance(axis_name, str)
        or axis_name not in mesh.shape
        or mesh.shape[axis_name] == 1
    ):
        return _xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)

    sp = mesh.shape[axis_name]
    tp = _mesh_axes_size(mesh, rules.get("act_heads"))
    h_local, kv_local = q.shape[2] // tp, k.shape[2] // tp
    if (
        q.shape[2] % tp
        or k.shape[2] % tp
        or not kv_local
        or h_local % sp
        or kv_local % sp
        or q.shape[1] % sp
        or q.shape[0] % _mesh_axes_size(mesh, rules.get("batch"))
    ):
        # Head slice per device would be fractional (or batch/seq don't
        # divide): ring attention handles every layout, at more KV traffic.
        from ditl_tpu.ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, mesh=mesh, rules=rules
        )

    qkv_spec = logical_to_spec(("batch", "seq", "act_heads", None), rules)
    args = [q, k, v]
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    if segment_ids is not None:
        args.append(segment_ids)
        in_specs.append(logical_to_spec(("batch", "seq"), rules))

    def local(q_, k_, v_, seg_=None):
        # Sequence-sharded -> head-sharded: each device receives every other
        # device's sequence chunk for its 1/sp slice of the heads. Chunks
        # concatenate in ring order == contiguous global order, so global
        # positions are simply 0..S-1 and the causal mask is the plain tril.
        to_heads = lambda x: jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
        q_g, k_g, v_g = to_heads(q_), to_heads(k_), to_heads(v_)
        seg_g = (
            jax.lax.all_gather(seg_, axis_name, axis=1, tiled=True)
            if seg_ is not None
            else None
        )
        out = _local_attention(q_g, k_g, v_g, causal=causal, segment_ids=seg_g)
        # Head-sharded -> sequence-sharded: the inverse transposition.
        return jax.lax.all_to_all(
            out, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )(*args)
